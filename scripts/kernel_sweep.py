#!/usr/bin/env python
"""Q40 matmul kernel bandwidth sweep — run on real TPU silicon.

The round-1 kernel measured ~190 GB/s while XLA's in-model dense matvec
reaches ~460 GB/s on the same chip (ROADMAP.md); this script separates the
hypotheses so the fix is driven by data, not guesses:

  A. xla-dense-bf16     : XLA jit matvec — the bandwidth target
  B. pallas-dense-bf16  : dense bf16 pallas matvec — isolates Pallas
                          pipeline overhead from dequant cost
  C. pallas-int8-raw    : int8 weights, no scales, cast+matmul — isolates
                          the int8->bf16 conversion cost
  D. qmm-current        : the shipping kernel (ops/quant_matmul.qmatmul_2d)
                          across (block_k, block_n) and grid-order variants
  E. qmm-vreg           : VPU-reduction variant (elementwise multiply +
                          sublane-sum instead of an MXU [1,k]x[k,n] dot —
                          matvecs underuse the MXU's 128x128 tile)
  F. qmm-flat           : 1D grid over n only (whole k per step) — fewer
                          grid steps, bigger DMAs

Usage:  python scripts/kernel_sweep.py            # full sweep
        SWEEP_QUICK=1 python scripts/kernel_sweep.py
Prints one line per variant: name, ms/call, effective GB/s (weight+scale
bytes moved per call / time).
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dllama_tpu.parallel.mesh import enable_compilation_cache, reassert_platform

reassert_platform()
enable_compilation_cache()

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Q_BLOCK = 32


def sync(x):
    return np.asarray(jax.device_get(jnp.ravel(x)[0]))


def timeit(f, n_iter=100):
    o = f()
    sync(o)
    t0 = time.perf_counter()
    for _ in range(n_iter):
        o = f()
    sync(o)
    return (time.perf_counter() - t0) / n_iter * 1000


def report(name: str, ms: float, nbytes: int):
    gbs = nbytes / (ms / 1000) / 1e9
    print(f"{name:42s} {ms:8.3f} ms   {gbs:7.1f} GB/s", flush=True)
    return gbs


def main():
    quick = bool(os.environ.get("SWEEP_QUICK"))
    k, n = (4096, 4096) if quick else (4096, 14336)
    m = 1
    rng = np.random.default_rng(0)
    print(f"devices: {jax.devices()}  shapes: m={m} k={k} n={n}", flush=True)

    wq = rng.integers(-8, 8, size=(k, n), dtype=np.int8)
    wd = (rng.standard_normal((k // Q_BLOCK, n)).astype(np.float32) * 0.01)
    wq_j = jnp.asarray(wq)
    wd_j = jnp.asarray(wd)
    w_bf16 = jnp.asarray(
        (wq.astype(np.float32) * np.repeat(wd, Q_BLOCK, axis=0)), jnp.bfloat16
    )
    x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32), jnp.bfloat16)

    q_bytes = k * n + (k // Q_BLOCK) * n * 4  # int8 + f32 scales
    dense_bytes = k * n * 2

    # A. XLA dense bf16 matvec (the target)
    f_xla = jax.jit(lambda xx, ww: xx @ ww)
    report("A xla-dense-bf16", timeit(lambda: f_xla(x, w_bf16)), dense_bytes)

    # B. dense bf16 pallas matvec, several block_n
    def dense_kernel(x_ref, w_ref, o_ref, acc_ref, *, n_k):
        pk = pl.program_id(1)
        p = jax.lax.dot_general(
            x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        @pl.when(pk == 0)
        def _():
            acc_ref[:] = p

        @pl.when(pk > 0)
        def _():
            acc_ref[:] += p

        @pl.when(pk == n_k - 1)
        def _():
            o_ref[:] = acc_ref[:]

    def pallas_dense(bn, bk, dims=None):
        n_k = k // bk
        grid = (n // bn, n_k)
        kw = {}
        if dims is not None:
            kw["compiler_params"] = pltpu.CompilerParams(
                dimension_semantics=dims
            )
        return pl.pallas_call(
            functools.partial(dense_kernel, n_k=n_k),
            out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
            grid=grid,
            in_specs=[
                pl.BlockSpec((m, bk), lambda i, j: (0, j)),
                pl.BlockSpec((bk, bn), lambda i, j: (j, i)),
            ],
            out_specs=pl.BlockSpec((m, bn), lambda i, j: (0, i)),
            scratch_shapes=[pltpu.VMEM((m, bn), jnp.float32)],
        )(x, w_bf16)

    for bn, bk in [(512, 2048), (512, 4096), (1024, 4096), (2048, 4096)]:
        if n % bn or k % bk:
            continue
        try:
            f = jax.jit(functools.partial(pallas_dense, bn, bk))
            report(f"B pallas-dense-bf16 bn={bn} bk={bk}", timeit(f), dense_bytes)
        except Exception as e:
            print(f"B pallas-dense-bf16 bn={bn} bk={bk}: {type(e).__name__}: {str(e)[:120]}")
    try:
        f = jax.jit(
            functools.partial(pallas_dense, 512, 4096, ("parallel", "arbitrary"))
        )
        report("B pallas-dense-bf16 512/4096 par-hint", timeit(f), dense_bytes)
    except Exception as e:  # compiler_params API drift
        print(f"  (par-hint variant unavailable: {type(e).__name__})")

    # C. int8 raw (no scales): conversion cost probe
    def int8_kernel(x_ref, q_ref, o_ref, acc_ref, *, n_k):
        pk = pl.program_id(1)
        w = q_ref[:].astype(jnp.bfloat16)
        p = jax.lax.dot_general(
            x_ref[:], w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        @pl.when(pk == 0)
        def _():
            acc_ref[:] = p

        @pl.when(pk > 0)
        def _():
            acc_ref[:] += p

        @pl.when(pk == n_k - 1)
        def _():
            o_ref[:] = acc_ref[:]

    def pallas_int8(bn, bk):
        n_k = k // bk
        return pl.pallas_call(
            functools.partial(int8_kernel, n_k=n_k),
            out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
            grid=(n // bn, n_k),
            in_specs=[
                pl.BlockSpec((m, bk), lambda i, j: (0, j)),
                pl.BlockSpec((bk, bn), lambda i, j: (j, i)),
            ],
            out_specs=pl.BlockSpec((m, bn), lambda i, j: (0, i)),
            scratch_shapes=[pltpu.VMEM((m, bn), jnp.float32)],
        )(x, wq_j)

    for bn, bk in [(512, 4096), (1024, 4096), (2048, 4096)]:
        if n % bn or k % bk:
            continue
        try:
            f = jax.jit(functools.partial(pallas_int8, bn, bk))
            report(f"C pallas-int8-raw bn={bn} bk={bk}", timeit(f), k * n)
        except Exception as e:
            print(f"C pallas-int8-raw bn={bn} bk={bk}: {type(e).__name__}: {str(e)[:120]}")

    # D. current shipping kernel across block configs
    from dllama_tpu.ops.quant_matmul import qmatmul_2d

    for bn, bk in [(512, 2048), (512, 4096), (1024, 2048), (1024, 4096),
                   (2048, 2048), (2048, 4096), (256, 4096)]:
        if n % bn or k % bk:
            continue
        try:
            f = jax.jit(
                lambda bn=bn, bk=bk: qmatmul_2d(x, wq_j, wd_j, block_n=bn, block_k=bk)
            )
            report(f"D qmm-current bn={bn} bk={bk}", timeit(f), q_bytes)
        except Exception as e:
            print(f"D qmm-current bn={bn} bk={bk}: {type(e).__name__}: {str(e)[:120]}")

    # E. VPU-reduction variant: no MXU — broadcast-multiply + k-axis sum.
    #    x arrives pre-scaled per k-row is impossible (scales vary per n),
    #    so dequant stays, but the reduction avoids the [1,k]x[k,n] MXU dot.
    def vreg_kernel(x_ref, q_ref, d_ref, o_ref, acc_ref, *, n_k):
        pk = pl.program_id(1)
        q = q_ref[:]  # [bk, bn] int8
        d = d_ref[:]  # [bk//32, bn] f32
        bk, bn = q.shape
        xv = x_ref[:]  # [1, bk] bf16
        # w[i, o] * x[i] summed over i: fold x into the dequant multiply
        xq = (q.astype(jnp.float32) * xv.reshape(bk, 1).astype(jnp.float32))
        part = jnp.sum(
            xq.reshape(bk // Q_BLOCK, Q_BLOCK, bn), axis=1
        )  # [bk//32, bn]
        p = jnp.sum(part * d, axis=0, keepdims=True)  # [1, bn]

        @pl.when(pk == 0)
        def _():
            acc_ref[:] = p

        @pl.when(pk > 0)
        def _():
            acc_ref[:] += p

        @pl.when(pk == n_k - 1)
        def _():
            o_ref[:] = acc_ref[:]

    def pallas_vreg(bn, bk):
        n_k = k // bk
        return pl.pallas_call(
            functools.partial(vreg_kernel, n_k=n_k),
            out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
            grid=(n // bn, n_k),
            in_specs=[
                pl.BlockSpec((m, bk), lambda i, j: (0, j)),
                pl.BlockSpec((bk, bn), lambda i, j: (j, i)),
                pl.BlockSpec((bk // Q_BLOCK, bn), lambda i, j: (j, i)),
            ],
            out_specs=pl.BlockSpec((m, bn), lambda i, j: (0, i)),
            scratch_shapes=[pltpu.VMEM((m, bn), jnp.float32)],
        )(x, wq_j, wd_j)

    for bn, bk in [(512, 2048), (1024, 2048), (2048, 1024)]:
        if n % bn or k % bk:
            continue
        try:
            f = jax.jit(functools.partial(pallas_vreg, bn, bk))
            report(f"E qmm-vreg bn={bn} bk={bk}", timeit(f), q_bytes)
        except Exception as e:
            print(f"E qmm-vreg bn={bn} bk={bk}: {type(e).__name__}: {str(e)[:120]}")

    # F. 1D grid: whole k per step (one tall DMA per n block)
    def flat_kernel(x_ref, q_ref, d_ref, o_ref):
        q = q_ref[:]
        d = d_ref[:]
        bk, bn = q.shape
        w = (
            (q.astype(jnp.float32).reshape(bk // Q_BLOCK, Q_BLOCK, bn)
             * d[:, None, :])
            .reshape(bk, bn)
            .astype(jnp.bfloat16)
        )
        o_ref[:] = jax.lax.dot_general(
            x_ref[:], w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def pallas_flat(bn):
        return pl.pallas_call(
            flat_kernel,
            out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
            grid=(n // bn,),
            in_specs=[
                pl.BlockSpec((m, k), lambda i: (0, 0)),
                pl.BlockSpec((k, bn), lambda i: (0, i)),
                pl.BlockSpec((k // Q_BLOCK, bn), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((m, bn), lambda i: (0, i)),
        )(x, wq_j, wd_j)

    for bn in [256, 512, 1024]:
        if n % bn:
            continue
        try:
            f = jax.jit(functools.partial(pallas_flat, bn))
            report(f"F qmm-flat bn={bn}", timeit(f), q_bytes)
        except Exception as e:
            print(f"F qmm-flat bn={bn}: {type(e).__name__}: {str(e)[:120]}")

    # G. kernel-launch overhead probe: decode runs 7 quantized matmuls per
    # layer; if N small calls cost meaningfully more than one call over
    # the same bytes, qkv/w1w3 fusion (ROADMAP #3) is worth the layout
    # complexity.
    n_split = 4
    n_small = n // n_split
    if n % n_split == 0 and n_small % 128 == 0:
        f_one = jax.jit(lambda: qmatmul_2d(x, wq_j, wd_j, block_n=512))
        qs = [jnp.asarray(wq[:, i * n_small:(i + 1) * n_small]) for i in range(n_split)]
        ds = [jnp.asarray(wd[:, i * n_small:(i + 1) * n_small]) for i in range(n_split)]

        def f_many():
            outs = [
                qmatmul_2d(x, qs[i], ds[i], block_n=min(512, n_small))
                for i in range(n_split)
            ]
            return outs[-1]

        f_many_j = jax.jit(f_many)
        t_one = timeit(f_one)
        t_many = timeit(f_many_j)
        report("G one fused call", t_one, q_bytes)
        report(f"G {n_split} split calls (same bytes)", t_many, q_bytes)
        print(f"  -> per-call overhead ~{(t_many - t_one) / (n_split - 1):.3f} ms")

    # correctness spot check for the variants that could ship
    from dllama_tpu.ops.quant_matmul import QuantWeight, qmatmul_ref

    ref = np.asarray(qmatmul_ref(x.astype(jnp.float32), QuantWeight(wq_j, wd_j)))
    cur = np.asarray(jax.jit(lambda: qmatmul_2d(x, wq_j, wd_j))())
    print("current kernel max err vs ref:", np.abs(cur - ref).max())


if __name__ == "__main__":
    main()
