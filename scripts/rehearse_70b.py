"""Llama-3.3-70B fit-and-plan rehearsal on the 8-virtual-device CPU mesh.

VERDICT r4 #2: nothing in the repo had ever run at 70B shapes. This script
does, end to end, with no silicon:

  1. streams a REAL-SIZE synthetic Q40 `.m` to disk (80 layers, 8192 dim,
     28672 ffn, 64/8 heads, 128k vocab — ~43 GB, the exact tensor plan a
     converted Llama-3.3-70B-Instruct-Q40 has; reference runs this model
     per /root/reference/README.md:22);
  2. STREAM-loads it onto a pp4 x tp2 mesh through models/loader's
     shard-by-shard path (the host high-water mark is the headline: the
     pre-r5 loader stacked whole [80, ...] tensors on host — ~37 GB for
     w13 alone);
  3. prints the per-device HBM plan (weights + int8 KV at the file's
     seq_len, plus the analytic 131k-context budget vs v5e 16 GB);
  4. runs ONE pp4xtp2 prefill chunk (T=8) and ONE decode step at full
     70B shapes and checks the logits are finite.

Run:  python scripts/rehearse_70b.py [--layers 80] [--path .scratch/synth70b.m]
Results land in docs/70b_plan.md (hand-recorded).
"""

import argparse
import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    flags += " --xla_force_host_platform_device_count=8"
# NOTE: 8 virtual devices time-slice ONE core here, so a shard can take
# minutes to reach a collective, and XLA CPU's rendezvous hard-terminates
# at 40 s. No flag governs that rendezvous
# (--xla_cpu_collective_timeout_seconds parses but both 80-layer runs
# still aborted at the first DECODE all-reduce with "of 40 seconds
# exceeded") — on a 1-core host the decode step is unreachable; prefill
# completes (docs/70b_plan.md).
os.environ["XLA_FLAGS"] = flags.strip()

import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from dllama_tpu.formats.model_file import ModelReader
from dllama_tpu.models import init_kv_cache, load_params
from dllama_tpu.models.synthetic import write_synth_model
from dllama_tpu.parallel import cache_specs, make_mesh, shard_params_put
from dllama_tpu.parallel.pipeline import forward_pp
from dllama_tpu.utils.telemetry import memory_report

V5E_HBM = 16e9


def hwm_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def per_device_bytes(tree) -> dict[str, int]:
    out: dict[str, int] = {}
    for leaf in jax.tree.leaves(tree):
        for sh in leaf.addressable_shards:
            key = str(sh.device)
            out[key] = out.get(key, 0) + sh.data.nbytes
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=80)
    ap.add_argument("--path", default=".scratch/synth70b.m")
    ap.add_argument("--pp", type=int, default=4)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=4096)
    args = ap.parse_args()
    rec: dict = {"layers": args.layers, "pp": args.pp, "tp": args.tp}

    if not os.path.exists(args.path):
        t0 = time.perf_counter()
        write_synth_model(
            args.path, "llama-70b", n_layers=args.layers,
            max_seq_len=args.seq_len,
        )
        rec["write_s"] = round(time.perf_counter() - t0, 1)
    rec["file_gb"] = round(os.path.getsize(args.path) / 1e9, 2)
    print(f"file: {rec['file_gb']} GB ({args.layers} layers)", flush=True)

    r = ModelReader(args.path)
    h = r.header
    if h.n_layers != args.layers or h.seq_len != args.seq_len:
        raise SystemExit(
            f"existing {args.path} has {h.n_layers} layers / seq "
            f"{h.seq_len}, but --layers {args.layers} --seq-len "
            f"{args.seq_len} was requested; delete the file or match the args"
        )
    mesh = make_mesh(tp=args.tp, pp=args.pp)
    base_hwm = hwm_gb()
    t0 = time.perf_counter()
    params = load_params(
        r, weight_format="q40", dtype=jnp.bfloat16,
        put=shard_params_put(mesh, h), fuse=args.tp,
    )
    jax.block_until_ready(jax.tree.leaves(params))
    rec["load_s"] = round(time.perf_counter() - t0, 1)
    rec["host_hwm_gb"] = round(hwm_gb(), 2)
    rec["host_hwm_baseline_gb"] = round(base_hwm, 2)

    cache = init_kv_cache(h, 1, dtype=jnp.int8)
    cs = cache_specs(h, pp=args.pp > 1)
    cache = {
        k: jax.device_put(v, NamedSharding(mesh, cs[k])) for k, v in cache.items()
    }
    dev_w = per_device_bytes(params)
    dev_c = per_device_bytes(cache)
    rec["per_device_weights_gb"] = {
        k: round(v / 1e9, 3) for k, v in sorted(dev_w.items())
    }
    rec["per_device_cache_gb_seq4096_int8"] = round(
        max(dev_c.values()) / 1e9, 3
    )
    rep = memory_report(params, cache, n_devices=8, tp=args.tp)
    rec["params_gb_total"] = round(rep.params_bytes / 1e9, 2)

    # analytic long-context budget: int8 KV at the true 131072 context
    kv131k = 2 * h.n_layers * h.n_kv_heads * 131072 * (h.head_dim + 4)
    rec["kv131k_int8_gb_per_chip"] = round(kv131k / 8 / 1e9, 2)
    worst = max(dev_w.values()) / 1e9
    rec["worst_chip_gb_at_131k"] = round(
        worst + kv131k / 8 / 1e9 + 0.5, 2  # +0.5 activations/workspace
    )
    rec["fits_v5e_16gb"] = rec["worst_chip_gb_at_131k"] < V5E_HBM / 1e9
    print(json.dumps(rec, indent=1), flush=True)

    # one pp4xtp2 prefill chunk + one decode step at full 70B shapes
    # (cache donated: the engine's steps donate too, and the rehearsal
    # host has no headroom for two live caches + logits)
    step = jax.jit(
        lambda p, t, c, pos: forward_pp(
            p, h, t, pos, c, mesh, logits_mode="last", sync_quant=False
        ),
        donate_argnums=(2,),
    )
    tok8 = jnp.ones((1, 8), jnp.int32)
    t0 = time.perf_counter()
    logits, cache = step(params, tok8, cache, jnp.int32(0))
    ok = bool(np.isfinite(np.asarray(logits)).all())
    rec["prefill8_s"] = round(time.perf_counter() - t0, 1)
    rec["prefill_finite"] = ok
    print(f"prefill8: {rec['prefill8_s']}s finite={ok}", flush=True)
    tok1 = jnp.ones((1, 1), jnp.int32)
    t0 = time.perf_counter()
    logits, cache = step(params, tok1, cache, jnp.int32(8))
    ok = bool(np.isfinite(np.asarray(logits)).all())
    rec["decode_s"] = round(time.perf_counter() - t0, 1)
    rec["decode_finite"] = ok
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
