#!/usr/bin/env python
"""One-shot TPU validation sweep: run after hardware becomes reachable.

Covers everything that cannot be validated on the CPU mesh: Pallas kernel
numerics on real silicon, q40-vs-dense token parity, the ragged MoE kernel
vs dense timing, and decode throughput at 1B/8B. Prints a summary table.

    python scripts/tpu_validation.py            # full sweep
    BENCH_QUICK=1 python scripts/tpu_validation.py   # smaller configs
    TPU_VALIDATION_ONLY=engine,bench python scripts/tpu_validation.py

Sections are INDEPENDENT (qmm, flash, moe, engine, bench) so a flaky
tunnel can be worked around by running each in its own subprocess with
its own timeout — a hang in one section (the tunnel wedges rather than
erroring) no longer forfeits the rest. scripts/silicon_watch.sh does
exactly that.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dllama_tpu.parallel.mesh import enable_compilation_cache, reassert_platform

reassert_platform()
enable_compilation_cache()

import jax
import jax.numpy as jnp

RESULTS: list[tuple[str, str]] = []
QUICK = bool(os.environ.get("BENCH_QUICK"))


def record(name: str, value: str):
    RESULTS.append((name, value))
    print(f"  {name}: {value}", flush=True)


def sync(x):
    return np.asarray(jax.device_get(jnp.ravel(x)[0]))


def timeit(f, n_iter=50):
    o = f()
    sync(o)
    t0 = time.perf_counter()
    for _ in range(n_iter):
        o = f()
    sync(o)
    return (time.perf_counter() - t0) / n_iter * 1000


def sec_qmm() -> None:
    """Q40 pallas matmul numerics on silicon."""
    from dllama_tpu.formats.quants import q40_to_planar, quantize_q40
    from dllama_tpu.ops.quant_matmul import from_planar, qmatmul_2d, qmatmul_ref

    rng = np.random.default_rng(0)
    n, k = (1024, 4096) if QUICK else (4096, 8192)
    w = rng.standard_normal((n, k)).astype(np.float32) * 0.05
    qv, dv = q40_to_planar(quantize_q40(w), n * k)
    qw = from_planar(qv.reshape(n, k), dv.reshape(n, k // 32))
    x = jnp.asarray(rng.standard_normal((1, k)).astype(np.float32))
    out = qmatmul_2d(x, qw.q, qw.d)
    ref = qmatmul_ref(x.astype(jnp.bfloat16).astype(jnp.float32), qw)
    rel = float(jnp.abs(out - ref).max() / jnp.abs(ref).max())
    record("q40 kernel rel err", f"{rel:.2e} {'OK' if rel < 5e-3 else 'FAIL'}")


def sec_flash() -> None:
    """Flash attention / decode / decode-stats numerics on silicon."""
    from dllama_tpu.ops.flash_attention import attention_ref, flash_attention

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 128, 8, 64)).astype(np.float32)).astype(jnp.bfloat16)
    # head-major cache layout [B, KH, S, hd]
    kc = jnp.asarray(rng.standard_normal((1, 4, 1024, 64)).astype(np.float32)).astype(jnp.bfloat16)
    vc = jnp.asarray(rng.standard_normal((1, 4, 1024, 64)).astype(np.float32)).astype(jnp.bfloat16)
    fo = flash_attention(q, kc, vc, jnp.int32(512))
    fr = attention_ref(q, kc, vc, jnp.int32(512))
    rel = float(
        jnp.abs(fo.astype(jnp.float32) - fr.astype(jnp.float32)).max()
    )
    record("flash attn abs err (bf16)", f"{rel:.2e} {'OK' if rel < 2e-2 else 'FAIL'}")

    # flash decode (T=1) numerics
    from dllama_tpu.ops.flash_attention import flash_decode

    S = 16384 if QUICK else 32768
    qd = jnp.asarray(rng.standard_normal((1, 1, 8, 64)).astype(np.float32)).astype(jnp.bfloat16)
    kd = jnp.asarray(rng.standard_normal((1, 4, S, 64)).astype(np.float32)).astype(jnp.bfloat16)
    vd = jnp.asarray(rng.standard_normal((1, 4, S, 64)).astype(np.float32)).astype(jnp.bfloat16)
    for p in (100, S - 1):
        fo = flash_decode(qd, kd, vd, jnp.int32(p))
        fr = attention_ref(qd, kd, vd, jnp.int32(p))
        err = float(jnp.abs(fo.astype(jnp.float32) - fr.astype(jnp.float32)).max())
        record(f"flash decode abs err pos={p}", f"{err:.2e} {'OK' if err < 2e-2 else 'FAIL'}")

    # flash decode STATS variant (the sp-decode local step) on silicon:
    # Mosaic lowering of the stats out-specs + the shard-offset clamp only
    # ever runs here before an sp>1 deployment would hit it
    from dllama_tpu.ops.flash_attention import flash_decode_stats
    from dllama_tpu.ops.jnp_ops import attention_stats as jnp_stats

    Ss = S // 2
    for p, s0 in ((Ss // 2, 0), (Ss // 2, Ss), (S - 1, Ss)):
        acc, m, l = flash_decode_stats(
            qd, kd[:, :, :Ss], vd[:, :, :Ss], jnp.int32(p), jnp.int32(s0)
        )
        acc_r, m_r, l_r = jnp_stats(
            qd, kd[:, :, :Ss], vd[:, :, :Ss], jnp.int32(p), jnp.int32(s0)
        )
        lmask = np.asarray(l_r) > 0
        if lmask.any():
            o = np.asarray(acc) / np.maximum(np.asarray(l)[..., None], 1e-30)
            o_r = np.asarray(acc_r) / np.maximum(
                np.asarray(l_r)[..., None], 1e-30
            )
            err = float(np.abs(o[lmask] - o_r[lmask]).max())
        else:
            err = float(np.abs(np.asarray(l)).max())  # must be all-masked too
        record(
            f"flash decode stats err pos={p} s0={s0}",
            f"{err:.2e} {'OK' if err < 2e-2 else 'FAIL'}",
        )

    # QuantKV-native flash prefill (r5): the [bs, 1]-blocked scale refs
    # are the one Mosaic-legality unknown (a size-1 TRAILING array dim,
    # unlike the rejected size-1 block of a larger dim) — this is the
    # first real-silicon compile+numerics check of that layout, incl.
    # the strided (cyclic-sp) mode
    from dllama_tpu.ops.kv_cache import QuantKV, dequant_kv, quantize_kv_rows

    qk = QuantKV(*quantize_kv_rows(kc))
    qv = QuantKV(*quantize_kv_rows(vc))
    fo = flash_attention(q, qk, qv, jnp.int32(512))
    fr = attention_ref(
        q, dequant_kv(qk, q.dtype), dequant_kv(qv, q.dtype), jnp.int32(512)
    )
    err = float(jnp.abs(fo.astype(jnp.float32) - fr.astype(jnp.float32)).max())
    record(
        "flash QuantKV prefill abs err", f"{err:.2e} {'OK' if err < 2e-2 else 'FAIL'}"
    )
    from dllama_tpu.ops.flash_attention import flash_attention_stats

    acc, m, l = flash_attention_stats(
        q, qk, qv, jnp.int32(512), jnp.int32(3), s_stride=4
    )
    acc_r, m_r, l_r = jnp_stats(
        q, dequant_kv(qk, q.dtype), dequant_kv(qv, q.dtype),
        jnp.int32(512), jnp.int32(3), s_stride=4,
    )
    lmask = np.asarray(l_r) > 0
    o = np.asarray(acc) / np.maximum(np.asarray(l)[..., None], 1e-30)
    o_r = np.asarray(acc_r) / np.maximum(np.asarray(l_r)[..., None], 1e-30)
    err = float(np.abs(o[lmask] - o_r[lmask]).max()) if lmask.any() else 0.0
    record(
        "flash QuantKV strided stats err",
        f"{err:.2e} {'OK' if err < 2e-2 else 'FAIL'}",
    )

    # NOTE: the round-3 silicon probe (scripts/decode_probe.py) showed
    # Mosaic does NOT elide repeated-index DMAs, so flash decode reads the
    # whole cache regardless of pos and the ENGINE now decodes via
    # windowed XLA dense attention instead. The ratio below is recorded
    # informationally (expected ~1.0), not gated.
    t_low = timeit(lambda: flash_decode(qd, kd, vd, jnp.int32(512)))
    t_high = timeit(lambda: flash_decode(qd, kd, vd, jnp.int32(S - 1)))
    record(
        "flash decode pos512/posS-1 (info)",
        f"{t_low:.3f} ms vs {t_high:.3f} ms (x{t_high / max(t_low, 1e-9):.1f})",
    )


def sec_moe() -> None:
    """Ragged + grouped MoE kernels on silicon (dense and q40) + timing."""
    from dllama_tpu.formats.quants import q40_to_planar, quantize_q40
    from dllama_tpu.ops.moe_kernel import moe_active_experts
    from dllama_tpu.ops.quant_matmul import (
        QuantWeight,
        dequant as qw_dequant,
        from_planar,
    )

    rng = np.random.default_rng(0)
    E, D, F, K = (32, 1024, 512, 4) if QUICK else (128, 2048, 768, 8)
    w1 = jnp.asarray(rng.standard_normal((E, D, F)).astype(np.float32) * 0.05).astype(jnp.bfloat16)
    w2 = jnp.asarray(rng.standard_normal((E, F, D)).astype(np.float32) * 0.05).astype(jnp.bfloat16)
    w3 = jnp.asarray(rng.standard_normal((E, D, F)).astype(np.float32) * 0.05).astype(jnp.bfloat16)
    M = 4  # multi-lane decode: exercises the dynamic sublane row select
    xm = jnp.asarray(rng.standard_normal((M, D)).astype(np.float32)).astype(jnp.bfloat16)
    idx = jnp.asarray(
        np.stack([rng.choice(E, K, replace=False) for _ in range(M)]).astype(np.int32)
    )
    wts = jnp.asarray(np.full((M, K), 1.0 / K, np.float32))
    out = moe_active_experts(xm, w1, w2, w3, idx, wts)
    # numpy oracle
    xf = np.asarray(xm, np.float32)
    exp = np.zeros((M, D), np.float32)
    for t_i in range(M):
        for i, e in enumerate(np.asarray(idx)[t_i]):
            h1 = xf[t_i : t_i + 1] @ np.asarray(w1[e], np.float32)
            h3 = xf[t_i : t_i + 1] @ np.asarray(w3[e], np.float32)
            exp[t_i] += float(wts[t_i, i]) * (
                (h1 / (1 + np.exp(-h1)) * h3) @ np.asarray(w2[e], np.float32)
            )[0]
    rel = float(np.abs(np.asarray(out) - exp).max() / (np.abs(exp).max() + 1e-9))
    record(f"ragged moe rel err (m={M})", f"{rel:.2e} {'OK' if rel < 5e-2 else 'FAIL'}")

    # quantized ragged MoE kernel on silicon
    from dllama_tpu.ops.moe_kernel import moe_active_experts_q40

    def quantize_experts(out_dim, in_dim):
        qs, ds = [], []
        for _ in range(E):
            we = rng.standard_normal((out_dim, in_dim)).astype(np.float32) * 0.05
            qv_, dv_ = q40_to_planar(quantize_q40(we), out_dim * in_dim)
            qw_ = from_planar(qv_.reshape(out_dim, in_dim),
                              dv_.reshape(out_dim, in_dim // 32))
            qs.append(np.asarray(qw_.q))
            ds.append(np.asarray(qw_.d))
        return QuantWeight(jnp.asarray(np.stack(qs)), jnp.asarray(np.stack(ds)))

    qw1, qw3 = quantize_experts(F, D), quantize_experts(F, D)
    qw2 = quantize_experts(D, F)
    outq = moe_active_experts_q40(
        xm, qw1.q, qw1.d, qw2.q, qw2.d, qw3.q, qw3.d, idx, wts
    )
    refq = moe_active_experts(
        xm, qw_dequant(qw1), qw_dequant(qw2), qw_dequant(qw3), idx, wts
    )
    rel = float(np.abs(np.asarray(outq) - np.asarray(refq)).max()
                / (np.abs(np.asarray(refq)).max() + 1e-9))
    record("ragged moe q40 rel err", f"{rel:.2e} {'OK' if rel < 5e-2 else 'FAIL'}")

    # grouped active-expert PREFILL kernel on silicon: numerics vs the
    # dense all-expert einsum at a prefill-scale token count, plus timing
    from dllama_tpu.ops.moe_kernel import moe_grouped_experts

    Np = 64 if QUICK else 256
    xg = jnp.asarray(
        rng.standard_normal((Np, D)).astype(np.float32)
    ).astype(jnp.bfloat16)
    idxg = jnp.asarray(
        np.stack([rng.choice(E, K, replace=False) for _ in range(Np)]).astype(np.int32)
    )
    wtsg_raw = rng.random((Np, K)).astype(np.float32)
    wtsg = jnp.asarray(wtsg_raw / wtsg_raw.sum(1, keepdims=True))
    outg = moe_grouped_experts(xg, w1, w2, w3, idxg, wtsg)
    xgf = np.asarray(xg, np.float32)
    expg = np.zeros((Np, D), np.float32)
    for t_i in range(Np):
        for i, ei in enumerate(np.asarray(idxg)[t_i]):
            h1 = xgf[t_i : t_i + 1] @ np.asarray(w1[ei], np.float32)
            h3 = xgf[t_i : t_i + 1] @ np.asarray(w3[ei], np.float32)
            expg[t_i] += float(wtsg[t_i, i]) * (
                (h1 / (1 + np.exp(-h1)) * h3) @ np.asarray(w2[ei], np.float32)
            )[0]
    relg = float(np.abs(np.asarray(outg) - expg).max() / (np.abs(expg).max() + 1e-9))
    record(
        f"grouped moe prefill rel err (N={Np})",
        f"{relg:.2e} {'OK' if relg < 5e-2 else 'FAIL'}",
    )
    # q40 twin: the quantized grouped kernel is what every quantized
    # prefill routes through — it must meet real Mosaic here first
    from dllama_tpu.ops.moe_kernel import moe_grouped_experts_q40

    outgq = moe_grouped_experts_q40(
        xg, qw1.q, qw1.d, qw2.q, qw2.d, qw3.q, qw3.d, idxg, wtsg
    )
    refgq = moe_grouped_experts(
        xg, qw_dequant(qw1), qw_dequant(qw2), qw_dequant(qw3), idxg, wtsg
    )
    relgq = float(
        np.abs(np.asarray(outgq) - np.asarray(refgq)).max()
        / (np.abs(np.asarray(refgq)).max() + 1e-9)
    )
    record(
        f"grouped moe q40 prefill rel err (N={Np})",
        f"{relgq:.2e} {'OK' if relgq < 5e-2 else 'FAIL'}",
    )
    t_grouped = timeit(lambda: moe_grouped_experts(xg, w1, w2, w3, idxg, wtsg), n_iter=20)
    f_dense_all = jax.jit(
        lambda xx: jnp.einsum("nd,edf->nef", xx, w1)
    )
    t_dense_all = timeit(lambda: f_dense_all(xg), n_iter=20)
    record(f"moe grouped prefill N={Np} (full swiglu)", f"{t_grouped:.2f} ms")
    record(f"moe dense prefill N={Np} (w1 only, all E)", f"{t_dense_all:.2f} ms")

    t_ragged = timeit(lambda: moe_active_experts(xm, w1, w2, w3, idx, wts))
    t_ragged_q = timeit(
        lambda: moe_active_experts_q40(
            xm, qw1.q, qw1.d, qw2.q, qw2.d, qw3.q, qw3.d, idx, wts
        )
    )
    # decode at full lane count: does expert DEDUP (grouped) beat the
    # per-(token, choice) ragged DMA schedule at m=16, where ~1/3 of the
    # 128 draws hit an expert another lane already read? (VERDICT r2 weak
    # #6 — data decides the routing threshold, MOE_PALLAS_MAX_TOKENS)
    M16 = 16
    x16 = jnp.asarray(
        rng.standard_normal((M16, D)).astype(np.float32)
    ).astype(jnp.bfloat16)
    idx16 = jnp.asarray(
        np.stack([rng.choice(E, K, replace=False) for _ in range(M16)]).astype(np.int32)
    )
    wts16 = jnp.asarray(np.full((M16, K), 1.0 / K, np.float32))
    t_ragged16 = timeit(
        lambda: moe_active_experts(x16, w1, w2, w3, idx16, wts16)
    )
    t_grouped16 = timeit(
        lambda: moe_grouped_experts(x16, w1, w2, w3, idx16, wts16)
    )
    record("moe ragged m=16", f"{t_ragged16:.2f} ms")
    record("moe grouped m=16", f"{t_grouped16:.2f} ms")
    f_dense = jax.jit(
        lambda xx: jnp.einsum("nd,edf->nef", xx, w1)
    )
    t_dense_w1 = timeit(lambda: f_dense(xm))
    record("moe ragged (full swiglu k experts)", f"{t_ragged:.2f} ms")
    record("moe ragged q40 (full swiglu k experts)", f"{t_ragged_q:.2f} ms")
    record("moe dense (w1 only, all E)", f"{t_dense_w1:.2f} ms")


def sec_engine() -> None:
    """q40-vs-dense greedy token parity + per-lane serving through the
    actual engine on real silicon (exercises the FUSED wqkv/w13 path —
    the q40 engine default)."""
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))
    from helpers import make_tiny_model

    from dllama_tpu.runtime.engine import InferenceEngine

    d = tempfile.mkdtemp()
    cfg = dict(dim=128, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=256, seq_len=128)
    make_tiny_model(d + "/m.m", cfg=cfg)
    eq = InferenceEngine(d + "/m.m", tp=1, dtype=jnp.bfloat16, temperature=0.0,
                         weight_format="q40")
    assert "wqkv" in eq.params["layers"], "q40 engine should fuse by default"
    outq, _, _ = eq.generate([1, 2, 3, 4], max_steps=20)
    del eq
    ed = InferenceEngine(d + "/m.m", tp=1, dtype=jnp.bfloat16, temperature=0.0,
                         weight_format="dense")
    outd, _, _ = ed.generate([1, 2, 3, 4], max_steps=20)
    del ed
    record("engine q40(fused) == dense tokens",
           "OK" if outq == outd else f"FAIL {outq} {outd}")

    # per-lane serving on silicon: parked prefill + per-lane decode
    eb = InferenceEngine(d + "/m.m", tp=1, dtype=jnp.bfloat16,
                         temperature=0.0, weight_format="q40", batch_size=2)
    prompts = [[1, 2, 3, 4], [9, 8, 7, 6, 5]]
    singles = []
    es = InferenceEngine(d + "/m.m", tp=1, dtype=jnp.bfloat16,
                         temperature=0.0, weight_format="q40")
    for p in prompts:
        es.reset()
        o, _, _ = es.generate(p, max_steps=20)
        singles.append(o)
    del es
    outs = eb.generate_batch(prompts, max_steps=20)
    record(
        "engine lanes == single-stream tokens",
        "OK" if outs == singles else f"FAIL {outs} {singles}",
    )
    del eb

    # round-4 paths on real silicon: grouped-int8 device format and the
    # int8 KV cache (each vs its own single-config oracle — q40i8/kv8
    # change numerics slightly, so the oracle is the same config tp=1)
    e8 = InferenceEngine(d + "/m.m", tp=1, dtype=jnp.bfloat16,
                         temperature=0.0, weight_format="q40i8")
    out8, _, _ = e8.generate([1, 2, 3, 4], max_steps=20)
    del e8
    record(
        "engine q40i8 decodes (tokens len)",
        "OK" if len(out8) == 17 else f"FAIL {out8}",
    )
    ekv = InferenceEngine(d + "/m.m", tp=1, dtype=jnp.bfloat16,
                          temperature=0.0, weight_format="q40",
                          kv_dtype="int8")
    outkv, _, _ = ekv.generate([1, 2, 3, 4], max_steps=20)
    del ekv
    # int8 KV perturbs logits only slightly; greedy streams on this
    # fixture matched exactly on CPU — report drift rather than fail
    record(
        "engine kv-int8 vs q40 tokens",
        "OK" if outkv == outq else f"DRIFT {outkv} vs {outq}",
    )


def sec_bench() -> None:
    """Decode throughput via bench.py subprocesses."""
    import subprocess

    env = dict(os.environ)
    for preset, fmt in (
        [("llama-1b", "q40"), ("llama-1b", "dense"), ("llama-8b", "q40")]
        if not QUICK
        else [("llama-1b", "q40")]
    ):
        env.update(BENCH_PRESET=preset, BENCH_FORMAT=fmt, BENCH_STEPS="64")
        try:
            r = subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(__file__), "..", "bench.py")],
                capture_output=True, text=True, env=env, timeout=1500,
            )
            if r.returncode != 0:
                line = f"FAIL rc={r.returncode}: {r.stderr.strip().splitlines()[-1] if r.stderr.strip() else 'no stderr'}"
            else:
                line = (
                    r.stdout.strip().splitlines()[-1]
                    if r.stdout.strip()
                    else "no output"
                )
        except subprocess.TimeoutExpired:
            line = "FAIL: timeout (1500s)"
        record(f"bench {preset} {fmt}", line)


SECTIONS = {
    "qmm": sec_qmm,
    "flash": sec_flash,
    "moe": sec_moe,
    "engine": sec_engine,
    "bench": sec_bench,
}


def main() -> None:
    print(f"devices: {jax.devices()}", flush=True)
    only = os.environ.get("TPU_VALIDATION_ONLY", "")
    wanted = [s for s in only.split(",") if s] or list(SECTIONS)
    unknown = [s for s in wanted if s not in SECTIONS]
    if unknown:
        raise SystemExit(
            f"unknown TPU_VALIDATION_ONLY section(s) {unknown}; "
            f"valid: {', '.join(SECTIONS)}"
        )
    for name in wanted:
        print(f"-- section {name} --", flush=True)
        SECTIONS[name]()

    print("\n=== TPU validation summary ===")
    for name, value in RESULTS:
        print(f"{name:40s} {value}")


if __name__ == "__main__":
    main()
