#!/usr/bin/env python
"""Decode-attention design probe — run on real TPU silicon.

Measurement method (the only one that survives this platform): the axon
tunnel adds a large, jittery fixed cost per dispatched program AND per
host readback (tens of ms round trip), so neither single-call timing nor
a single fori_loop average is meaningful. Each variant therefore runs as
ONE jitted lax.fori_loop at TWO iteration counts (N_LO, N_HI) and reports
the MARGINAL per-iteration time (t_hi - t_lo) / (N_HI - N_LO), min over
several reps — fixed dispatch/readback costs cancel in the difference.

Questions:
  A. does the flash-decode clamped index map bound cache reads by pos on
     real Mosaic (pos=511 vs pos=S-1, same program)?
  B. XLA dense T=1 attention on the same cache.
  C. windowed dense / flash (what bucketed decode costs at small pos).
  D. raw HBM read-rate reference (sum-reduce the cache).
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dllama_tpu.parallel.mesh import enable_compilation_cache, reassert_platform

reassert_platform()
enable_compilation_cache()

import jax
import jax.numpy as jnp
from jax import lax

N_LO, N_HI = 8, 128


def sync(x):
    return np.asarray(jax.device_get(jnp.ravel(x)[0]))


def marginal_ms(body, n_outer=6):
    """Per-iteration device ms of `body(i) -> array`, by differencing two
    on-device loop lengths (fixed tunnel costs cancel)."""

    def make(n):
        @jax.jit
        def run():
            def step(i, acc):
                return acc + body(i).astype(jnp.float32).sum()

            return lax.fori_loop(0, n, step, jnp.float32(0.0))

        return run

    f_lo, f_hi = make(N_LO), make(N_HI)
    sync(f_lo())
    sync(f_hi())
    best_lo = best_hi = float("inf")
    for _ in range(n_outer):
        t0 = time.perf_counter()
        sync(f_lo())
        best_lo = min(best_lo, time.perf_counter() - t0)
        t0 = time.perf_counter()
        sync(f_hi())
        best_hi = min(best_hi, time.perf_counter() - t0)
    return (best_hi - best_lo) / (N_HI - N_LO) * 1000


def report(name, ms, mbytes):
    print(f"{name:34s} {ms:8.4f} ms/iter  {mbytes / ms:7.1f} GB/s eff",
          flush=True)


def main():
    from dllama_tpu.ops.flash_attention import flash_decode
    from dllama_tpu.ops.jnp_ops import attention_dense

    rng = np.random.default_rng(0)
    B, H, KH, HD = 1, 8, 4, 64
    S = 32768
    q = jnp.asarray(rng.standard_normal((B, 1, H, HD)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, KH, S, HD)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, KH, S, HD)), jnp.bfloat16)
    cache_mb = 2 * B * KH * S * HD * 2 / 1e6
    print(f"cache bytes (k+v): {cache_mb:.1f} MB; marginal over "
          f"N={N_LO}->{N_HI} on-device iters", flush=True)

    # D: raw read-rate reference
    ms = marginal_ms(lambda i: (k + i).astype(jnp.float32).sum()[None])
    report(f"D sum-reduce k ({cache_mb/2:.0f} MB)", ms, cache_mb / 2)

    # A: flash decode, pos-bounded?
    for pos, bs in ((511, 1024), (S - 1, 1024)):
        try:
            ms = marginal_ms(
                lambda i, pos=pos, bs=bs: flash_decode(
                    q, k, v, jnp.int32(pos) + 0 * i, block_s=bs)
            )
            report(f"A flash pos={pos} bs={bs}", ms, cache_mb)
        except Exception as e:
            print(f"A flash pos={pos} bs={bs}: {type(e).__name__}: "
                  f"{str(e)[:100]}", flush=True)

    # B: XLA dense full cache
    ms = marginal_ms(lambda i: attention_dense(q, k, v, jnp.int32(S - 1) + 0 * i))
    report(f"B xla-dense S={S}", ms, cache_mb)

    # C: windowed dense / flash at small pos
    for w in (512, 2048, 8192):
        kw, vw = k[:, :, :w], v[:, :, :w]
        mb = 2 * B * KH * w * HD * 2 / 1e6
        ms = marginal_ms(
            lambda i, kw=kw, vw=vw, w=w: attention_dense(
                q, kw, vw, jnp.int32(w - 1) + 0 * i)
        )
        report(f"C xla-dense window={w}", ms, mb)
    for w in (2048, 8192):
        kw, vw = k[:, :, :w], v[:, :, :w]
        mb = 2 * B * KH * w * HD * 2 / 1e6
        try:
            ms = marginal_ms(
                lambda i, kw=kw, vw=vw, w=w: flash_decode(
                    q, kw, vw, jnp.int32(w - 1) + 0 * i, block_s=1024)
            )
            report(f"C2 flash window={w}", ms, mb)
        except Exception as e:
            print(f"C2 flash window={w}: {type(e).__name__}: {str(e)[:100]}",
                  flush=True)


if __name__ == "__main__":
    main()
