#!/usr/bin/env python
"""Lint: every registered `dllama_*` metric is documented, and vice versa.

This check is now the `metrics-docs` rule inside the dlint framework
(`python -m dllama_tpu.analysis` runs it with everything else); this
script survives as a thin shim so existing invocations and CI steps keep
working. See dllama_tpu/analysis/rules_metrics.py for the semantics.

Usage: python scripts/check_metrics_docs.py  (exit 0 clean, 1 drifted)
"""

from __future__ import annotations

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dllama_tpu.analysis.core import collect_repo, run_rules  # noqa: E402
from dllama_tpu.analysis.rules_metrics import MetricsDocsRule  # noqa: E402


def main() -> int:
    repo = collect_repo(REPO, ["dllama_tpu", "bench.py"])
    findings, _ = run_rules(repo, [MetricsDocsRule()])
    for f in findings:
        print(f.render())
    if findings:
        print(
            "\nfix: update the tables in docs/serving_metrics.md to match "
            "the registration sites (grep for the name above)."
        )
        return 1
    print("metrics docs in sync (dlint metrics-docs rule)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
