#!/usr/bin/env python
"""Lint: every registered `dllama_*` metric is documented, and vice versa.

The metric tables in docs/serving_metrics.md are the operator contract —
dashboards and alerts are built off them. This check fails CI when a
metric is registered in code but missing from the doc (silent new
telemetry nobody can discover) or documented but no longer registered
(dashboards querying a phantom).

Source side: static scan of `reg.counter("dllama_...")` /
`.gauge(` / `.histogram(` registration calls across `dllama_tpu/` and
`bench.py` (registrations span lines, so the regex runs over whole file
contents). Dynamically named metrics — `utils/telemetry.Counter`'s
f-string `dllama_<name>_events_total` pair — have no literal name at the
registration site and are intentionally out of scope; the doc describes
them as a template.

Doc side: every backticked `dllama_*` identifier in
docs/serving_metrics.md. The `<name>` placeholder in the Counter
template breaks the identifier pattern, so the template never counts as
a concrete metric.

Usage: python scripts/check_metrics_docs.py  (exit 0 clean, 1 drifted)
"""

from __future__ import annotations

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
DOC = REPO / "docs" / "serving_metrics.md"

_REGISTRATION = re.compile(
    r"\b(?:counter|gauge|histogram)\(\s*[\"'](dllama_[a-z0-9_]+)[\"']"
)
_DOC_NAME = re.compile(r"`(dllama_[a-z0-9_]+)`")


def registered_names() -> set[str]:
    names: set[str] = set()
    sources = list((REPO / "dllama_tpu").rglob("*.py"))
    sources.append(REPO / "bench.py")
    for path in sources:
        names |= set(_REGISTRATION.findall(path.read_text()))
    return names


def documented_names() -> set[str]:
    return set(_DOC_NAME.findall(DOC.read_text()))


def main() -> int:
    code = registered_names()
    doc = documented_names()
    undocumented = sorted(code - doc)
    phantom = sorted(doc - code)
    if undocumented:
        print(f"metrics registered in code but missing from {DOC.name}:")
        for n in undocumented:
            print(f"  {n}")
    if phantom:
        print(f"metrics documented in {DOC.name} but registered nowhere:")
        for n in phantom:
            print(f"  {n}")
    if undocumented or phantom:
        print(
            "\nfix: update the tables in docs/serving_metrics.md to match "
            "the registration sites (grep for the name above)."
        )
        return 1
    print(f"metrics docs in sync: {len(code)} metrics, all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
