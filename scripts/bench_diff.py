#!/usr/bin/env python
"""Bench trajectory: append each run's BENCH_*.json to a history dir and
diff it against the previous run.

bench.py already writes machine-readable ``BENCH_<section>.json``
summaries (DECODE / TTFT / LANES / SWEEP / SERVING) at the end of every
run; this script turns those isolated snapshots into a trajectory:

* append the current run — tagged with a git SHA and a timestamp — as
  one JSON record under ``artifacts/bench_history/``;
* print a per-metric delta table against the previous recorded run;
* exit non-zero when a WATCHED latency metric (decode step p50, TTFT
  p50) regressed by more than ``--threshold`` (default 15%), unless
  ``--warn-only`` (the CI soft gate: noisy shared runners must not turn
  a perf wiggle into a red build).

The library functions take the timestamp and SHA as ARGUMENTS — only
``main()`` reads the real clock and the git repo — so tests drive the
whole append/diff/regression path deterministically.

Usage:
    python scripts/bench_diff.py                 # hard gate
    python scripts/bench_diff.py --warn-only     # CI soft gate
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

SECTIONS = ("DECODE", "TTFT", "LANES", "SWEEP", "SERVING")

# metric -> direction; "lower" means an INCREASE past the threshold is a
# regression. These are the two latencies the ISSUE gates on; everything
# else is reported but never fails the run.
WATCHED: dict[str, str] = {
    "DECODE.step_ms.p50": "lower",
    "TTFT.ttft_ms_p50": "lower",
    "SERVING.ttft_ms_p50": "lower",
    # the recovery tax: how long a poisoned lane's client stalls while
    # its history re-prefills (ISSUE 12; generous threshold headroom is
    # the --threshold flag's job, not this table's)
    "SERVING.resilience.p99_gap_ms_recovery": "lower",
    # the oversubscription tax: steady-state decode cadence while 2x the
    # lane count of streams park/resume through the pool-native path
    # (ISSUE 16)
    "SERVING.oversubscription.tpot_ms_p50": "lower",
    # the fleet front door's delivered rate on the shared-prefix round:
    # a drop here means affinity routing stopped landing prompts on the
    # replica that already holds their prefix (ISSUE 17)
    "SERVING.fleet.goodput_tok_s": "higher",
    # cross-lane shared speculation on the natural-language fanout
    # round: a drop means sibling continuations stopped reaching the
    # drafter through the shared n-gram store (ISSUE 18)
    "SERVING.speculation_nl.tok_s_shared": "higher",
    # the failover tax a client actually feels: dead air between the
    # victim's last relayed byte and the sibling's catch-up chunk on the
    # seeded kill round (ISSUE 19)
    "SERVING.fleet.fleet_obs.failover_gap_ms_p99": "lower",
    # SLO-met tokens/s under the 4x mixed-deadline overload wave with
    # predictive admission on: a drop means the predictor stopped
    # steering lane time away from infeasible work (ISSUE 20)
    "SERVING.overload.goodput_tok_s": "higher",
}


def load_sections(bench_dir: str) -> dict[str, dict]:
    """The BENCH_<section>.json files present in ``bench_dir``."""
    out: dict[str, dict] = {}
    for section in SECTIONS:
        path = os.path.join(bench_dir, f"BENCH_{section}.json")
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                out[section] = json.load(f)
        except (OSError, ValueError) as e:
            print(f"bench-diff: skipping unreadable {path}: {e}")
    return out


def flatten(payload: object, prefix: str = "") -> dict[str, float]:
    """Numeric leaves of a (nested) section payload as dotted keys."""
    out: dict[str, float] = {}
    if isinstance(payload, bool):
        return out
    if isinstance(payload, (int, float)):
        out[prefix] = float(payload)
        return out
    if isinstance(payload, dict):
        for k, v in sorted(payload.items()):
            key = f"{prefix}.{k}" if prefix else str(k)
            out.update(flatten(v, key))
    return out


def run_record(
    sections: dict[str, dict], git_sha: str, timestamp: float
) -> dict:
    return {
        "git_sha": git_sha,
        "timestamp": timestamp,
        "sections": sections,
    }


def append_history(
    history_dir: str, record: dict
) -> str:
    """Write ``record`` as ``<timestamp>-<sha>.json`` under
    ``history_dir`` (created on demand); lexicographic filename order is
    chronological order."""
    os.makedirs(history_dir, exist_ok=True)
    sha = re.sub(r"[^0-9a-zA-Z]", "", record["git_sha"]) or "unknown"
    name = f"{int(record['timestamp']):013d}-{sha}.json"
    path = os.path.join(history_dir, name)
    with open(path, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def previous_record(history_dir: str, exclude: str) -> dict | None:
    """The newest history record other than ``exclude`` (the one just
    written)."""
    if not os.path.isdir(history_dir):
        return None
    names = sorted(
        n for n in os.listdir(history_dir)
        if n.endswith(".json")
        and os.path.join(history_dir, n) != exclude
    )
    for name in reversed(names):
        try:
            with open(os.path.join(history_dir, name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            continue
    return None


def diff_rows(
    prev: dict, cur: dict
) -> list[tuple[str, float | None, float | None, float | None]]:
    """(metric, prev, cur, delta_pct) per numeric metric in either run;
    delta_pct is None when either side is missing or prev is 0."""
    pf = flatten(prev.get("sections", {}))
    cf = flatten(cur.get("sections", {}))
    rows = []
    for key in sorted(set(pf) | set(cf)):
        p, c = pf.get(key), cf.get(key)
        delta = (
            (c - p) / abs(p) * 100.0
            if p is not None and c is not None and p != 0
            else None
        )
        rows.append((key, p, c, delta))
    return rows


def regressions(
    prev: dict, cur: dict, threshold: float = 0.15
) -> list[str]:
    """WATCHED metrics that moved the wrong way past ``threshold``."""
    pf = flatten(prev.get("sections", {}))
    cf = flatten(cur.get("sections", {}))
    out = []
    for key, direction in WATCHED.items():
        p, c = pf.get(key), cf.get(key)
        if p is None or c is None or p <= 0:
            continue
        worse = c > p * (1.0 + threshold) if direction == "lower" else (
            c < p * (1.0 - threshold)
        )
        if worse:
            out.append(
                f"{key}: {p:g} -> {c:g} "
                f"({(c - p) / p * 100.0:+.1f}% past the "
                f"{threshold * 100.0:.0f}% gate)"
            )
    return out


def render_table(
    rows: list[tuple[str, float | None, float | None, float | None]]
) -> str:
    def fmt(v: float | None) -> str:
        return "-" if v is None else f"{v:g}"

    lines = [f"{'metric':<52} {'prev':>12} {'cur':>12} {'delta':>9}"]
    for key, p, c, d in rows:
        delta = "-" if d is None else f"{d:+.1f}%"
        lines.append(f"{key:<52} {fmt(p):>12} {fmt(c):>12} {delta:>9}")
    return "\n".join(lines)


def git_short_sha() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "unknown"
    except (OSError, subprocess.SubprocessError):
        return "unknown"


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="bench-diff", description=__doc__)
    parser.add_argument(
        "--bench-dir", default=".",
        help="directory holding the BENCH_*.json files (default: .)",
    )
    parser.add_argument(
        "--history-dir", default="artifacts/bench_history",
        help="history directory runs append to",
    )
    parser.add_argument(
        "--threshold", type=float, default=0.15,
        help="regression gate as a fraction (default 0.15 = 15%%)",
    )
    parser.add_argument(
        "--warn-only", action="store_true",
        help="report regressions but exit 0 (the CI soft gate)",
    )
    parser.add_argument("--git-sha", default=None)
    parser.add_argument("--timestamp", type=float, default=None)
    args = parser.parse_args(argv)

    sections = load_sections(args.bench_dir)
    if not sections:
        print(f"bench-diff: no BENCH_*.json in {args.bench_dir}; nothing to do")
        return 0
    sha = args.git_sha if args.git_sha else git_short_sha()
    ts = args.timestamp if args.timestamp is not None else time.time()
    record = run_record(sections, sha, ts)
    path = append_history(args.history_dir, record)
    print(f"bench-diff: recorded {path}")
    prev = previous_record(args.history_dir, exclude=path)
    if prev is None:
        print("bench-diff: first recorded run; no diff")
        return 0
    print(
        f"bench-diff: vs {prev.get('git_sha', '?')} "
        f"@ {prev.get('timestamp', '?')}"
    )
    print(render_table(diff_rows(prev, record)))
    regs = regressions(prev, record, args.threshold)
    if regs:
        for r in regs:
            print(f"bench-diff: REGRESSION {r}")
        if args.warn_only:
            print("bench-diff: --warn-only set; not failing the run")
            return 0
        return 1
    print("bench-diff: no watched regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
