"""Monte-Carlo routing correlation study for the MoE decode dedup default.

VERDICT r4 #8: `--moe-decode-dedup`'s two-tier lax.cond pays off iff the
runtime unique-expert count u of a decode batch fits the small grid
(u <= U_small = lanes*k/2). Whether that happens depends on routing
correlation across lanes, which no synthetic fixture exhibits and no real
checkpoint is reachable (zero egress). This sim maps the DECISION
BOUNDARY instead: for A3B shapes (E=128, k=8), how correlated must lane
routing be before the small grid hits most of the time?

Model: lane l's gate logits z_l = sqrt(rho) * g_shared + sqrt(1-rho) *
g_l + bias, g ~ N(0, I_E); bias_e = -s * log(rank_e) imposes a Zipf-like
expert popularity (s = 0 balanced, s = 1 strongly skewed — aux-loss-
balanced MoEs sit near 0..0.5 corpus-wide). rho models shared-prefix /
same-domain lanes. u = |union of per-lane top-k|.

Prints a table of E[u] and P(u <= U_small) over (batch, rho, s); the
conclusion lives in docs/moe_decode_dedup.md.
"""

import json
import sys

import numpy as np

E, K = 128, 8
TRIALS = 4000


def sim(batch: int, rho: float, s: float, rng) -> tuple[float, float]:
    cap = batch * K // 2
    bias = -s * np.log(np.arange(1, E + 1, dtype=np.float64))
    us = np.empty(TRIALS, np.int64)
    for t in range(TRIALS):
        shared = rng.standard_normal(E)
        z = (
            np.sqrt(rho) * shared[None, :]
            + np.sqrt(1.0 - rho) * rng.standard_normal((batch, E))
            + bias[None, :]
        )
        top = np.argpartition(z, -K, axis=1)[:, -K:]
        us[t] = np.unique(top).size
    return float(us.mean()), float((us <= cap).mean())


def main() -> None:
    rng = np.random.default_rng(0)
    rows = []
    for batch in (4, 8, 16):
        for rho in (0.0, 0.5, 0.8, 0.9, 0.95, 0.99):
            for s in (0.0, 0.5, 1.0):
                mean_u, hit = sim(batch, rho, s, rng)
                rows.append(
                    dict(batch=batch, rho=rho, zipf_s=s, cap=batch * K // 2,
                         mean_u=round(mean_u, 1), hit_rate=round(hit, 3))
                )
    print(json.dumps(rows))
    # human table on stderr
    print(f"{'B':>3} {'rho':>5} {'s':>4} {'cap':>4} {'E[u]':>6} {'P(hit)':>7}",
          file=sys.stderr)
    for r in rows:
        print(
            f"{r['batch']:>3} {r['rho']:>5} {r['zipf_s']:>4} {r['cap']:>4} "
            f"{r['mean_u']:>6} {r['hit_rate']:>7}",
            file=sys.stderr,
        )


if __name__ == "__main__":
    main()
