#!/usr/bin/env python
"""Focused follow-up sweep for the shipping Q40 kernel (run on silicon).

Round-3's broad sweep (kernel_sweep.py) picked (bn=256, bk=4096) at
m=1, k=4096, n=14336. This narrows in on what the engine actually
launches after the qkv/w13 fusion:

  * block-shape neighborhood of the winner,
  * decode lane counts m in {1, 4, 8, 16} (continuous batching),
  * the FUSED out dims for the 8B shapes: qkv n=6144 (4096+2*1024),
    w13 n=28672 (2*14336), wo/w2 shapes,
  * bf16 scales variant (halves scale bytes; scales are ~2% of traffic
    so this mostly probes whether the f32->bf16 widening in VMEM costs).

Prints ms/call and effective GB/s per config.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dllama_tpu.parallel.mesh import enable_compilation_cache, reassert_platform

reassert_platform()
enable_compilation_cache()

import jax
import jax.numpy as jnp

from dllama_tpu.ops.quant_matmul import qmatmul_2d

Q_BLOCK = 32


def sync(x):
    return np.asarray(jax.device_get(jnp.ravel(x)[0]))


def timeit(f, n_iter=50):
    o = f()
    sync(o)
    t0 = time.perf_counter()
    for _ in range(n_iter):
        o = f()
    sync(o)
    return (time.perf_counter() - t0) / n_iter * 1000


def prefill_section(rng):
    """Prefill path choices, measured (TTFT components):

    (a) q40 Pallas matmul at m=128 vs XLA dense bf16 GEMM on the same
        (dequantized) weights — at prefill m the matmul is compute-denser
        and the MXU-optimal dense GEMM may beat the dequant kernel even
        though it reads ~1.8x the bytes;
    (b) flash prefill attention vs XLA dense attention at T=128, the
        default TTFT prompt shape.
    """
    from dllama_tpu.ops.flash_attention import attention_ref, flash_attention
    from dllama_tpu.ops.quant_matmul import QuantWeight, dequant

    k, n = 4096, 14336
    wq = jnp.asarray(rng.integers(-8, 8, size=(k, n), dtype=np.int8))
    wd = jnp.asarray(
        rng.standard_normal((k // Q_BLOCK, n)).astype(np.float32) * 0.01
    )
    w_dense = dequant(QuantWeight(wq, wd), jnp.bfloat16)
    f_dense = jax.jit(lambda xx: xx @ w_dense)
    for m in (1, 32, 128):
        x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32))
        xb = x.astype(jnp.bfloat16)
        ms_q = timeit(lambda: qmatmul_2d(x, wq, wd))
        ms_d = timeit(lambda: f_dense(xb))
        print(f"prefill matmul m={m:4d}: q40 {ms_q:7.3f} ms  "
              f"xla-dense-bf16 {ms_d:7.3f} ms", flush=True)

    b, t, s, hq, kh, hd = 1, 128, 2048, 32, 8, 128
    q = jnp.asarray(
        rng.standard_normal((b, t, hq, hd)).astype(np.float32)
    ).astype(jnp.bfloat16)
    kc = jnp.asarray(
        rng.standard_normal((b, kh, s, hd)).astype(np.float32)
    ).astype(jnp.bfloat16)
    vc = jnp.asarray(
        rng.standard_normal((b, kh, s, hd)).astype(np.float32)
    ).astype(jnp.bfloat16)
    pos = jnp.int32(s - t)
    ms_f = timeit(lambda: flash_attention(q, kc, vc, pos))
    f_ref = jax.jit(lambda qq, kk, vv: attention_ref(qq, kk, vv, pos))
    ms_r = timeit(lambda: f_ref(q, kc, vc))
    print(f"prefill attn T={t} S={s}: flash {ms_f:7.3f} ms  "
          f"xla-dense {ms_r:7.3f} ms", flush=True)


def main():
    rng = np.random.default_rng(0)
    print(f"devices: {jax.devices()}", flush=True)
    prefill_section(rng)

    # (label, m, k, n) — the 8B decode launches after fusion
    shapes = [
        ("qkv-fused 8B", 1, 4096, 6144),
        ("wo 8B", 1, 4096, 4096),
        ("w13-fused 8B", 1, 4096, 28672),
        ("w2 8B", 1, 14336, 4096),
    ]
    for m in (4, 8, 16):
        shapes.append((f"w13-fused 8B m={m}", m, 4096, 28672))

    blocks = [(256, 4096), (128, 4096), (512, 4096), (256, 2048), (256, 8192)]

    for label, m, k, n in shapes:
        wq = jnp.asarray(rng.integers(-8, 8, size=(k, n), dtype=np.int8))
        wd = jnp.asarray(
            rng.standard_normal((k // Q_BLOCK, n)).astype(np.float32) * 0.01
        )
        x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32))
        nbytes = wq.size + wd.size * 4
        for bn, bk in blocks:
            if bk > k:
                continue
            try:
                ms = timeit(
                    lambda: qmatmul_2d(x, wq, wd, block_n=bn, block_k=bk)
                )
            except Exception as e:
                print(f"{label:22s} bn={bn:5d} bk={bk:5d}  FAIL {type(e).__name__}: {e}",
                      flush=True)
                continue
            gbs = nbytes / (ms / 1000) / 1e9
            print(f"{label:22s} bn={bn:5d} bk={bk:5d}  {ms:8.3f} ms  {gbs:7.1f} GB/s",
                  flush=True)


if __name__ == "__main__":
    main()
