#!/usr/bin/env python
"""Focused follow-up sweep for the shipping Q40 kernel (run on silicon).

Round-3's broad sweep (kernel_sweep.py) picked (bn=256, bk=4096) at
m=1, k=4096, n=14336. This narrows in on what the engine actually
launches after the qkv/w13 fusion:

  * block-shape neighborhood of the winner,
  * decode lane counts m in {1, 4, 8, 16} (continuous batching),
  * the FUSED out dims for the 8B shapes: qkv n=6144 (4096+2*1024),
    w13 n=28672 (2*14336), wo/w2 shapes,
  * bf16 scales variant (halves scale bytes; scales are ~2% of traffic
    so this mostly probes whether the f32->bf16 widening in VMEM costs).

Prints ms/call and effective GB/s per config.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dllama_tpu.parallel.mesh import enable_compilation_cache, reassert_platform

reassert_platform()
enable_compilation_cache()

import jax
import jax.numpy as jnp

from dllama_tpu.ops.quant_matmul import qmatmul_2d

Q_BLOCK = 32


def sync(x):
    return np.asarray(jax.device_get(jnp.ravel(x)[0]))


def timeit(f, n_iter=50):
    o = f()
    sync(o)
    t0 = time.perf_counter()
    for _ in range(n_iter):
        o = f()
    sync(o)
    return (time.perf_counter() - t0) / n_iter * 1000


def main():
    rng = np.random.default_rng(0)
    print(f"devices: {jax.devices()}", flush=True)

    # (label, m, k, n) — the 8B decode launches after fusion
    shapes = [
        ("qkv-fused 8B", 1, 4096, 6144),
        ("wo 8B", 1, 4096, 4096),
        ("w13-fused 8B", 1, 4096, 28672),
        ("w2 8B", 1, 14336, 4096),
    ]
    for m in (4, 8, 16):
        shapes.append((f"w13-fused 8B m={m}", m, 4096, 28672))

    blocks = [(256, 4096), (128, 4096), (512, 4096), (256, 2048), (256, 8192)]

    for label, m, k, n in shapes:
        wq = jnp.asarray(rng.integers(-8, 8, size=(k, n), dtype=np.int8))
        wd = jnp.asarray(
            rng.standard_normal((k // Q_BLOCK, n)).astype(np.float32) * 0.01
        )
        x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32))
        nbytes = wq.size + wd.size * 4
        for bn, bk in blocks:
            if bk > k:
                continue
            try:
                ms = timeit(
                    lambda: qmatmul_2d(x, wq, wd, block_n=bn, block_k=bk)
                )
            except Exception as e:
                print(f"{label:22s} bn={bn:5d} bk={bk:5d}  FAIL {type(e).__name__}: {e}",
                      flush=True)
                continue
            gbs = nbytes / (ms / 1000) / 1e9
            print(f"{label:22s} bn={bn:5d} bk={bk:5d}  {ms:8.3f} ms  {gbs:7.1f} GB/s",
                  flush=True)


if __name__ == "__main__":
    main()
