#!/bin/bash
# Tunnel watcher: probe the TPU every couple of minutes; when it answers,
# run the post-fusion silicon capture section by section, each in its own
# subprocess with its own timeout (the tunnel WEDGES rather than errors,
# so a hang must only cost one section). Results land in artifacts/.
cd /root/repo
A=artifacts
probe() {
  timeout 150 python -c "
import jax, jax.numpy as jnp, numpy as np
x = jnp.ones((256, 256))
print(float(np.asarray((x @ x).ravel()[0])))
" >/dev/null 2>&1
}

until probe; do
  echo "$(date +%H:%M:%S) tunnel down; retrying in 120s" >&2
  sleep 120
done
echo "$(date +%H:%M:%S) tunnel UP — starting capture" >&2

run() { # name timeout_s cmd...
  local name=$1 t=$2; shift 2
  echo "=== $name ==="
  timeout "$t" "$@" >"$A/$name.log" 2>&1
  echo "exit=$? (tail):"
  tail -5 "$A/$name.log"
}

run bench_8b_q40_fused 1800 env BENCH_PRESET=llama-8b BENCH_FORMAT=q40 python bench.py
run sweep_r04_i8 2400 python scripts/sweep_r04_i8.py
run bench_8b_q40i8 1800 env BENCH_PRESET=llama-8b BENCH_FORMAT=q40i8 python bench.py
run bench_8b_q40i8_kv8 1800 env BENCH_PRESET=llama-8b BENCH_FORMAT=q40i8 BENCH_KV=int8 python bench.py
run validate_engine 900 env TPU_VALIDATION_ONLY=engine python scripts/tpu_validation.py
run validate_qmm_flash 1200 env TPU_VALIDATION_ONLY=qmm,flash python scripts/tpu_validation.py
run sweep_r03b 2400 python scripts/sweep_r03b.py
run validate_moe 1500 env TPU_VALIDATION_ONLY=moe python scripts/tpu_validation.py
run bench_1b_q40_fused 900 env BENCH_PRESET=llama-1b BENCH_FORMAT=q40 python bench.py
run bench_moe_q40 1800 env BENCH_PRESET=qwen3-30b-a3b BENCH_FORMAT=q40 python bench.py
echo "=== capture done ==="
