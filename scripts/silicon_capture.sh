#!/bin/bash
# Serial real-TPU capture: bench presets then validation sweep.
# One TPU process at a time (the tunnel wedges under concurrency).
cd /root/repo
A=artifacts
for cfg in "llama-1b q40" "llama-1b dense" "llama-8b q40"; do
  set -- $cfg
  p=$1; f=$2
  echo "=== bench $p $f ===" 
  BENCH_PRESET=$p BENCH_FORMAT=$f timeout 1800 python bench.py \
    >"$A/bench_${p}_${f}.json" 2>"$A/bench_${p}_${f}.log"
  echo "exit=$? $(cat $A/bench_${p}_${f}.json)"
done
echo "=== tpu_validation ==="
timeout 2400 python scripts/tpu_validation.py >"$A/tpu_validation_r03.log" 2>&1
echo "exit=$?"
tail -30 "$A/tpu_validation_r03.log"
