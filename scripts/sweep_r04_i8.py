#!/usr/bin/env python
"""Round-4 silicon sweep: grouped-int8 MXU kernel vs the shipping Q40
kernel on the engine's REAL launch shapes (8B, post qkv/w13 fusion).

The r3 sweep showed the Q40 kernel is dequant-compute-bound (46% of HBM
peak); ops/int8_matmul.py moves the arithmetic to native int8 MXU dots.
This measures, per shape:

  * shipping Q40 kernel (bn=256, bk=4096 default),
  * grouped-int8 kernel across (group, bn, bk) neighborhoods,
  * XLA dense bf16 matvec (floor),

and prints ms/call + effective GB/s against each variant's actual HBM
bytes. Timing: differenced on-device fori_loop iteration counts (fixed
tunnel costs cancel; docs/silicon_r03.md "Measurement method").
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dllama_tpu.parallel.mesh import enable_compilation_cache, reassert_platform

reassert_platform()
enable_compilation_cache()

import jax
import jax.numpy as jnp
from jax import lax

from dllama_tpu.ops.int8_matmul import i8matmul_2d, quantize_acts
from dllama_tpu.ops.quant_matmul import qmatmul_2d

Q_BLOCK = 32

# the 8B fused decode shapes the engine actually launches (m=1), plus a
# lane batch
SHAPES = [
    ("qkv", 1, 4096, 6144),
    ("wo", 1, 4096, 4096),
    ("w13", 1, 4096, 28672),
    ("w2", 1, 14336, 4096),
    ("w13_m8", 8, 4096, 28672),
]

GROUPS = [256, 512, 1024]
BLOCKS = [(256, 4096), (512, 4096), (256, 2048), (512, 2048), (1024, 4096),
          (256, 8192)]


def timed_loop(step, args, n_iter: int):
    """ms/call via two differenced on-device fori_loop lengths.

    `step(it, *args)` must run the op `it` times under fori_loop. The
    operand arrays ride as jit ARGUMENTS (not closure constants) so XLA
    cannot constant-fold the computation away."""
    f = jax.jit(step, static_argnums=(0,))

    def run(n):
        out = f(n, *args)
        _ = np.asarray(jax.device_get(jnp.ravel(out)[0]))  # full sync
        t0 = time.perf_counter()
        out = f(n, *args)
        _ = np.asarray(jax.device_get(jnp.ravel(out)[0]))
        return time.perf_counter() - t0

    t_small = run(n_iter // 4)
    t_big = run(n_iter)
    return (t_big - t_small) * 1000.0 / (n_iter - n_iter // 4)


def main() -> None:
    print(f"devices: {jax.devices()}", flush=True)
    rng = np.random.default_rng(0)
    n_iter = int(os.environ.get("SWEEP_ITERS", "40"))

    for name, m, k, n in SHAPES:
        print(f"\n=== {name}: m={m} k={k} n={n} ===", flush=True)
        x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.3)
        q40_q = jnp.asarray(rng.integers(-8, 8, size=(k, n), dtype=np.int8))
        q40_d = jnp.asarray(
            (rng.random((k // Q_BLOCK, n)) * 0.02 + 0.01).astype(np.float32)
        )
        q40_bytes = k * n + (k // Q_BLOCK) * n * 4

        # floor: XLA dense bf16
        wd = jnp.asarray(
            (rng.standard_normal((k, n)) * 0.02).astype(np.float32)
        ).astype(jnp.bfloat16)
        xb = x.astype(jnp.bfloat16)

        def dense_step(it, xb, wd):
            def body(i, acc):
                o = jnp.dot(xb, wd, preferred_element_type=jnp.float32)
                return acc + o[0, 0]

            return lax.fori_loop(0, it, body, jnp.float32(0))

        try:
            ms = timed_loop(dense_step, (xb, wd), n_iter)
            gbs = 2.0 * k * n / ms / 1e6
            print(f"  dense-bf16-xla: {ms:8.3f} ms  {gbs:6.0f} GB/s", flush=True)
        except Exception as e:
            print(f"  dense-bf16-xla: {type(e).__name__}: {str(e)[:100]}")

        # shipping Q40 kernel
        for bn, bk in [(256, 4096), (512, 4096)]:
            bk = min(bk, k)
            if n % bn or k % bk:
                continue

            def q40_step(it, x, q, d, bn=bn, bk=bk):
                def body(i, acc):
                    o = qmatmul_2d(x, q, d, block_n=bn, block_k=bk)
                    return acc + o[0, 0]

                return lax.fori_loop(0, it, body, jnp.float32(0))

            try:
                ms = timed_loop(q40_step, (x, q40_q, q40_d), n_iter)
                gbs = q40_bytes / ms / 1e6
                print(
                    f"  q40 bn={bn} bk={bk}: {ms:8.3f} ms  {gbs:6.0f} GB/s",
                    flush=True,
                )
            except Exception as e:
                print(f"  q40 bn={bn} bk={bk}: {type(e).__name__}: {str(e)[:100]}")

        # grouped-int8 kernel
        for group in GROUPS:
            if k % group:
                continue
            qi = jnp.asarray(rng.integers(-127, 128, size=(k, n), dtype=np.int8))
            si = jnp.asarray(
                (rng.random((k // group, n)) * 0.001 + 0.001).astype(np.float32)
            )
            xq, sx = quantize_acts(x, group)
            xq = jax.device_put(xq)
            sx = jax.device_put(sx)
            i8_bytes = k * n + (k // group) * n * 4 + m * k + m * (k // group) * 4
            seen = set()
            for bn, bk in BLOCKS:
                bk = min(bk, k)
                if n % bn or k % bk or bk % group or (bn, bk) in seen:
                    continue
                seen.add((bn, bk))

                def i8_step(it, xq, sx, qi, si, bn=bn, bk=bk):
                    def body(i, acc):
                        o = i8matmul_2d(
                            xq, sx, qi, si, block_n=bn, block_k=bk
                        )
                        return acc + o[0, 0]

                    return lax.fori_loop(0, it, body, jnp.float32(0))

                try:
                    ms = timed_loop(i8_step, (xq, sx, qi, si), n_iter)
                    gbs = i8_bytes / ms / 1e6
                    print(
                        f"  i8 G={group} bn={bn} bk={bk}: {ms:8.3f} ms  "
                        f"{gbs:6.0f} GB/s",
                        flush=True,
                    )
                except Exception as e:
                    print(
                        f"  i8 G={group} bn={bn} bk={bk}: "
                        f"{type(e).__name__}: {str(e)[:100]}"
                    )


if __name__ == "__main__":
    main()
