"""Q40/Q80 codec tests.

Mirrors the reference's test strategy (SURVEY.md §4): golden bytes for the
serialized form (converter/writer-test.py) and quantize->dequantize roundtrip
tolerance (src/nn/nn-cpu-ops-test.cpp:87-104).
"""

import numpy as np
import pytest

from dllama_tpu.formats import (
    Q40_BLOCK_SIZE,
    quantize_q40,
    quantize_q80,
    dequantize_q40,
    dequantize_q80,
    q40_to_planar,
    q80_to_planar,
    tensor_bytes,
)
from dllama_tpu.formats.quants import FloatType, quantize_q80_values

# sub-minute CPU-only surface (codecs, tokenizer, native loader,
# interpret-mode kernel parity): the first CI lane runs `pytest -m fast`
pytestmark = pytest.mark.fast


# Golden hex of Q40(torch.manual_seed(1); torch.randn(32, 16)) — identical to
# the reference's converter/writer-test.py EXPECTED_OUTPUT.
GOLDEN_Q40_HEX = (
    "7e346345a692b89665b2c5790537876e598aaa366d988876a898b8d788a98868ce660c66f6b3a8"
    "8cba5ce9a871987ba9cc5bcaaa760c1eb556a4455b747b6b9504968828ef2a8d7c1db5c6be3764"
    "799e66db6d8e76463126a30e4333cad7a4f645947c6cf97f9de086d468c8d535a6ba7dc799d3d0"
    "c657bab6799468cad8bb349eb7d7635c7c798998696bb38e4085a9eb34444ba96a7f8ba7b2b42d"
    "746a96cf9660aeb4499d8708ad5c7b9a7558947645f3bbb6b0346a656887ad9a86059baac5c596"
    "ab781c703569bb8a4356a4bd58cb78736ba09759bb0e34a6274e827b957d7a67dfa86846955660"
    "d234b6d9d78a378094a8a8708a7a774ae92f8a36b8c999a9b77a7d958a69747c807963941235379"
    "886d69a7a8767b3a6a4ac71999760"
)


def test_q40_golden_bytes():
    torch = pytest.importorskip("torch")
    torch.manual_seed(1)
    x = torch.randn(32, 16).numpy()
    raw = quantize_q40(x)
    assert raw.tobytes().hex() == GOLDEN_Q40_HEX


def test_q40_roundtrip_tolerance():
    rng = np.random.default_rng(12345)
    x = rng.standard_normal(4096).astype(np.float32)
    raw = quantize_q40(x)
    y = dequantize_q40(raw, x.size)
    # Reference tolerance model: 4-bit asymmetric, error bounded by the scale.
    scales = np.abs(x.reshape(-1, Q40_BLOCK_SIZE)).max(axis=1) / 8.0
    err = np.abs(x - y).reshape(-1, Q40_BLOCK_SIZE)
    assert (err <= scales[:, None] * 1.01 + 1e-6).all()


def test_q80_roundtrip_tight():
    rng = np.random.default_rng(7)
    x = rng.standard_normal(2048).astype(np.float32)
    raw = quantize_q80(x)
    y = dequantize_q80(raw, x.size)
    scales = np.abs(x.reshape(-1, 32)).max(axis=1) / 127.0
    err = np.abs(x - y).reshape(-1, 32)
    # 0.5 ulp of the int8 round + fp16 rounding of the stored scale
    # (quantization divides by the f32 scale, dequant multiplies by its
    # fp16-rounded value — same asymmetry as the reference writer).
    assert (err <= scales[:, None] * (0.5 + 127 * 2**-11) + 1e-7).all()


def test_q40_planar_matches_dequant():
    rng = np.random.default_rng(3)
    x = rng.standard_normal(1024).astype(np.float32)
    raw = quantize_q40(x)
    q, d = q40_to_planar(raw, x.size)
    assert q.dtype == np.int8 and d.dtype == np.float16
    assert q.min() >= -8 and q.max() <= 7
    manual = (q.reshape(-1, 32).astype(np.float32) * d.astype(np.float32)[:, None]).reshape(-1)
    np.testing.assert_allclose(manual, dequantize_q40(raw, x.size), rtol=0, atol=0)


def test_q80_planar_matches_dequant():
    rng = np.random.default_rng(4)
    x = rng.standard_normal(1024).astype(np.float32)
    raw = quantize_q80(x)
    q, d = q80_to_planar(raw, x.size)
    manual = (q.reshape(-1, 32).astype(np.float32) * d.astype(np.float32)[:, None]).reshape(-1)
    np.testing.assert_allclose(manual, dequantize_q80(raw, x.size), rtol=0, atol=0)


def test_q80_values_roundtrip():
    rng = np.random.default_rng(5)
    x = rng.standard_normal(256).astype(np.float32)
    q, d = quantize_q80_values(x)
    y = (q.reshape(-1, 32).astype(np.float32) * d.astype(np.float32)[:, None]).reshape(-1)
    assert np.abs(x - y).max() < np.abs(x).max() / 64


def test_tensor_bytes():
    assert tensor_bytes(FloatType.F32, 64) == 256
    assert tensor_bytes(FloatType.F16, 64) == 128
    assert tensor_bytes(FloatType.Q40, 64) == 2 * 18
    assert tensor_bytes(FloatType.Q80, 64) == 2 * 34


def test_q40_zero_block():
    x = np.zeros(32, dtype=np.float32)
    raw = quantize_q40(x)
    np.testing.assert_array_equal(dequantize_q40(raw, 32), x)
