"""Shared test fixtures: synthetic tiny models written in the real `.m`/`.t`
wire formats, so the whole read path (header parse -> tensor plan -> dequant)
is exercised exactly as it is for real checkpoints."""

from __future__ import annotations

import os

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from dllama_tpu.formats import FloatType
from dllama_tpu.formats.model_file import LlmArch
from dllama_tpu.formats.tokenizer_file import TokenizerData, write_tokenizer
from dllama_tpu.formats.writer import write_header, write_tensor

TINY = dict(
    dim=64,
    hidden_dim=160,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    head_dim=16,
    vocab_size=256,
    seq_len=64,
)

TINY_MOE = dict(
    dim=64,
    hidden_dim=160,
    moe_hidden_dim=96,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    head_dim=16,
    vocab_size=256,
    seq_len=64,
    n_experts=4,
    n_active_experts=2,
)


def make_tiny_model(
    path,
    arch: LlmArch = LlmArch.LLAMA,
    weight_type: FloatType = FloatType.Q40,
    seed: int = 0,
    cfg: dict | None = None,
    rope_scaling: bool = False,
) -> dict[str, np.ndarray]:
    """Write a tiny random model to `path`; returns the exact f32 tensors
    (pre-quantization) keyed by plan name."""
    if cfg is None:
        cfg = dict(TINY_MOE if arch == LlmArch.QWEN3_MOE else TINY)
    rng = np.random.default_rng(seed)
    d = cfg["dim"]
    hd = cfg["head_dim"]
    q_dim = hd * cfg["n_heads"]
    kv_dim = hd * cfg["n_kv_heads"]
    n_experts = cfg.get("n_experts", 0)
    ff = cfg["moe_hidden_dim"] if arch == LlmArch.QWEN3_MOE else cfg["hidden_dim"]

    params = {
        "version": 0,
        "arch_type": int(arch),
        "dim": d,
        "hidden_dim": cfg["hidden_dim"],
        "n_layers": cfg["n_layers"],
        "n_heads": cfg["n_heads"],
        "n_kv_heads": cfg["n_kv_heads"],
        "n_experts": n_experts,
        "n_active_experts": cfg.get("n_active_experts", 0),
        "vocab_size": cfg["vocab_size"],
        "max_seq_len": cfg["seq_len"],
        "hidden_act": 1,  # silu
        "rope_theta": 10000,
        "weights_float_type": int(weight_type),
        "head_dim": hd,
        "norm_epsilon": 5,
    }
    if arch == LlmArch.QWEN3_MOE:
        params["moe_hidden_dim"] = cfg["moe_hidden_dim"]
    if rope_scaling:
        params.update(
            rope_type=2,  # llama3.1
            rope_scaling_factor=8,
            rope_scaling_low_freq_factor=1,
            rope_scaling_high_freq_factory=4,
            rope_scaling_orig_max_seq_len=cfg["seq_len"] // 2,
        )

    def t(*shape):
        return (rng.standard_normal(shape) * 0.08).astype(np.float32)

    tensors: dict[str, tuple[np.ndarray, FloatType]] = {}

    def add(name, arr, ft):
        tensors[name] = (arr, ft)

    wt = weight_type
    add("embed", t(cfg["vocab_size"], d), FloatType.F32)
    for l in range(cfg["n_layers"]):
        add(f"layers.{l}.q", t(q_dim, d), wt)
        add(f"layers.{l}.k", t(kv_dim, d), wt)
        add(f"layers.{l}.v", t(kv_dim, d), wt)
        add(f"layers.{l}.wo", t(d, q_dim), wt)
        if n_experts > 0:
            add(f"layers.{l}.moe_gate", t(n_experts, d), FloatType.F32)
            for e in range(n_experts):
                add(f"layers.{l}.experts.{e}.w1", t(ff, d), wt)
                add(f"layers.{l}.experts.{e}.w2", t(d, ff), wt)
                add(f"layers.{l}.experts.{e}.w3", t(ff, d), wt)
        else:
            add(f"layers.{l}.w1", t(ff, d), wt)
            add(f"layers.{l}.w2", t(d, ff), wt)
            add(f"layers.{l}.w3", t(ff, d), wt)
        if arch in (LlmArch.QWEN3, LlmArch.QWEN3_MOE):
            add(f"layers.{l}.q_norm", 1.0 + t(hd), FloatType.F32)
            add(f"layers.{l}.k_norm", 1.0 + t(hd), FloatType.F32)
        add(f"layers.{l}.att_norm", 1.0 + t(d), FloatType.F32)
        add(f"layers.{l}.ffn_norm", 1.0 + t(d), FloatType.F32)
    add("final_norm", 1.0 + t(d), FloatType.F32)
    add("wcls", t(cfg["vocab_size"], d), wt)

    with open(path, "wb") as f:
        write_header(f, params)
        for name, (arr, ft) in tensors.items():
            write_tensor(f, arr, ft)

    return {name: arr for name, (arr, ft) in tensors.items()}


def make_tiny_tokenizer(
    path, chat_template: str | None = None, pad_to: int = 0
) -> TokenizerData:
    """A tiny byte-level tokenizer: 256 single-byte regular tokens, then a few
    merged tokens, then specials. Regular/special split at bos_id, matching
    the reference layout assumption (src/tokenizer.cpp:138-140)."""
    vocab: list[bytes] = [bytes([i]) for i in range(256)]
    scores: list[float] = [0.0] * 256
    merges = [
        b"he", b"ll", b"llo", b"hello",
        b" w", b" wo", b" wor", b" worl", b" world",
        b"hi", b"th", b"the",
    ]
    for i, m in enumerate(merges):
        vocab.append(m)
        scores.append(float(i + 1))
    specials = [b"<s>", b"</s>", b"<|eot|>"]
    # pad the regular vocab so tokenizer size can match a model's vocab
    # (reference decode indexes vocab[token] for any sampled id)
    if pad_to:
        assert pad_to >= len(vocab) + len(specials), (pad_to, len(vocab))
        while len(vocab) < pad_to - len(specials):
            vocab.append(f"<pad{len(vocab)}>".encode())
            scores.append(0.0)
    bos_id = len(vocab)
    for s in specials:
        vocab.append(s)
        scores.append(0.0)
    if pad_to:
        assert len(vocab) == pad_to, (len(vocab), pad_to)
    data = TokenizerData(
        vocab=vocab,
        scores=scores,
        bos_id=bos_id,
        add_bos=True,
        eos_token_ids=[bos_id + 1, bos_id + 2],
        chat_template=chat_template,
        max_token_length=max(len(v) for v in vocab),
    )
    write_tokenizer(path, data)
    return data
