"""bench.py emission-path guards.

A tunnel outage must never produce a record that pattern-matches a real
perf datapoint: on CPU fallback the headline's `vs_baseline` is null and
`comparable` is false (VERDICT r4 weak #2). The raw value is kept, with
the honest `_cpu_fallback` metric suffix.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bench import NORTH_STAR_TOK_S_PER_CHIP, headline_record


def test_fallback_record_suppresses_ratio():
    rec = headline_record(
        "tiny", "q40", "bf16", per_chip=2374.3, weight_gbs=0.3, fallback=True
    )
    assert rec["metric"] == "decode_tok_s_per_chip_tiny_q40_cpu_fallback"
    assert rec["vs_baseline"] is None
    assert rec["comparable"] is False
    assert rec["value"] == 2374.3  # raw number stays, honestly labeled


def test_real_record_carries_ratio():
    rec = headline_record(
        "llama-8b", "q40i8", "int8", per_chip=55.0, weight_gbs=600.0,
        fallback=False,
    )
    assert rec["metric"] == "decode_tok_s_per_chip_llama_8b_q40i8_kv8"
    assert rec["comparable"] is True
    assert rec["vs_baseline"] == round(55.0 / NORTH_STAR_TOK_S_PER_CHIP, 3)


def test_bench_summaries_section_split():
    from bench import bench_summaries

    result = {
        "metric": "decode_tok_s_per_chip_tiny_q40",
        "value": 12.3, "unit": "tokens/s/chip", "vs_baseline": 0.25,
        "comparable": True, "weight_gbs_per_chip": 100.0,
        "step_ms": {"block_tokens": 64, "n_blocks": 5, "p50": 10.0,
                    "p90": 12.0, "max": 13.0, "per_token_p50": 0.156},
        "ttft_ms_p50": 42.5,
        "lanes4_tok_s_per_chip": 30.0,
        "format_sweep_tok_s_per_chip": {"q40": 12.3, "q40i8": 14.0},
        "serving": {"n_clients": 3, "ttft_ms_p50": 50.0,
                    "obs_overhead_pct": 0.4},
    }
    out = bench_summaries(result)
    assert set(out) == {"DECODE", "TTFT", "LANES", "SWEEP", "SERVING"}
    assert out["DECODE"]["value"] == 12.3
    assert out["DECODE"]["step_ms"]["p90"] == 12.0
    assert out["TTFT"]["ttft_ms_p50"] == 42.5
    assert out["LANES"]["lanes4_tok_s_per_chip"] == 30.0
    assert out["SWEEP"]["tok_s_per_chip"]["q40i8"] == 14.0
    assert out["SERVING"]["obs_overhead_pct"] == 0.4


def test_bench_summaries_only_sections_that_ran():
    from bench import bench_summaries

    out = bench_summaries({
        "metric": "decode_tok_s_per_chip_tiny_q40_cpu_fallback",
        "value": 1.0, "unit": "tokens/s/chip", "vs_baseline": None,
        "comparable": False,
    })
    assert set(out) == {"DECODE"}  # skipped sections leave no stale files
    assert bench_summaries({}) == {}


def test_write_bench_summaries_files(tmp_path):
    import json

    from bench import write_bench_summaries

    result = {"metric": "m", "value": 1.0, "unit": "tokens/s/chip",
              "vs_baseline": None, "comparable": False,
              "ttft_ms_p50": 9.0}
    paths = write_bench_summaries(result, out_dir=str(tmp_path))
    assert sorted(p.split("/")[-1] for p in paths) == [
        "BENCH_DECODE.json", "BENCH_TTFT.json",
    ]
    decode = json.loads((tmp_path / "BENCH_DECODE.json").read_text())
    assert decode["metric"] == "m" and decode["comparable"] is False
    # unwritable destination degrades to a logged skip, never a crash
    assert write_bench_summaries(result, out_dir=str(tmp_path / "no" / "x")) == []
