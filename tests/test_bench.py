"""bench.py emission-path guards.

A tunnel outage must never produce a record that pattern-matches a real
perf datapoint: on CPU fallback the headline's `vs_baseline` is null and
`comparable` is false (VERDICT r4 weak #2). The raw value is kept, with
the honest `_cpu_fallback` metric suffix.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bench import NORTH_STAR_TOK_S_PER_CHIP, headline_record


def test_fallback_record_suppresses_ratio():
    rec = headline_record(
        "tiny", "q40", "bf16", per_chip=2374.3, weight_gbs=0.3, fallback=True
    )
    assert rec["metric"] == "decode_tok_s_per_chip_tiny_q40_cpu_fallback"
    assert rec["vs_baseline"] is None
    assert rec["comparable"] is False
    assert rec["value"] == 2374.3  # raw number stays, honestly labeled


def test_real_record_carries_ratio():
    rec = headline_record(
        "llama-8b", "q40i8", "int8", per_chip=55.0, weight_gbs=600.0,
        fallback=False,
    )
    assert rec["metric"] == "decode_tok_s_per_chip_llama_8b_q40i8_kv8"
    assert rec["comparable"] is True
    assert rec["vs_baseline"] == round(55.0 / NORTH_STAR_TOK_S_PER_CHIP, 3)
