"""API server tests: OpenAI-compatible surface over a tiny model."""

import json
import threading
import urllib.error
import urllib.request

import jax.numpy as jnp
import pytest

from dllama_tpu.formats import FloatType
from dllama_tpu.runtime.api_server import ApiState, NaiveCache, ChatMessage, serve
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.tokenizer import Tokenizer

from helpers import make_tiny_model, make_tiny_tokenizer


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    d = tmp_path_factory.mktemp("api")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3
    )
    srv = serve(engine, tok, host="127.0.0.1", port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    return urllib.request.urlopen(req, timeout=300)


def test_models_endpoint(server):
    with urllib.request.urlopen(server + "/v1/models") as r:
        data = json.loads(r.read())
    assert data["object"] == "list"
    assert data["data"][0]["object"] == "model"


def test_chat_completion(server):
    with _post(
        server,
        {
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 8,
            "temperature": 0,
        },
    ) as r:
        data = json.loads(r.read())
    assert data["object"] == "chat.completion"
    choice = data["choices"][0]
    assert choice["message"]["role"] == "assistant"
    # random tiny model almost never emits EOS within 8 tokens -> "length"
    assert choice["finish_reason"] in ("stop", "length")
    usage = data["usage"]
    assert usage["prompt_tokens"] > 0
    assert usage["total_tokens"] == usage["prompt_tokens"] + usage["completion_tokens"]
    assert usage["completion_tokens"] <= 8


def test_chat_completion_streaming(server):
    with _post(
        server,
        {
            "messages": [{"role": "user", "content": "hello world"}],
            "max_tokens": 6,
            "temperature": 0,
            "stream": True,
        },
    ) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        raw = r.read().decode()
    events = [
        json.loads(line[len("data: "):])
        for line in raw.splitlines()
        if line.startswith("data: ") and line != "data: [DONE]"
    ]
    assert raw.rstrip().endswith("data: [DONE]")
    assert events, "no SSE chunks"
    # max_tokens truncation on the random model reports "length" (stream
    # now mirrors the non-stream finish_reason)
    assert events[-1]["choices"][0]["finish_reason"] in ("stop", "length")
    for e in events[:-1]:
        assert e["object"] == "chat.completion.chunk"
        assert e["choices"][0]["delta"]["role"] == "assistant"


def test_naive_cache_reuses_prefix(server):
    msgs = [{"role": "user", "content": "first question"}]
    with _post(server, {"messages": msgs, "max_tokens": 4, "temperature": 0}) as r:
        first = json.loads(r.read())
    reply = first["choices"][0]["message"]["content"]
    msgs2 = msgs + [
        {"role": "assistant", "content": reply},
        {"role": "user", "content": "second question"},
    ]
    with _post(server, {"messages": msgs2, "max_tokens": 4, "temperature": 0}) as r:
        second = json.loads(r.read())
    # prefix reuse: the second request's prompt covers only the delta
    # (assistant echo + new user message), strictly fewer tokens than a
    # full re-encode of the 3-message conversation would need; the first
    # 1-message prompt is the lower bound that a full re-encode must exceed
    assert second["usage"]["prompt_tokens"] < first["usage"]["prompt_tokens"] + 40
    assert second["choices"][0]["message"]["role"] == "assistant"


def test_seed_param_deterministic(server):
    payload = {
        "messages": [{"role": "user", "content": "tell me"}],
        "max_tokens": 6,
        "temperature": 0.9,
        "seed": 42,
    }
    with _post(server, payload) as r:
        a = json.loads(r.read())["choices"][0]["message"]["content"]
    with _post(server, payload) as r:
        b = json.loads(r.read())["choices"][0]["message"]["content"]
    assert a == b


def test_not_found(server):
    try:
        urllib.request.urlopen(server + "/nope", timeout=30)
        assert False
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_bad_request(server):
    try:
        _post(server, {"no_messages": True})
        assert False
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_naive_cache_unit():
    c = NaiveCache()
    m1 = ChatMessage("user", "a")
    c.push(type("I", (), {"end_pos": 5, "message": m1})())
    msgs, pos = c.resolve_delta_prompt([m1, ChatMessage("user", "b")])
    assert pos == 5
    assert len(msgs) == 1 and msgs[0].content == "b"
    # mismatch clears
    msgs, pos = c.resolve_delta_prompt([ChatMessage("user", "x"), ChatMessage("user", "y")])
    assert pos == 0 and len(msgs) == 2
    assert c.items == []


def test_stop_as_string_and_mismatched_count(server):
    # OpenAI allows `stop` as a bare string; also more stops than eos ids
    with _post(
        server,
        {
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 4,
            "temperature": 0,
            "stop": "###",
        },
    ) as r:
        assert json.loads(r.read())["object"] == "chat.completion"
    with _post(
        server,
        {
            "messages": [{"role": "user", "content": "hi again"}],
            "max_tokens": 4,
            "temperature": 0,
            "stop": ["###", "END", "@@@"],
        },
    ) as r:
        assert json.loads(r.read())["object"] == "chat.completion"


def test_stream_error_still_terminates(server):
    # a prompt that overflows seq_len raises inside complete(); the SSE
    # stream must still deliver an error payload and [DONE]
    big = "x" * 4000
    with _post(
        server,
        {
            "messages": [{"role": "user", "content": big}],
            "stream": True,
        },
    ) as r:
        raw = r.read().decode()
    assert '"error"' in raw
    assert raw.rstrip().endswith("data: [DONE]")


@pytest.fixture(scope="module")
def lane_server(tmp_path_factory):
    """batch_size > 1 engine -> the LaneScheduler concurrent path."""
    d = tmp_path_factory.mktemp("api_lanes")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=3,
    )
    srv = serve(engine, tok, host="127.0.0.1", port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def test_lane_server_concurrent_requests(server, lane_server):
    """Three simultaneous greedy requests through the lane scheduler must
    each reproduce the single-lane server's answer for the same prompt
    (same tiny model in both fixtures)."""
    prompts = ["hello", "the quick brown", "zebra"]

    def single(prompt):
        with _post(server, {
            "messages": [{"role": "user", "content": prompt}],
            "max_tokens": 10, "temperature": 0,
        }) as r:
            return json.loads(r.read())["choices"][0]["message"]["content"]

    expected = [single(p) for p in prompts]

    results = [None] * len(prompts)
    errors = []

    def worker(i):
        try:
            with _post(lane_server, {
                "messages": [{"role": "user", "content": prompts[i]}],
                "max_tokens": 10, "temperature": 0,
            }) as r:
                results[i] = json.loads(r.read())["choices"][0]["message"]["content"]
        except Exception as e:  # pragma: no cover
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors
    assert results == expected, (results, expected)


def test_lane_server_streaming(lane_server):
    """SSE streaming through the scheduler path terminates with [DONE]."""
    req = urllib.request.Request(
        lane_server + "/v1/chat/completions",
        data=json.dumps({
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 6, "temperature": 0, "stream": True,
        }).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        body = r.read().decode()
    assert "data: [DONE]" in body
    assert '"finish_reason"' in body


def test_lane_server_conversation_affinity(lane_server):
    """A continuing conversation is routed back to its lane and resumes
    from the cached prefix (per-lane NaiveCache): turn 2 must produce a
    normal completion, and a concurrent unrelated request must not
    disturb it."""
    def ask(messages):
        with _post(lane_server, {
            "messages": messages, "max_tokens": 8, "temperature": 0,
        }) as r:
            body = json.loads(r.read())
        return (body["choices"][0]["message"]["content"],
                body["usage"]["prompt_tokens"])

    convo = [{"role": "user", "content": "tell me a story"}]
    a1, _ = ask(convo)
    # interleave an unrelated request (occupies some lane)
    ask([{"role": "user", "content": "unrelated"}])
    convo += [{"role": "assistant", "content": a1},
              {"role": "user", "content": "continue"}]
    a2, n2 = ask(convo)
    # same-shape conversation with a different opening -> no cache match,
    # full render; the matched continuation must have prefilled fewer
    # tokens (just the delta + pending token)
    fresh = [dict(convo[0], content="a different opening line"),
             convo[1], convo[2]]
    _, n_full = ask(fresh)
    assert n2 < n_full, (n2, n_full)
    # the conversation keeps extending through its lane cache: the third
    # turn's delta must be smaller than the second turn's full-render
    # equivalent even though the conversation got longer
    convo += [{"role": "assistant", "content": a2},
              {"role": "user", "content": "more"}]
    a3, n3 = ask(convo)
    assert isinstance(a3, str) and n3 < n_full, (n3, n_full)


def test_api_main_chat_template_flag(tmp_path):
    """--chat-template forces the template type even when the tokenizer
    carries a different/absent jinja template."""
    import subprocess
    import sys
    import os as _os
    from helpers import REPO_ROOT, make_tiny_model, make_tiny_tokenizer

    mp = str(tmp_path / "m.m")
    tp = str(tmp_path / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, cfg=cfg)
    make_tiny_tokenizer(tp, pad_to=288)  # no chat template in the file
    import socket

    with socket.socket() as s0:
        s0.bind(("127.0.0.1", 0))
        port = s0.getsockname()[1]
    log_path = tmp_path / "server.log"
    with open(log_path, "wb") as log:
        proc = subprocess.Popen(
            [sys.executable, "-m", "dllama_tpu.runtime.api_server",
             "--model", mp, "--tokenizer", tp, "--port", str(port),
             "--host", "127.0.0.1", "--tp", "1", "--dtype", "f32",
             "--temperature", "0", "--chat-template", "chatml"],
            env={**_os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=REPO_ROOT,
            stdout=log, stderr=subprocess.STDOUT,
        )
    try:
        import time as _t
        import urllib.request

        deadline = _t.time() + 120
        while _t.time() < deadline:
            if proc.poll() is not None:
                raise AssertionError(
                    f"server exited rc={proc.returncode}:\n"
                    + log_path.read_text()[-1000:]
                )
            try:
                urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=2)
                break
            except Exception:
                _t.sleep(1)
        else:
            raise AssertionError(
                "server did not come up:\n" + log_path.read_text()[-1000:]
            )
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/chat/completions",
            data=json.dumps({"messages": [{"role": "user", "content": "hi"}],
                             "max_tokens": 3, "temperature": 0}).encode(),
            headers={"Content-Type": "application/json"},
        )
        data = json.loads(urllib.request.urlopen(req, timeout=120).read())
        assert data["object"] == "chat.completion"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_lane_server_seed_warning(lane_server):
    """A `seed` under the lane scheduler cannot be honored (shared
    on-device RNG across lanes); the response must SAY so instead of
    silently returning non-reproducible output (ADVICE r2 #3)."""
    with _post(lane_server, {
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 4, "temperature": 0, "seed": 42,
    }) as r:
        body = json.loads(r.read())
    assert "warning" in body and "seed" in body["warning"], body
    # no seed -> no warning
    with _post(lane_server, {
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 4, "temperature": 0,
    }) as r:
        body = json.loads(r.read())
    assert "warning" not in body, body


def test_chat_completion_q40_fused_engine(tmp_path):
    """The serving path over a weight_format='q40' engine (which fuses
    wqkv/w13 by default) must produce the same completion as the dense
    engine for a greedy request — server x fusion x NaiveCache in one
    pass."""
    mp, tp_ = str(tmp_path / "m.m"), str(tmp_path / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")

    payload = {
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 8,
        "temperature": 0,
    }
    outs = {}
    for fmt in ("q40", "dense"):
        tok = Tokenizer(tp_)
        engine = InferenceEngine(
            mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0,
            seed=3, weight_format=fmt,
        )
        if fmt == "q40":
            assert "wqkv" in engine.params["layers"]
            assert "w13" in engine.params["layers"]
        srv = serve(engine, tok, host="127.0.0.1", port=0)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            with _post(url, payload) as r:
                outs[fmt] = json.loads(r.read())["choices"][0]["message"]
        finally:
            srv.shutdown()
    assert outs["q40"] == outs["dense"], outs


def test_single_stream_crash_recovery(tmp_path):
    """VERDICT r4 item 7: an injected engine error mid-request yields a
    500, the donated KV cache and the stale NaiveCache entries are
    dropped (cache epoch moved), and the next request succeeds."""
    mp, tp_ = str(tmp_path / "m.m"), str(tmp_path / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3
    )
    srv = serve(engine, tok, host="127.0.0.1", port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    payload = {
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 6,
        "temperature": 0,
    }
    try:
        # 1. clean request works
        with _post(url, payload) as r:
            ok1 = json.loads(r.read())
        assert ok1["choices"][0]["message"]["content"] is not None

        # 2. poison the next dispatch: donate the cache, then fail
        real = engine._decode_block_fn

        def poisoned(n_steps, greedy, window=0):
            block = real(n_steps, greedy, window)

            def bad(params, token, cache, pos, rng, temp, topp):
                block(params, token, cache, pos, rng, temp, topp)
                raise RuntimeError("injected dispatch failure")

            return bad

        engine._decode_block_fn = poisoned
        epoch0 = engine.cache_epoch
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(url, payload).read()
        assert exc.value.code == 500
        assert "injected" in json.loads(exc.value.read())["error"]["message"]
        engine._decode_block_fn = real
        assert engine.cache_epoch > epoch0

        # 3. next request (same conversation prefix) succeeds and matches
        #    the clean run — nothing resumed from poisoned state
        with _post(url, payload) as r:
            ok2 = json.loads(r.read())
        assert (
            ok2["choices"][0]["message"]["content"]
            == ok1["choices"][0]["message"]["content"]
        )
    finally:
        srv.shutdown()


def test_chat_completion_q40i8_kv8_engine(tmp_path):
    """Serving over the maximum-headroom decode configuration (grouped-
    int8 weights + int8 KV cache): a greedy request completes and is
    reproducible across two identical requests (NaiveCache prefix path
    included). Hidden dims sized for the q40i8 group divisibility."""
    mp, tp_ = str(tmp_path / "m8.m"), str(tmp_path / "t.t")
    cfg = dict(dim=64, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0,
        seed=3, weight_format="q40i8", kv_dtype="int8",
    )
    assert engine.i8_group >= 32
    srv = serve(engine, tok, host="127.0.0.1", port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    payload = {
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 8,
        "temperature": 0,
    }
    try:
        with _post(url, payload) as r:
            one = json.loads(r.read())["choices"][0]["message"]["content"]
        with _post(url, payload) as r:
            two = json.loads(r.read())["choices"][0]["message"]["content"]
        assert one == two and isinstance(one, str)
    finally:
        srv.shutdown()
