"""API server tests: OpenAI-compatible surface over a tiny model."""

import json
import re
import socket
import struct
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import pytest

from dllama_tpu.formats import FloatType
from dllama_tpu.runtime.api_server import ApiState, NaiveCache, ChatMessage, serve
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.tokenizer import Tokenizer

from helpers import make_tiny_model, make_tiny_tokenizer


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    d = tmp_path_factory.mktemp("api")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3
    )
    srv = serve(engine, tok, host="127.0.0.1", port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    return urllib.request.urlopen(req, timeout=300)


def test_models_endpoint(server):
    with urllib.request.urlopen(server + "/v1/models") as r:
        data = json.loads(r.read())
    assert data["object"] == "list"
    assert data["data"][0]["object"] == "model"


def test_chat_completion(server):
    with _post(
        server,
        {
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 8,
            "temperature": 0,
        },
    ) as r:
        data = json.loads(r.read())
    assert data["object"] == "chat.completion"
    choice = data["choices"][0]
    assert choice["message"]["role"] == "assistant"
    # random tiny model almost never emits EOS within 8 tokens -> "length"
    assert choice["finish_reason"] in ("stop", "length")
    usage = data["usage"]
    assert usage["prompt_tokens"] > 0
    assert usage["total_tokens"] == usage["prompt_tokens"] + usage["completion_tokens"]
    assert usage["completion_tokens"] <= 8


def test_chat_completion_streaming(server):
    with _post(
        server,
        {
            "messages": [{"role": "user", "content": "hello world"}],
            "max_tokens": 6,
            "temperature": 0,
            "stream": True,
        },
    ) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        raw = r.read().decode()
    events = [
        json.loads(line[len("data: "):])
        for line in raw.splitlines()
        if line.startswith("data: ") and line != "data: [DONE]"
    ]
    assert raw.rstrip().endswith("data: [DONE]")
    assert events, "no SSE chunks"
    # max_tokens truncation on the random model reports "length" (stream
    # now mirrors the non-stream finish_reason)
    assert events[-1]["choices"][0]["finish_reason"] in ("stop", "length")
    for e in events[:-1]:
        assert e["object"] == "chat.completion.chunk"
        assert e["choices"][0]["delta"]["role"] == "assistant"


def test_naive_cache_reuses_prefix(server):
    msgs = [{"role": "user", "content": "first question"}]
    with _post(server, {"messages": msgs, "max_tokens": 4, "temperature": 0}) as r:
        first = json.loads(r.read())
    reply = first["choices"][0]["message"]["content"]
    msgs2 = msgs + [
        {"role": "assistant", "content": reply},
        {"role": "user", "content": "second question"},
    ]
    with _post(server, {"messages": msgs2, "max_tokens": 4, "temperature": 0}) as r:
        second = json.loads(r.read())
    # prefix reuse: the second request's prompt covers only the delta
    # (assistant echo + new user message), strictly fewer tokens than a
    # full re-encode of the 3-message conversation would need; the first
    # 1-message prompt is the lower bound that a full re-encode must exceed
    assert second["usage"]["prompt_tokens"] < first["usage"]["prompt_tokens"] + 40
    assert second["choices"][0]["message"]["role"] == "assistant"


def test_seed_param_deterministic(server):
    payload = {
        "messages": [{"role": "user", "content": "tell me"}],
        "max_tokens": 6,
        "temperature": 0.9,
        "seed": 42,
    }
    with _post(server, payload) as r:
        a = json.loads(r.read())["choices"][0]["message"]["content"]
    with _post(server, payload) as r:
        b = json.loads(r.read())["choices"][0]["message"]["content"]
    assert a == b


def test_not_found(server):
    try:
        urllib.request.urlopen(server + "/nope", timeout=30)
        assert False
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_bad_request(server):
    try:
        _post(server, {"no_messages": True})
        assert False
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_naive_cache_unit():
    c = NaiveCache()
    m1 = ChatMessage("user", "a")
    c.push(type("I", (), {"end_pos": 5, "message": m1})())
    msgs, pos = c.resolve_delta_prompt([m1, ChatMessage("user", "b")])
    assert pos == 5
    assert len(msgs) == 1 and msgs[0].content == "b"
    # mismatch clears
    msgs, pos = c.resolve_delta_prompt([ChatMessage("user", "x"), ChatMessage("user", "y")])
    assert pos == 0 and len(msgs) == 2
    assert c.items == []


def test_stop_as_string_and_mismatched_count(server):
    # OpenAI allows `stop` as a bare string; also more stops than eos ids
    with _post(
        server,
        {
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 4,
            "temperature": 0,
            "stop": "###",
        },
    ) as r:
        assert json.loads(r.read())["object"] == "chat.completion"
    with _post(
        server,
        {
            "messages": [{"role": "user", "content": "hi again"}],
            "max_tokens": 4,
            "temperature": 0,
            "stop": ["###", "END", "@@@"],
        },
    ) as r:
        assert json.loads(r.read())["object"] == "chat.completion"


def test_stream_error_still_terminates(server):
    # a prompt that overflows seq_len raises inside complete(); the SSE
    # stream must still deliver an error payload and [DONE]
    big = "x" * 4000
    with _post(
        server,
        {
            "messages": [{"role": "user", "content": big}],
            "stream": True,
        },
    ) as r:
        raw = r.read().decode()
    assert '"error"' in raw
    assert raw.rstrip().endswith("data: [DONE]")


@pytest.fixture(scope="module")
def lane_server(tmp_path_factory):
    """batch_size > 1 engine -> the LaneScheduler concurrent path."""
    d = tmp_path_factory.mktemp("api_lanes")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=3,
    )
    srv = serve(engine, tok, host="127.0.0.1", port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def test_lane_server_concurrent_requests(server, lane_server):
    """Three simultaneous greedy requests through the lane scheduler must
    each reproduce the single-lane server's answer for the same prompt
    (same tiny model in both fixtures)."""
    prompts = ["hello", "the quick brown", "zebra"]

    def single(prompt):
        with _post(server, {
            "messages": [{"role": "user", "content": prompt}],
            "max_tokens": 10, "temperature": 0,
        }) as r:
            return json.loads(r.read())["choices"][0]["message"]["content"]

    expected = [single(p) for p in prompts]

    results = [None] * len(prompts)
    errors = []

    def worker(i):
        try:
            with _post(lane_server, {
                "messages": [{"role": "user", "content": prompts[i]}],
                "max_tokens": 10, "temperature": 0,
            }) as r:
                results[i] = json.loads(r.read())["choices"][0]["message"]["content"]
        except Exception as e:  # pragma: no cover
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors
    assert results == expected, (results, expected)


def test_lane_server_streaming(lane_server):
    """SSE streaming through the scheduler path terminates with [DONE]."""
    req = urllib.request.Request(
        lane_server + "/v1/chat/completions",
        data=json.dumps({
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 6, "temperature": 0, "stream": True,
        }).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        body = r.read().decode()
    assert "data: [DONE]" in body
    assert '"finish_reason"' in body


def test_lane_server_conversation_continuation_reuses_prefix(lane_server):
    """A continuing conversation reuses its stored prefix from the shared
    radix pool — on WHATEVER lane it lands (PR6 replaced per-lane
    NaiveCache affinity with cross-lane paged-KV sharing): turn 2 must
    report reused_prefix_tokens > 0 even with an unrelated request
    interleaved, and keep reusing as the conversation extends."""
    def ask(messages):
        # max_tokens kept tiny: the random model's replies re-encode
        # verbosely, and the fully-retokenized turn-3 conversation must
        # stay inside the tiny model's seq_len
        with _post(lane_server, {
            "messages": messages, "max_tokens": 4, "temperature": 0,
        }) as r:
            body = json.loads(r.read())
        return (body["choices"][0]["message"]["content"],
                body["dllama"]["reused_prefix_tokens"],
                body["dllama"]["lane"])

    convo = [{"role": "user", "content": "tell me a story"}]
    a1, _, lane1 = ask(convo)
    # interleave an unrelated request (occupies some lane, publishes its
    # own prefix — must not disturb the conversation's stored pages)
    ask([{"role": "user", "content": "unrelated"}])
    convo += [{"role": "assistant", "content": a1},
              {"role": "user", "content": "continue"}]
    a2, reused2, lane2 = ask(convo)
    # the turn-2 render begins with turn 1's fed tokens: the radix match
    # must cover at least one page of them
    assert reused2 > 0, (reused2, lane1, lane2)
    # the conversation keeps extending through the shared pool: turn 3
    # reuses at least as much as turn 2 (its prefix grew)
    convo += [{"role": "assistant", "content": a2},
              {"role": "user", "content": "more"}]
    a3, reused3, _ = ask(convo)
    assert isinstance(a3, str) and reused3 >= reused2, (reused3, reused2)


def test_api_main_chat_template_flag(tmp_path):
    """--chat-template forces the template type even when the tokenizer
    carries a different/absent jinja template."""
    import subprocess
    import sys
    import os as _os
    from helpers import REPO_ROOT, make_tiny_model, make_tiny_tokenizer

    mp = str(tmp_path / "m.m")
    tp = str(tmp_path / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, cfg=cfg)
    make_tiny_tokenizer(tp, pad_to=288)  # no chat template in the file
    import socket

    with socket.socket() as s0:
        s0.bind(("127.0.0.1", 0))
        port = s0.getsockname()[1]
    log_path = tmp_path / "server.log"
    with open(log_path, "wb") as log:
        proc = subprocess.Popen(
            [sys.executable, "-m", "dllama_tpu.runtime.api_server",
             "--model", mp, "--tokenizer", tp, "--port", str(port),
             "--host", "127.0.0.1", "--tp", "1", "--dtype", "f32",
             "--temperature", "0", "--chat-template", "chatml"],
            env={**_os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=REPO_ROOT,
            stdout=log, stderr=subprocess.STDOUT,
        )
    try:
        import time as _t
        import urllib.request

        deadline = _t.time() + 120
        while _t.time() < deadline:
            if proc.poll() is not None:
                raise AssertionError(
                    f"server exited rc={proc.returncode}:\n"
                    + log_path.read_text()[-1000:]
                )
            try:
                urllib.request.urlopen(f"http://127.0.0.1:{port}/health", timeout=2)
                break
            except Exception:
                _t.sleep(1)
        else:
            raise AssertionError(
                "server did not come up:\n" + log_path.read_text()[-1000:]
            )
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/chat/completions",
            data=json.dumps({"messages": [{"role": "user", "content": "hi"}],
                             "max_tokens": 3, "temperature": 0}).encode(),
            headers={"Content-Type": "application/json"},
        )
        data = json.loads(urllib.request.urlopen(req, timeout=120).read())
        assert data["object"] == "chat.completion"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_lane_server_seed_reproducible(lane_server):
    """A `seed` under the lane scheduler IS honored per lane (r5:
    decode_lanes derives each lane's sampling keys from its own seed and
    absolute positions): a seeded sampled request reproduces through the
    concurrent path, and the response no longer carries the old
    best-effort warning."""
    payload = {
        "messages": [{"role": "user", "content": "tell me"}],
        "max_tokens": 6, "temperature": 0.9, "seed": 42,
    }
    with _post(lane_server, payload) as r:
        body = json.loads(r.read())
    assert "warning" not in body, body
    a = body["choices"][0]["message"]["content"]
    with _post(lane_server, payload) as r:
        b = json.loads(r.read())["choices"][0]["message"]["content"]
    assert a == b


def test_chat_completion_q40_fused_engine(tmp_path):
    """The serving path over a weight_format='q40' engine (which fuses
    wqkv/w13 by default) must produce the same completion as the dense
    engine for a greedy request — server x fusion x NaiveCache in one
    pass."""
    mp, tp_ = str(tmp_path / "m.m"), str(tmp_path / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")

    payload = {
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 8,
        "temperature": 0,
    }
    outs = {}
    for fmt in ("q40", "dense"):
        tok = Tokenizer(tp_)
        engine = InferenceEngine(
            mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0,
            seed=3, weight_format=fmt,
        )
        if fmt == "q40":
            assert "wqkv" in engine.params["layers"]
            assert "w13" in engine.params["layers"]
        srv = serve(engine, tok, host="127.0.0.1", port=0)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            with _post(url, payload) as r:
                outs[fmt] = json.loads(r.read())["choices"][0]["message"]
        finally:
            srv.shutdown()
    assert outs["q40"] == outs["dense"], outs


def test_single_stream_crash_recovery(tmp_path):
    """VERDICT r4 item 7: an injected engine error mid-request yields a
    500, the donated KV cache and the stale NaiveCache entries are
    dropped (cache epoch moved), and the next request succeeds."""
    mp, tp_ = str(tmp_path / "m.m"), str(tmp_path / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3
    )
    srv = serve(engine, tok, host="127.0.0.1", port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    payload = {
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 6,
        "temperature": 0,
    }
    try:
        # 1. clean request works
        with _post(url, payload) as r:
            ok1 = json.loads(r.read())
        assert ok1["choices"][0]["message"]["content"] is not None

        # 2. poison the next dispatch: donate the cache, then fail
        real = engine._decode_block_fn

        def poisoned(n_steps, greedy, window=0):
            block = real(n_steps, greedy, window)

            def bad(params, token, cache, pos, rng, temp, topp):
                block(params, token, cache, pos, rng, temp, topp)
                raise RuntimeError("injected dispatch failure")

            return bad

        engine._decode_block_fn = poisoned
        epoch0 = engine.cache_epoch
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(url, payload).read()
        assert exc.value.code == 500
        assert "injected" in json.loads(exc.value.read())["error"]["message"]
        engine._decode_block_fn = real
        assert engine.cache_epoch > epoch0

        # 3. next request (same conversation prefix) succeeds and matches
        #    the clean run — nothing resumed from poisoned state
        with _post(url, payload) as r:
            ok2 = json.loads(r.read())
        assert (
            ok2["choices"][0]["message"]["content"]
            == ok1["choices"][0]["message"]["content"]
        )
    finally:
        srv.shutdown()


def test_chat_completion_q40i8_kv8_engine(tmp_path):
    """Serving over the maximum-headroom decode configuration (grouped-
    int8 weights + int8 KV cache): a greedy request completes and is
    reproducible across two identical requests (NaiveCache prefix path
    included). Hidden dims sized for the q40i8 group divisibility."""
    mp, tp_ = str(tmp_path / "m8.m"), str(tmp_path / "t.t")
    cfg = dict(dim=64, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0,
        seed=3, weight_format="q40i8", kv_dtype="int8",
    )
    assert engine.i8_group >= 32
    srv = serve(engine, tok, host="127.0.0.1", port=0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    payload = {
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 8,
        "temperature": 0,
    }
    try:
        with _post(url, payload) as r:
            one = json.loads(r.read())["choices"][0]["message"]["content"]
        with _post(url, payload) as r:
            two = json.loads(r.read())["choices"][0]["message"]["content"]
        assert one == two and isinstance(one, str)
    finally:
        srv.shutdown()


# -- observability (obs/): /metrics, /v1/health, --trace-out ----------------
#
# These tests own their server (unlike the URL-only fixtures above) so they
# can reach `srv.state` — the metric handles, the tracer ring, and the lane
# scheduler. The metrics registry is process-global, so every assertion on
# a counter is a DELTA against a before-value, never an absolute count.


@pytest.fixture(scope="module")
def obs_server(tmp_path_factory):
    """batch_size-3 engine + --trace-out sink; yields the HTTPServer."""
    d = tmp_path_factory.mktemp("api_obs")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=3,
    )
    trace_path = str(d / "trace.jsonl")
    srv = serve(engine, tok, host="127.0.0.1", port=0, trace_out=trace_path)
    srv.trace_path = trace_path
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv
    srv.shutdown()


def _url(srv):
    return f"http://127.0.0.1:{srv.server_address[1]}"


def _scrape(srv):
    with urllib.request.urlopen(_url(srv) + "/metrics", timeout=30) as r:
        return r.headers["Content-Type"], r.read().decode()


def _sample(text, name):
    m = re.search(rf"^{re.escape(name)} ([0-9.e+-]+)$", text, re.M)
    assert m, f"{name} not in scrape"
    return float(m.group(1))


def test_metrics_under_concurrent_streams(obs_server):
    """The acceptance scrape: >=3 concurrent streaming requests against a
    batch_size>1 engine, then GET /metrics serves Prometheus text with
    non-empty TTFT/TPOT histograms, queue-wait, lane gauges, and the
    NaiveCache hit/miss counters."""
    state = obs_server.state
    b_ttft, b_adm = state.m_ttft.count, state.m_admissions.value
    b_qw, b_fin = state.m_queue_wait.count, state.m_finished.child_values()
    prompts = ["alpha", "beta stream", "gamma ray"]
    results, errors = [None] * 3, []

    def worker(i):
        try:
            with _post(_url(obs_server), {
                "messages": [{"role": "user", "content": prompts[i]}],
                "max_tokens": 8, "temperature": 0, "stream": True,
            }) as r:
                results[i] = r.read().decode()
        except Exception as e:  # pragma: no cover
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors
    for raw in results:
        assert raw.rstrip().endswith("data: [DONE]")
    # the final SSE chunk carries the span-derived request metadata
    events = [json.loads(line[len("data: "):])
              for line in results[0].splitlines()
              if line.startswith("data: ") and line != "data: [DONE]"]
    meta = events[-1]["dllama"]
    assert meta["request_id"].startswith("req-")
    assert meta["lane"] is not None and meta["ttft_ms"] > 0

    # every request got admitted, waited in queue, and marked a TTFT
    assert state.m_ttft.count >= b_ttft + 3
    assert state.m_queue_wait.count >= b_qw + 3
    assert state.m_admissions.value >= b_adm + 3
    fin = state.m_finished.child_values()
    assert sum(fin.values()) >= sum(b_fin.values()) + 3

    ctype, text = _scrape(obs_server)
    assert ctype == state.obs.CONTENT_TYPE
    for fam in (
        "dllama_ttft_seconds", "dllama_tpot_seconds",
        "dllama_queue_wait_seconds", "dllama_prefill_seconds",
        "dllama_lanes_total", "dllama_lanes_active", "dllama_queue_depth",
        "dllama_prefix_cache_hits_total", "dllama_prefix_cache_misses_total",
        "dllama_requests_finished_total", "dllama_http_requests_total",
        "dllama_engine_step_seconds", "dllama_engine_compiles_total",
    ):
        assert f"# TYPE {fam} " in text, fam
    m = re.search(r"^dllama_ttft_seconds_count (\d+)$", text, re.M)
    assert m and int(m.group(1)) >= 3
    m = re.search(r"^dllama_tpot_seconds_count (\d+)$", text, re.M)
    assert m and int(m.group(1)) >= 1
    assert _sample(text, "dllama_lanes_total") == 3
    # cumulative buckets: the +Inf bucket equals the count
    inf = re.search(r'^dllama_ttft_seconds_bucket\{le="\+Inf"\} (\d+)$',
                    text, re.M)
    cnt = re.search(r"^dllama_ttft_seconds_count (\d+)$", text, re.M)
    assert inf and cnt and inf.group(1) == cnt.group(1)


def test_health_endpoint(obs_server):
    with urllib.request.urlopen(_url(obs_server) + "/v1/health",
                                timeout=30) as r:
        data = json.loads(r.read())
    assert data["status"] == "ok"
    assert data["model"]
    assert data["uptime_s"] >= 0
    assert data["lanes"]["total"] == 3
    assert data["lanes"]["active"] + data["lanes"]["free"] == 3
    assert data["queue_depth"] >= 0
    assert isinstance(data["cache_epoch"], int)


def test_trace_out_roundtrip_completed(obs_server):
    """A finished request's lifecycle lands in the --trace-out JSONL with
    queue wait, prefill span, first-token time, token counts, and finish
    reason — matched to the request by the response's request_id."""
    from dllama_tpu.obs.trace import read_jsonl

    with _post(_url(obs_server), {
        "messages": [{"role": "user", "content": "trace me"}],
        "max_tokens": 5, "temperature": 0,
    }) as r:
        body = json.loads(r.read())
    rid = body["dllama"]["request_id"]
    assert body["dllama"]["ttft_ms"] > 0

    rec = None
    deadline = time.time() + 60
    while rec is None and time.time() < deadline:
        recs = [x for x in read_jsonl(obs_server.trace_path)
                if x["request_id"] == rid]
        rec = recs[0] if recs else None
        if rec is None:
            time.sleep(0.1)
    assert rec is not None, "trace record never hit the sink"
    assert rec["path"] == "lanes" and rec["finish_reason"] in ("stop", "length")
    assert rec["cancelled"] is False
    assert rec["queue_wait_s"] >= 0 and rec["prefill_s"] > 0
    assert rec["ttft_s"] >= rec["queue_wait_s"]
    assert rec["n_prompt_tokens"] > 0
    assert 1 <= rec["n_completion"] <= 5
    assert rec["total_s"] >= rec["ttft_s"]
    # the in-memory ring holds the same record
    assert any(x["request_id"] == rid
               for x in obs_server.state.tracer.records())


def test_trace_cancelled_stream(obs_server):
    """A client that disconnects mid-stream produces a `cancelled` trace
    record and bumps the SSE-cancellation counter: raw socket, read until
    the first delta, then RST-close."""
    state = obs_server.state
    b_cancel = state.m_cancellations.value
    b_recs = sum(1 for x in state.tracer.records()
                 if x["finish_reason"] == "cancelled")
    payload = json.dumps({
        "messages": [{"role": "user", "content": "stream then vanish"}],
        "max_tokens": 300, "temperature": 0, "stream": True,
    }).encode()
    s = socket.create_connection(
        ("127.0.0.1", obs_server.server_address[1]), timeout=120)
    try:
        s.sendall(b"POST /v1/chat/completions HTTP/1.1\r\n"
                  b"Host: t\r\nContent-Type: application/json\r\n"
                  b"Content-Length: " + str(len(payload)).encode()
                  + b"\r\n\r\n" + payload)
        buf = b""
        while b"data:" not in buf:
            chunk = s.recv(4096)
            assert chunk, f"stream closed before first delta: {buf!r}"
            buf += chunk
        # RST on close so the server's next write fails immediately
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     struct.pack("ii", 1, 0))
    finally:
        s.close()

    rec = None
    deadline = time.time() + 120
    while rec is None and time.time() < deadline:
        recs = [x for x in state.tracer.records()
                if x["finish_reason"] == "cancelled"]
        rec = recs[-1] if len(recs) > b_recs else None
        if rec is None:
            time.sleep(0.2)
    assert rec is not None, "cancellation never reached the tracer"
    assert rec["cancelled"] is True
    assert rec["n_completion"] >= 1  # it really was mid-stream
    assert rec["queue_wait_s"] is not None and rec["ttft_s"] is not None
    assert state.m_cancellations.value >= b_cancel + 1


def test_cross_lane_radix_reuse_and_kv_debug(obs_server):
    """Shared-prefix fanout through the radix pool: the same conversation
    asked repeatedly is admitted onto DIFFERENT lanes yet reuses the
    stored pages (trace records the reused length), the pool's
    page accounting proves the prefix is physically stored once (repeat
    publishes dedup to zero new pages), and /v1/debug/kv exposes it all.
    After the lanes drain, no page retains leak."""
    state = obs_server.state
    sched = state.scheduler
    kv = state.kv_manager
    assert kv is not None and sched.kv is kv

    def drain():
        deadline = time.time() + 60
        while (any(ls is not None for ls in sched.lanes) or sched.pending
               or sched.admitting):
            assert time.time() < deadline, "lanes never drained"
            time.sleep(0.05)

    drain()
    kv.reset()  # deterministic accounting below

    def ask(messages):
        with _post(_url(obs_server), {
            "messages": messages, "max_tokens": 5, "temperature": 0,
        }) as r:
            return json.loads(r.read())

    b_hits = state.m_prefix_hits.value
    convo = [{"role": "user", "content":
              "shared system preamble: you are a careful assistant who "
              "always answers in rhyming couplets about the sea"}]
    a1 = ask(convo)
    assert a1["dllama"]["reused_prefix_tokens"] == 0
    used_once = kv.pool.stats().used
    assert used_once > 0  # the first stream's prefix was published

    # fan the SAME conversation out twice more (greedy -> identical
    # continuations): each lands on a different (LRU) lane, reuses the
    # stored prefix, and publishes NOTHING new — stored once, physically
    a2 = ask(list(convo))
    a3 = ask(list(convo))
    assert a2["dllama"]["lane"] != a1["dllama"]["lane"]
    assert a2["dllama"]["reused_prefix_tokens"] > 0
    assert a3["dllama"]["reused_prefix_tokens"] > 0
    assert state.m_prefix_hits.value >= b_hits + 2
    assert kv.pool.stats().used == used_once, "fanout duplicated pages"
    # identical greedy requests reproduce through adopted pages
    assert (a2["choices"][0]["message"]["content"]
            == a1["choices"][0]["message"]["content"])

    # the trace record carries the reused length, same as the response
    rec = next(x for x in state.tracer.records()
               if x["request_id"] == a2["dllama"]["request_id"])
    assert rec["reused_prefix_tokens"] == a2["dllama"]["reused_prefix_tokens"]
    assert rec["lane"] == a2["dllama"]["lane"]

    # /v1/debug/kv: live accounting, consistent with the pool
    with urllib.request.urlopen(_url(obs_server) + "/v1/debug/kv",
                                timeout=30) as r:
        dbg = json.loads(r.read())
    assert dbg["enabled"] is True
    assert dbg["pool"]["total"] == kv.pool.n_pages - 1
    assert dbg["pool"]["free"] + dbg["pool"]["used"] == dbg["pool"]["total"]
    assert dbg["pool"]["used"] == used_once
    assert dbg["radix"]["pages"] == used_once
    assert dbg["radix"]["nodes"] >= 1

    # a continuation reuses at least the whole stored prefix
    convo += [
        {"role": "assistant", "content": a1["choices"][0]["message"]["content"]},
        {"role": "user", "content": "continue"},
    ]
    c1 = ask(convo)
    assert c1["dllama"]["reused_prefix_tokens"] >= a2["dllama"]["reused_prefix_tokens"]

    # leak check: drained lanes hold no page retains; every allocated
    # page is accounted to the tree (refcount exactly 1 -> shared == 0)
    drain()
    kv.check()
    st = kv.pool.stats()
    assert st.shared == 0, st
    assert not kv._lane_pages
    # the /metrics scrape carries the new pool gauges + radix counters
    _, text = _scrape(obs_server)
    for fam in ("dllama_kv_pages_total", "dllama_kv_pages_free",
                "dllama_kv_pages_shared", "dllama_radix_hits_total",
                "dllama_radix_evictions_total",
                "dllama_shared_prefix_tokens_total",
                "dllama_kv_cow_forks_total"):
        assert f"# TYPE {fam} " in text, fam
    assert _sample(text, "dllama_radix_hits_total") >= 2
    assert _sample(text, "dllama_shared_prefix_tokens_total") > 0


def test_scheduler_error_counter(obs_server):
    """An engine error inside the scheduler loop is counted (satellite:
    the loop used to swallow these silently), the in-flight request gets
    a structured retryable 503 + Retry-After (PR 12: the cache epoch
    never moved, so this is a transient-class failure the client should
    simply retry), and the server keeps serving."""
    state = obs_server.state
    engine = state.engine
    b_err = state.m_sched_errors.value
    b_retry = state.m_dispatch_retries.value
    real = engine.decode_lanes

    def boom(*a, **k):
        raise RuntimeError("injected lane dispatch failure")

    engine.decode_lanes = boom
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(_url(obs_server), {
                "messages": [{"role": "user", "content": "doomed"}],
                "max_tokens": 4, "temperature": 0,
            }).read()
        assert exc.value.code == 503
        assert exc.value.headers.get("Retry-After") is not None
        err = json.loads(exc.value.read())["error"]
        assert "injected" in err["message"]
        assert err["retryable"] is True
    finally:
        engine.decode_lanes = real
    assert state.m_sched_errors.value == b_err + 1
    # the deterministic failure was retried with backoff before the drop
    assert state.m_dispatch_retries.value == b_retry + state.retry_max
    # scheduler thread survived: the next request completes normally
    with _post(_url(obs_server), {
        "messages": [{"role": "user", "content": "still alive?"}],
        "max_tokens": 4, "temperature": 0,
    }) as r:
        assert json.loads(r.read())["object"] == "chat.completion"


# -- /v1/debug introspection + postmortem ------------------------------------


def _get_json(srv, path):
    with urllib.request.urlopen(_url(srv) + path, timeout=30) as r:
        return json.loads(r.read())


def test_debug_recorder_endpoint(obs_server):
    """After real traffic the flight-recorder dump shows the whole story:
    scheduler admits/finishes bracketing engine dispatch/complete pairs,
    in recording order, with wall times on the completes."""
    with _post(_url(obs_server), {
        "messages": [{"role": "user", "content": "record me"}],
        "max_tokens": 4, "temperature": 0,
    }) as r:
        r.read()
    dump = _get_json(obs_server, "/v1/debug/recorder")
    assert dump["capacity"] > 0 and dump["n_events"] > 0
    assert dump["total_recorded"] >= dump["n_events"]
    kinds = {e["kind"] for e in dump["events"]}
    assert {"admit", "finish", "step_dispatch", "step_complete"} <= kinds
    for e in dump["events"]:
        assert e["t"] > 0 and e["seq"] > 0
        if e["kind"] == "step_complete":
            assert e["ms"] >= 0
    seqs = [e["seq"] for e in dump["events"]]
    assert seqs == sorted(seqs)


def test_debug_memory_endpoint(obs_server):
    data = _get_json(obs_server, "/v1/debug/memory")
    assert len(data["devices"]) >= 1
    for d in data["devices"]:
        assert {"device", "platform", "available"} <= set(d)
    an = data["analytic"]
    assert an["params_bytes"] > 0 and an["cache_bytes"] > 0
    assert an["total_bytes"] == an["params_bytes"] + an["cache_bytes"]
    assert 0 < an["per_device_bytes"] <= an["total_bytes"]
    cmp_ = data["comparison"]
    assert cmp_["analytic_per_chip_bytes"] == an["per_device_bytes"]
    if not any(d["available"] for d in data["devices"]):
        # CPU test backend: explicit unavailability, no fabricated figures
        assert cmp_["available"] is False


def test_debug_compile_endpoint(obs_server):
    """The acceptance probe: /v1/debug/compile reports non-empty XLA cost
    analysis for at least the decode step on CPU (AOT-compiled block
    programs), and lazily jitted programs carry the explicit
    'unavailable' marker instead of nothing."""
    with _post(_url(obs_server), {
        "messages": [{"role": "user", "content": "compile me"}],
        "max_tokens": 4, "temperature": 0,
    }) as r:
        r.read()
    data = _get_json(obs_server, "/v1/debug/compile")
    programs = data["programs"]
    assert programs
    for p in programs:
        assert p["kind"] in (
            "prefill", "prefill_lane", "decode_block", "decode_lanes",
            "score", "kv_adopt", "kv_publish", "kv_page_copy",
        )
        assert p["origin"] in ("dispatch", "prefetch", "prefetch-failed")
        assert p["cost"] == "unavailable" or p["cost"]["bytes_accessed"] >= 0
    decode = [p for p in programs
              if p["kind"] in ("decode_block", "decode_lanes")]
    assert decode, "no decode program in the compile cache after a request"
    assert any(isinstance(p["cost"], dict) and p["cost"]["flops"] > 0
               for p in decode)
    assert all(p["compile_seconds"] is None or p["compile_seconds"] >= 0
               for p in programs)

    cost = data["cost"]
    assert "hbm_peak_bytes_per_s" in cost  # None on CPU, a number on TPU
    kinds = cost["kinds"]
    assert any(k in kinds for k in ("decode_block", "decode_lanes"))
    for info in kinds.values():
        assert info["bytes_accessed"] > 0
        if cost["hbm_peak_bytes_per_s"] is None:
            assert info["roofline_fraction"] is None


def test_debug_endpoints_count_http_metrics(obs_server):
    """Debug paths ride the same HTTP accounting as the serving paths."""
    state = obs_server.state
    before = state.m_http.child_values().get(("/v1/debug/recorder",), 0)
    _get_json(obs_server, "/v1/debug/recorder")
    after = state.m_http.child_values()[("/v1/debug/recorder",)]
    assert after == before + 1


def test_scheduler_error_writes_postmortem(obs_server, tmp_path):
    """An injected scheduler-loop failure produces a postmortem JSON
    containing the event ring (the tentpole's black-box guarantee), and
    the server keeps serving afterwards."""
    state = obs_server.state
    engine = state.engine
    pm_dir = tmp_path / "pm"
    old_dir = state.recorder.postmortem_dir
    state.recorder.postmortem_dir = str(pm_dir)
    real = engine.decode_lanes

    def boom(*a, **k):
        raise RuntimeError("injected postmortem failure")

    engine.decode_lanes = boom
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(_url(obs_server), {
                "messages": [{"role": "user", "content": "doomed again"}],
                "max_tokens": 4, "temperature": 0,
            }).read()
        assert exc.value.code == 503
    finally:
        engine.decode_lanes = real
        state.recorder.postmortem_dir = old_dir

    files = sorted(pm_dir.glob("postmortem-*.json"))
    assert files, "scheduler error never wrote a postmortem"
    payload = json.loads(files[-1].read_text())
    assert payload["reason"] == "scheduler-loop"
    assert "injected postmortem failure" in payload["error"]
    assert payload["error_type"] == "RuntimeError"
    kinds = [e["kind"] for e in payload["events"]]
    assert "scheduler_error" in kinds  # the ring captured the failure
    assert "step_dispatch" in kinds    # ...and the engine history before it
    # PR 12 satellite: the dump embeds the server-level evidence — a
    # /v1/health snapshot and the trailing anomaly-signal series — so a
    # ring file is diagnosable without the live server
    ctx = payload["context"]
    assert ctx["health"]["model"] == state.model_name
    assert "lanes" in ctx["health"] and "cache_epoch" in ctx["health"]
    assert isinstance(ctx["series_60s"], dict)
    # the loop survived: a normal request completes and the dump shows it
    with _post(_url(obs_server), {
        "messages": [{"role": "user", "content": "recovered?"}],
        "max_tokens": 4, "temperature": 0,
    }) as r:
        assert json.loads(r.read())["object"] == "chat.completion"


def test_debug_timeline_endpoint_and_coverage(obs_server):
    """A finished request's span timeline is served as Chrome-trace JSON
    and its phase accounting covers >=95% of the request's wall time (the
    tentpole acceptance bar: queue + admission + decode + publish spans
    leave only scheduler-tick bookkeeping uncovered)."""
    with _post(_url(obs_server), {
        "messages": [{"role": "user", "content": "time me"}],
        "max_tokens": 6, "temperature": 0,
    }) as r:
        body = json.loads(r.read())
    rid = body["dllama"]["request_id"]

    trace = _get_json(obs_server, f"/v1/debug/timeline?request_id={rid}")
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert xs, "no spans for the request"
    names = {e["name"] for e in xs}
    assert "queue" in names and "decode" in names
    assert all(e["args"]["request_id"] == rid for e in xs)
    assert all(e["dur"] >= 0 for e in xs)
    summary = trace["dllama"]["summary"]
    assert summary["request_id"] == rid
    assert summary["wall_ms"] > 0
    assert summary["coverage"] >= 0.95, summary
    assert "queue" in summary["phases"] and "decode" in summary["phases"]
    # phase totals are consistent with the span list
    assert summary["n_spans"] == len(xs)

    # the unfiltered timeline aggregates every component's spans
    full = _get_json(obs_server, "/v1/debug/timeline")
    assert full["dllama"]["n_spans"] >= len(xs)
    comps = {e["args"]["name"]
             for e in full["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert {"scheduler", "engine"} <= comps


def test_debug_slo_endpoint_and_gauges(obs_server):
    """/v1/debug/slo serves the three sliding windows with finite
    attainment/goodput, and the scrape-time snapshot refreshes the
    dllama_slo_* gauges in /metrics."""
    with _post(_url(obs_server), {
        "messages": [{"role": "user", "content": "meet my slo"}],
        "max_tokens": 4, "temperature": 0,
    }) as r:
        r.read()
    snap = _get_json(obs_server, "/v1/debug/slo")
    assert set(snap["targets"]) == {"ttft_ms", "tpot_ms"}
    assert set(snap["windows"]) == {"10s", "1m", "5m"}
    for w in snap["windows"].values():
        assert w["n_requests"] >= 0
        assert 0.0 <= w["attainment"] <= 1.0
        assert 0.0 <= w["ttft_attainment"] <= 1.0
        assert w["goodput_tokens_per_s"] >= 0.0
        assert w["throughput_tokens_per_s"] >= 0.0
    # the request we just finished is inside the 5m window
    assert snap["windows"]["5m"]["n_requests"] >= 1
    assert snap["windows"]["5m"]["throughput_tokens_per_s"] > 0

    _, text = _scrape(obs_server)
    for fam in ("dllama_slo_attainment", "dllama_slo_ttft_attainment",
                "dllama_slo_tpot_attainment",
                "dllama_slo_goodput_tokens_per_s",
                "dllama_slo_throughput_tokens_per_s",
                "dllama_slo_window_requests"):
        assert f"# TYPE {fam} " in text, fam
    assert re.search(
        r'^dllama_slo_window_requests\{window="5m"\} \d+$', text, re.M)


def test_watchdog_trips_on_injected_stall(obs_server, tmp_path):
    """A dispatch left hanging past the timeout (driven by a fake clock,
    so the test is fast) flips /v1/health to degraded, increments
    dllama_watchdog_stalls_total, and writes a watchdog postmortem; when
    the dispatch clears the watchdog recovers."""
    wd = obs_server.state.watchdog
    assert wd is not None, "lane server must run a watchdog"
    pm_dir = tmp_path / "pm"
    old_dir = wd.recorder.postmortem_dir
    old_clock = wd._clock
    fake = {"t": 10_000.0}
    stalls = wd.m_stalls.labels(reason="dispatch-hung")
    b_stalls = stalls.value
    try:
        wd.recorder.postmortem_dir = str(pm_dir)
        wd._clock = lambda: fake["t"]
        wd.dispatch_begin("decode_lanes")  # ...and never ends: a hang
        fake["t"] += wd.dispatch_timeout_s + 1.0
        assert wd.check_once() == "dispatch-hung"
        assert wd.degraded

        health = _get_json(obs_server, "/v1/health")
        assert health["status"] == "degraded"
        assert health["watchdog"]["degraded"] is True
        assert health["watchdog"]["reason"] == "dispatch-hung"
        assert "decode_lanes" in health["watchdog"]["detail"]
        assert stalls.value == b_stalls + 1
        _, text = _scrape(obs_server)
        assert "dllama_watchdog_degraded 1" in text

        files = sorted(pm_dir.glob("postmortem-*.json"))
        assert files, "watchdog stall never wrote a postmortem"
        payload = json.loads(files[-1].read_text())
        assert payload["reason"] == "watchdog"
        assert "dispatch-hung" in payload["error"]

        # the dispatch completes: one check later the episode is over
        wd.dispatch_end()
        assert wd.check_once() is None
        assert not wd.degraded
        assert _get_json(obs_server, "/v1/health")["status"] == "ok"
        # edge-triggered: the whole episode cost exactly one postmortem
        assert len(sorted(pm_dir.glob("postmortem-*.json"))) == 1
    finally:
        wd.dispatch_end()
        wd._clock = old_clock
        wd.recorder.postmortem_dir = old_dir
        wd.check_once()  # clear any degraded state with the real clock


# -- time-series store, /dashboard, anomaly detection (obs/timeseries,
# obs/anomaly, obs/dashboard) ------------------------------------------------


def _post_json(srv, path, payload):
    req = urllib.request.Request(
        _url(srv) + path,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def test_debug_series_index_and_query(obs_server):
    """/v1/debug/series with no ?name= lists the tracked series plus the
    anomaly monitor's status; with ?name=&window= it serves the trailing
    points the dashboard sparklines poll."""
    state = obs_server.state
    # one deterministic tick so the store is populated regardless of the
    # background sampler's phase
    state.sampler.sample_once()
    idx = _get_json(obs_server, "/v1/debug/series")
    assert idx["interval_s"] == state.series.interval_s
    assert idx["retention_s"] == state.series.retention_s
    assert "dllama_lanes_active" in idx["names"]
    assert "dllama_queue_depth" in idx["names"]
    # the scrape-only SLO gauges ride the shared refresh hooks into the
    # store too (the stale-gauge fix: sampler and scraper run the SAME
    # refresh path)
    assert any(n.startswith("dllama_slo_goodput_tokens_per_s")
               for n in idx["names"])
    anom = idx["anomaly"]
    assert anom["enabled"] is True and anom["n_rules"] >= 5
    assert {"decode_stall", "ttft", "tpot", "kv_free_slope", "goodput"} <= (
        set(anom["baselines"])
    )

    res = _get_json(
        obs_server, "/v1/debug/series?name=dllama_lanes_active&window=60")
    assert res["name"] == "dllama_lanes_active"
    assert res["kind"] == "gauge" and res["tier"] == "1s"
    assert res["points"] and all(len(p) == 2 for p in res["points"])
    ts = [p[0] for p in res["points"]]
    assert ts == sorted(ts)


def test_debug_series_bad_window_and_missing_series(obs_server):
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get_json(
            obs_server,
            "/v1/debug/series?name=dllama_lanes_active&window=bogus")
    assert exc.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get_json(obs_server, "/v1/debug/series?name=no_such_series")
    assert exc.value.code == 404
    assert "no series" in json.loads(exc.value.read())["error"]["message"]


def test_dashboard_serves_self_contained_page(obs_server):
    """GET /dashboard is a single self-contained HTML page — inline CSS,
    inline JS, canvas sparklines, polling only same-origin endpoints (the
    air-gap promise the dashboard-static dlint rule enforces)."""
    with urllib.request.urlopen(_url(obs_server) + "/dashboard",
                                timeout=30) as r:
        ctype = r.headers["Content-Type"]
        html = r.read().decode("utf-8")
    assert ctype.startswith("text/html")
    assert "<canvas" in html and "<script>" in html
    # it polls the in-process endpoints, nothing else
    assert "/v1/debug/series" in html and "/v1/health" in html
    low = html.lower()
    assert "http://" not in low and "https://" not in low
    assert "<script src" not in low and "@import" not in low
    assert 'src="//' not in low and 'href="//' not in low


def test_dashboard_series_reflect_fake_clock_traffic(obs_server):
    """The acceptance loop, closed end-to-end: real traffic lands in the
    registry, injected fake-clock sampler ticks snapshot it into the
    store, and the exact queries the dashboard's sparklines poll
    (/v1/debug/series?name=&window=) serve those points back over HTTP."""
    state = obs_server.state
    state.sampler.stop()  # only the injected fake-clock ticks below
    try:
        with _post(_url(obs_server), {
            "messages": [{"role": "user", "content": "draw me"}],
            "max_tokens": 5, "temperature": 0,
        }) as r:
            assert json.loads(r.read())["object"] == "chat.completion"
        base = time.monotonic() + 1e6  # newer than every real-clock tick
        ticks = [base + i for i in range(5)]
        for t in ticks:
            state.sampler.sample_once(now=t)
        for name in ("dllama_lanes_active", "dllama_queue_depth",
                     "dllama_ttft_seconds_p50"):
            res = _get_json(
                obs_server, f"/v1/debug/series?name={name}&window=60")
            assert [p[0] for p in res["points"]] == ticks, name
        # the TTFT sparkline really reflects the request served above
        res = _get_json(
            obs_server,
            "/v1/debug/series?name=dllama_ttft_seconds_p50&window=60")
        assert all(v > 0 for _, v in res["points"])
    finally:
        state.sampler.start()


def test_debug_profile_endpoint(obs_server, tmp_path):
    """POST /v1/debug/profile captures an on-demand profile (CPU-safe:
    the hardened telemetry.profile logs-and-continues where tracing is
    unavailable), validates the capture length, and serializes captures
    through the non-blocking profile lock."""
    state = obs_server.state
    b_events = len(state.recorder.events(kind="profile_capture"))
    out = str(tmp_path / "prof")
    data = _post_json(obs_server, "/v1/debug/profile",
                      {"seconds": 0.05, "out_dir": out})
    assert data["log_dir"] == out and data["seconds"] == 0.05
    assert data["n_files"] >= 0
    events = state.recorder.events(kind="profile_capture")
    assert len(events) == b_events + 1
    assert events[-1]["log_dir"] == out

    # out-of-range capture lengths are rejected before any tracing
    for bad in (0, -1, 61):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post_json(obs_server, "/v1/debug/profile", {"seconds": bad})
        assert exc.value.code == 400

    # one capture at a time: while the lock is held the endpoint is 409
    assert state.profile_lock.acquire(blocking=False)
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post_json(obs_server, "/v1/debug/profile", {"seconds": 0.05})
        assert exc.value.code == 409
    finally:
        state.profile_lock.release()


def test_anomaly_fires_and_recovers_through_server(obs_server):
    """The anomaly acceptance bar, on the live monitor under a fake
    clock: a signal leaving its baseline fires exactly one
    dllama_anomaly_total{signal=} increment (visible in a /metrics
    scrape), flips /v1/health to degraded with the anomaly reason, and
    recovers back to ok after the calm-tick hysteresis — all
    deterministic (edge-triggered, frozen baseline while active)."""
    from dllama_tpu.obs.anomaly import AnomalyRule, _RuleState

    state = obs_server.state
    mon = state.anomaly
    val = {"v": 1.0}
    rule = AnomalyRule(
        "test_e2e", lambda: val["v"], direction="high", z_threshold=4.0,
        min_samples=5, min_abs=0.1, std_floor=1e-3, recover_ticks=2,
    )
    counter = mon.m_anomalies.labels(signal="test_e2e")
    b_count = counter.value
    b_events = len(state.recorder.events(kind="anomaly"))
    with mon._lock:
        mon.rules.append(rule)
        mon._state["test_e2e"] = _RuleState(rule.alpha)
    try:
        # teach the baseline with calm ticks (the background sampler may
        # interleave more ticks at the same value — also calm, also
        # teaching — so every outcome below stays deterministic)
        for i in range(10):
            mon.evaluate(now=1_000.0 + i)
        assert "test_e2e" not in mon.active_signals()
        assert _get_json(obs_server, "/v1/health")["status"] == "ok"

        # the signal leaves its baseline: exactly one edge
        val["v"] = 100.0
        mon.evaluate(now=1_020.0)
        assert "test_e2e" in mon.active_signals()
        assert counter.value == b_count + 1

        health = _get_json(obs_server, "/v1/health")
        assert health["status"] == "degraded"
        assert "anomaly:test_e2e" in health["degraded_reasons"]
        detail = health["anomaly"]["active"]["test_e2e"]
        assert detail["z"] >= 4.0 and detail["value"] == 100.0
        assert detail["active_s"] >= 0

        _, text = _scrape(obs_server)
        m = re.search(
            r'^dllama_anomaly_total\{signal="test_e2e"\} ([0-9.]+)$',
            text, re.M)
        assert m and float(m.group(1)) == b_count + 1
        assert _sample(text, "dllama_anomaly_degraded") == 1.0

        # still abnormal on a later tick: edge-triggered, no re-count
        mon.evaluate(now=1_021.0)
        assert counter.value == b_count + 1
        fired = state.recorder.events(kind="anomaly")[b_events:]
        assert [e for e in fired if e.get("signal") == "test_e2e"]

        # calm again: recover_ticks consecutive calm ticks clear it (the
        # baseline was frozen at ~1.0, so 1.0 reads as calm immediately)
        val["v"] = 1.0
        mon.evaluate(now=1_030.0)
        mon.evaluate(now=1_031.0)
        assert "test_e2e" not in mon.active_signals()
        assert _get_json(obs_server, "/v1/health")["status"] == "ok"
        recovered = state.recorder.events(kind="anomaly_recovered")
        assert any(e.get("signal") == "test_e2e" for e in recovered)
        assert counter.value == b_count + 1  # the episode cost one count
    finally:
        with mon._lock:
            if rule in mon.rules:
                mon.rules.remove(rule)
            mon._state.pop("test_e2e", None)
        mon.g_degraded.set(1.0 if mon.degraded else 0.0)


def test_health_degraded_reasons_compose(obs_server):
    """A watchdog stall AND an active anomaly at once: /v1/health lists
    BOTH reasons (composition, never last-writer-wins), keeps the
    surviving reason when one source recovers, and returns to "ok" only
    when both have cleared."""
    from dllama_tpu.obs.anomaly import AnomalyRule, _RuleState

    state = obs_server.state
    wd = state.watchdog
    mon = state.anomaly
    old_clock = wd._clock
    fake = {"t": 50_000.0}
    # value_fn=None ticks are calm for an ACTIVE rule, so a huge
    # recover_ticks keeps the background sampler from clearing the
    # injected episode under the test
    rule = AnomalyRule("test_compose", lambda: None, recover_ticks=10**6)
    with mon._lock:
        mon.rules.append(rule)
        st = _RuleState(rule.alpha)
        st.active = True
        st.since = mon._clock()
        st.detail = {"signal": "test_compose", "value": 9.0,
                     "baseline_mean": 1.0, "z": 8.0}
        mon._state["test_compose"] = st
    try:
        wd._clock = lambda: fake["t"]
        # re-stamp the heartbeat in fake time with idle lanes, so stale
        # real-clock liveness state from earlier tests can't trip the
        # scheduler-stalled rule under the fake clock
        wd.beat(n_active=0, n_admitting=0)
        wd.dispatch_begin("decode_lanes")  # ...and never ends: a hang
        fake["t"] += wd.dispatch_timeout_s + 1.0
        assert wd.check_once() == "dispatch-hung"

        health = _get_json(obs_server, "/v1/health")
        assert health["status"] == "degraded"
        reasons = health["degraded_reasons"]
        assert "watchdog:dispatch-hung" in reasons
        assert "anomaly:test_compose" in reasons
        assert health["watchdog"]["degraded"] is True
        assert "test_compose" in health["anomaly"]["active"]

        # watchdog recovers first: still degraded on the anomaly alone
        wd.dispatch_end()
        wd.beat(n_active=0, n_admitting=0)
        assert wd.check_once() is None
        health = _get_json(obs_server, "/v1/health")
        assert health["status"] == "degraded"
        assert health["degraded_reasons"] == ["anomaly:test_compose"]
        assert "watchdog" not in health

        # the anomaly clears too: back to ok, no degraded payload at all
        with mon._lock:
            mon._state["test_compose"].active = False
        health = _get_json(obs_server, "/v1/health")
        assert health["status"] == "ok"
        assert "degraded_reasons" not in health
        assert "anomaly" not in health
    finally:
        wd.dispatch_end()
        wd._clock = old_clock
        wd.check_once()  # clear any degraded state with the real clock
        with mon._lock:
            if rule in mon.rules:
                mon.rules.remove(rule)
            mon._state.pop("test_compose", None)


def test_server_close_joins_sampler_thread(tmp_path):
    """server_close() joins the named sampler thread: a closed server
    (and test churn) can never leak a sampler mutating the process-global
    registry behind the next server's back."""
    mp, tp_ = str(tmp_path / "m.m"), str(tmp_path / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3
    )
    srv = serve(engine, tok, host="127.0.0.1", port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    sampler = srv.state.sampler
    t = sampler._thread
    assert t is not None and t.is_alive()
    assert t.name == "dllama-series-sampler" and t.daemon
    srv.shutdown()
    srv.server_close()
    assert sampler._thread is None
    assert not t.is_alive(), "server_close left the sampler running"
