"""Native C++ loader kernels vs numpy codecs (exact parity required)."""

import numpy as np
import pytest

from dllama_tpu.formats.quants import dequantize_q40, q40_to_planar, quantize_q40
from dllama_tpu.utils import native

# sub-minute CPU-only surface (codecs, tokenizer, native loader,
# interpret-mode kernel parity): the first CI lane runs `pytest -m fast`
pytestmark = pytest.mark.fast



@pytest.fixture(scope="module")
def lib():
    lib = native.load_library()
    if lib is None:
        pytest.skip("native library unavailable (no toolchain)")
    return lib


def test_unpack_transposed_parity(lib):
    rows, cols = 96, 160
    rng = np.random.default_rng(0)
    w = rng.standard_normal((rows, cols)).astype(np.float32)
    raw = quantize_q40(w)
    q, d = native.q40_unpack_transposed(raw, rows, cols)
    q_np, d_np = q40_to_planar(raw, rows * cols)
    np.testing.assert_array_equal(q, q_np.reshape(rows, cols).T)
    np.testing.assert_allclose(
        d, d_np.reshape(rows, cols // 32).T.astype(np.float32), rtol=0, atol=0
    )


def test_dequant_parity(lib):
    rows, cols = 64, 128
    rng = np.random.default_rng(1)
    w = rng.standard_normal((rows, cols)).astype(np.float32)
    raw = quantize_q40(w)
    expected = dequantize_q40(raw, rows * cols).reshape(rows, cols)
    np.testing.assert_allclose(
        native.q40_dequant(raw, rows, cols), expected, rtol=0, atol=0
    )
    np.testing.assert_allclose(
        native.q40_dequant_transposed(raw, rows, cols), expected.T, rtol=0, atol=0
    )


def test_loader_uses_native_path(tmp_path, lib):
    """End-to-end: params loaded with the native path match the numpy path."""
    import sys

    sys.path.insert(0, "tests")
    from helpers import make_tiny_model

    from dllama_tpu.formats import FloatType, ModelReader
    from dllama_tpu.models import load_params

    mp = str(tmp_path / "m.m")
    make_tiny_model(mp, weight_type=FloatType.Q40)
    reader = ModelReader(mp)
    p_native = load_params(reader, weight_format="q40")
    # force numpy fallback
    saved = native._lib
    native._lib = None
    native._lib_tried = True
    try:
        p_numpy = load_params(reader, weight_format="q40")
    finally:
        native._lib = saved
    np.testing.assert_array_equal(
        np.asarray(p_native["layers"]["wq"].q), np.asarray(p_numpy["layers"]["wq"].q)
    )
    np.testing.assert_allclose(
        np.asarray(p_native["layers"]["wq"].d), np.asarray(p_numpy["layers"]["wq"].d)
    )
    np.testing.assert_array_equal(
        np.asarray(p_native["wcls"].q), np.asarray(p_numpy["wcls"].q)
    )


def test_f32_transpose_parity(lib):
    rng = np.random.default_rng(5)
    a = rng.standard_normal((130, 257)).astype(np.float32)  # odd sizes
    out = native.f32_transpose(a)
    np.testing.assert_array_equal(out, a.T)


def test_bpe_encode_parity(lib, tmp_path):
    """Native heap-based BPE vs the Python rescan loop: identical token
    streams over random texts, specials on and off, empty input, and the
    un-tokenizable case (native punts back to Python's detailed error)."""
    from helpers import make_tiny_tokenizer
    from dllama_tpu.tokenizer import Tokenizer

    make_tiny_tokenizer(str(tmp_path / "t.t"))
    tok = Tokenizer(str(tmp_path / "t.t"))

    def python_encode(text, **kw):
        saved = tok._encode_native
        tok._encode_native = lambda raw, sp, bos: None
        try:
            return tok.encode(text, **kw)
        finally:
            tok._encode_native = saved

    rng = np.random.default_rng(9)
    cases = [
        "hello world",
        "",
        "the quick brown fox jumps over the lazy dog " * 10,
        "<s>special</s> mixed <|eot|> text",
        "émojis 🦙 and ünïcode",
    ]
    for _ in range(20):
        n = int(rng.integers(1, 200))
        cases.append(bytes(rng.integers(32, 127, n).astype(np.uint8)).decode())
    for text in cases:
        for sp in (True, False):
            got = tok.encode(text, add_special_tokens=sp)
            want = python_encode(text, add_special_tokens=sp)
            assert got == want, (text[:40], sp, got[:10], want[:10])

    # multi-byte UTF-8 straddling merges
    s = "ααββγγ" * 30
    assert tok.encode(s) == python_encode(s)


def test_bpe_encode_tie_break_leftmost(lib):
    """Equal merge scores: the heap must pick the LEFTMOST pair, exactly
    like the Python rescan loop's strictly-greater comparison does.
    Vocab: a,b,c + ab,bc with EQUAL scores — "abc" must merge (a,b)
    first -> [ab, c], not [a, bc]."""
    from dllama_tpu.formats.tokenizer_file import TokenizerData
    from dllama_tpu.tokenizer import Tokenizer

    vocab = [b"a", b"b", b"c", b"ab", b"bc", b"<s>"]
    scores = [0.0, 0.0, 0.0, 5.0, 5.0, 0.0]
    data = TokenizerData(
        vocab=vocab, scores=scores, bos_id=5, add_bos=False,
        eos_token_ids=[], chat_template=None, max_token_length=3,
    )
    tok = Tokenizer(data)

    def python_encode(text):
        saved = tok._encode_native
        tok._encode_native = lambda raw, sp, bos: None
        try:
            return tok.encode(text)
        finally:
            tok._encode_native = saved

    for text in ("abc", "abcabc", "abcbcab", "aabbcc", "cabcab"):
        got = tok.encode(text)
        want = python_encode(text)
        assert got == want, (text, got, want)
    # the canonical tie: leftmost pair wins
    assert tok.encode("abc") == [3, 2]  # [ab, c]
