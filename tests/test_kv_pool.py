"""Paged KV pool with cross-lane radix prefix sharing (ISSUE 6).

Unit layers bottom-up: PagePool refcount/free-list invariants, RadixTree
match/insert/split/LRU-eviction, the paged gather/scatter/view helpers
(QuantKV included), the paged flash decode kernel (interpret mode) — then
the device seam: engine publish -> adopt round trips are byte-identical
to fresh prefill (full pages, partial-tail + chunked suffix resume, int8
KV pool), and the PagedKVManager's dedup/COW/eviction accounting on top.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.kv import MatchResult, PagePool, RadixTree
from dllama_tpu.kv.pool import SCRATCH_PAGE
from dllama_tpu.ops.kv_cache import (
    QuantKV,
    dequant_kv,
    gather_pages,
    paged_view,
    quantize_kv_rows,
    scatter_pages,
)

from helpers import make_tiny_model

PS = 4  # page size used across the host-side tests


# -- PagePool -----------------------------------------------------------------


@pytest.mark.fast
def test_page_pool_invariants():
    events = []
    pool = PagePool(8, PS, on_event=lambda k, p: events.append((k, p)))
    st = pool.stats()
    assert st.total == 7 and st.free == 7 and st.used == 0  # scratch excluded

    a = pool.alloc(3)
    assert len(a) == 3 and SCRATCH_PAGE not in a
    assert all(pool.refcount(p) == 1 for p in a)
    pool.check()

    # retain -> shared; release -> back to tree-only; refcounts exact
    pool.retain(a)
    assert pool.stats().shared == 3
    assert all(pool.refcount(p) == 2 for p in a)
    assert pool.release(a) == 0  # still referenced once
    assert pool.stats().shared == 0 and pool.stats().used == 3

    # fork: a COW alloc, counted
    f = pool.fork(a[0])
    assert f not in a and pool.refcount(f) == 1
    assert pool.stats().cow_forks == 1
    assert any(k == "kv_cow_fork" for k, _ in events)

    # exhaustion raises without corrupting state
    rest = pool.alloc(pool.free_pages)
    with pytest.raises(MemoryError):
        pool.alloc(1)
    pool.check()

    # full release drains back to an all-free pool
    freed = pool.release(a + [f] + rest)
    assert freed == 7 and pool.free_pages == 7
    pool.check()

    # LIFO free list: the last freed page is reused first
    x = pool.alloc(1)[0]
    pool.release([x])
    assert pool.alloc(1)[0] == x

    # invalid ops surface loudly
    with pytest.raises(KeyError):
        pool.release([SCRATCH_PAGE])
    with pytest.raises(KeyError):
        pool.retain([999])
    with pytest.raises(KeyError):
        pool.fork(SCRATCH_PAGE)  # padded page-id vectors must not leak in

    pool.reset()
    assert pool.free_pages == 7 and pool.stats().used == 0
    assert pool.stats().cow_forks == 1  # cumulative telemetry survives reset
    assert any(k == "kv_page_alloc" for k, _ in events)
    assert any(k == "kv_page_free" for k, _ in events)


# -- RadixTree ----------------------------------------------------------------


def _seq(*chunks):
    out = []
    for c in chunks:
        out.extend(c)
    return out


@pytest.mark.fast
def test_radix_match_insert_split():
    pool = PagePool(32, PS)
    tree = RadixTree(PS)
    assert tree.match([1, 2, 3]) == MatchResult(0, [])

    # store A = 3 pages
    A = _seq([1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12])
    pa = pool.alloc(3)
    tree.insert(A, pa, first_slot=0)
    assert tree.n_pages == 3 and tree.token_count() == 12

    # exact + partial-final-page matches collect pages in slot order
    m = tree.match(A)
    assert m.n_tokens == 12 and m.pages == pa
    m = tree.match(A[:6] + [99])  # diverges mid page 1
    assert m.n_tokens == 6 and m.pages == pa  # stale-tail pages included
    m = tree.match(A + [13, 14])  # query longer than stored
    assert m.n_tokens == 12 and m.pages == pa

    # store B sharing pages 0-1, new final page: edge splits, the shared
    # pages move to the split head, dedup'd insert attaches only slot 2
    B = A[:8] + [20, 21, 22, 23]
    mb = tree.match(B)
    assert mb.n_tokens == 8 and mb.pages == pa
    pb = pool.alloc(1)
    tree.insert(B, pb, first_slot=2)
    assert tree.n_pages == 4
    assert tree.match(A).pages == pa
    assert tree.match(B).pages == pa[:2] + pb
    # mid-page divergence against BOTH: shares only slot 0's span + 2 toks
    C = A[:6] + [50, 51]
    mc = tree.match(C)
    assert mc.n_tokens == 6 and mc.pages[0] == pa[0]
    pool.check()


@pytest.mark.fast
def test_radix_lru_eviction_respects_refcounts():
    pool = PagePool(16, PS)
    tree = RadixTree(PS)
    seqs = {}
    for i in range(3):
        s = [100 * i + j for j in range(8)]  # 2 pages each, disjoint
        seqs[i] = (s, pool.alloc(2))
        tree.insert(s, seqs[i][1], first_slot=0)
    assert tree.n_pages == 6

    # touch 0 and 2: sequence 1 is LRU
    tree.match(seqs[0][0])
    tree.match(seqs[2][0])
    freed = tree.evict(1, pool)
    assert freed == 2  # leaf granularity: the whole LRU leaf goes
    assert tree.match(seqs[1][0]).n_tokens == 0
    assert tree.match(seqs[0][0]).n_tokens == 8

    # a lane-retained (refcount 2) leaf is NOT evictable; the next LRU is
    pool.retain(seqs[0][1])
    tree.match(seqs[0][0])  # 0 is now MRU anyway; make 2 LRU explicit
    freed = tree.evict(4, pool)
    assert freed == 2  # only sequence 2's leaf could go
    assert tree.match(seqs[0][0]).n_tokens == 8
    assert tree.n_pages == 2
    pool.release(seqs[0][1])
    # clear releases the tree's remaining pages back to the pool
    tree.clear(pool)
    assert pool.free_pages == 15
    pool.check()


@pytest.mark.fast
def test_radix_insert_rejects_gapped_path():
    """insert(first_slot=k) whose dedup'd lower slots are NOT stored
    (e.g. the matched leaf was evicted after the caller's match) must
    raise before mutating anything, never build a token path with no
    pages behind its early positions."""
    pool = PagePool(8, PS)
    tree = RadixTree(PS)
    A = [1, 2, 3, 4, 5, 6, 7, 8]
    pa = pool.alloc(1)
    with pytest.raises(ValueError):
        tree.insert(A, pa, first_slot=1)  # slot 0 was never stored
    assert tree.node_count() == 0 and tree.n_pages == 0

    # ...and with a stored-but-too-short prefix it still refuses
    tree.insert(A[:4], pa, first_slot=0)
    pb = pool.alloc(1)
    B = A + [9, 10, 11, 12]
    with pytest.raises(ValueError):
        tree.insert(B, pb, first_slot=2)  # slot 1 missing from the path
    assert tree.n_pages == 1
    pool.check()


@pytest.mark.fast
def test_radix_evict_collapses_dead_ancestors():
    """Evicting a leaf must also remove now-childless, pageless
    ancestors: left behind they are match()-able token spans with no
    pages, inflating node/token counts until the next pressure event."""
    pool = PagePool(16, PS)
    tree = RadixTree(PS)
    A = [1, 2, 3, 4, 5, 6, 7, 8]
    B = [1, 2, 30, 40, 50, 60, 70, 80]  # splits A's first edge at offset 2
    tree.insert(A, pool.alloc(2), first_slot=0)
    tree.insert(B, pool.alloc(2), first_slot=0)
    # the split head [1, 2] holds no pages (no slot ends inside it)
    assert tree.evict(4, pool) == 4
    assert tree.node_count() == 0 and tree.token_count() == 0
    assert tree.n_pages == 0
    assert tree.match(A) == MatchResult(0, [])
    pool.check()


# -- PagedKVManager host accounting (no device) -------------------------------


class _StubEngine:
    """Host-accounting-only stand-in: the manager's match/publish
    bookkeeping races need no device to reproduce."""

    kv_pool_epoch = 0

    def init_kv_pool(self, page_size, n_pages, native=False):
        return n_pages

    def kv_adopt(self, lane, pages):
        pass

    def kv_publish(self, lane, pages, start_page):
        pass

    def reset_kv_pool(self):
        pass


@pytest.mark.fast
def test_publish_pressure_pins_matched_prefix():
    """Regression: a publish extending a stored prefix under pool
    pressure must not LRU-evict that prefix's own refcount-1 leaf out
    from under its MatchResult — previously the stale ``mr`` made
    insert rebuild a gapped token path and later matches returned
    suffix pages as if they covered slot 0 (cross-request KV
    corruption)."""
    from dllama_tpu.kv.manager import PagedKVManager

    kv = PagedKVManager(_StubEngine(), page_size=PS, n_pages=6)  # 5 usable
    A = [10 + i for i in range(8)]  # 2 pages, tree-only (refcount 1)
    assert kv.publish(0, A) == 2
    pa = kv.tree.match(A).pages

    # B extends A by 4 pages: 3 free, 1 short — and the ONLY refcount-1
    # leaf is A's own, which this publish just matched. It must be
    # pinned: eviction frees nothing and the publish is skipped whole.
    B = A + [60 + i for i in range(16)]
    assert kv.publish(1, B) == 0
    m = kv.tree.match(A)
    assert m.n_tokens == 8 and m.pages == pa  # prefix intact, same pages
    assert kv.tree.match(B).n_tokens == 8  # only the old prefix stored
    kv.check()

    # the pin was transient: a fitting publish still works afterwards
    C = [200 + i for i in range(8)]
    assert kv.publish(0, C) == 2
    kv.check()


@pytest.mark.fast
def test_match_retains_pages_until_release():
    """Regression: match() must pin the returned pages immediately —
    the scheduler runs the adopt copy a full tick after the match, and
    another lane's publish->evict in that gap previously freed and
    reallocated the refcount-1 pages, copying an unrelated sequence's
    KV into the new lane's prefix rows."""
    from dllama_tpu.kv.manager import PagedKVManager

    kv = PagedKVManager(_StubEngine(), page_size=PS, n_pages=6)  # 5 usable
    A = [10 + i for i in range(8)]  # 2 pages, tree-only
    assert kv.publish(0, A) == 2
    m, pages = kv.match(1, A + [9])
    assert m == 8 and pages == kv.tree.match(A).pages
    assert all(kv.pool.refcount(p) == 2 for p in pages)  # pinned NOW

    # another lane publishes in the match->adopt gap, filling the pool
    # and then forcing an eviction: the pinned pages are untouchable,
    # the pressure lands on the other leaf instead
    D = [90 + i for i in range(12)]
    assert kv.publish(0, D) == 3  # pool now full
    E = [300 + i for i in range(4)]
    assert kv.publish(0, E) == 1  # evicts D's leaf, never A's
    assert kv.tree.match(D).n_tokens == 0
    assert kv.tree.match(A).pages == pages
    assert all(kv.pool.refcount(p) == 2 for p in pages)

    kv.adopt(1, pages)  # device copy only: no double retain
    assert all(kv.pool.refcount(p) == 2 for p in pages)
    kv.release_lane(1)  # the single release path drops the match pin
    assert all(kv.pool.refcount(p) == 1 for p in pages)
    kv.check()


# -- paged gather/scatter/view helpers ---------------------------------------


@pytest.mark.fast
def test_gather_scatter_paged_view_roundtrip():
    rng = np.random.default_rng(0)
    P, KH, ps, hd = 6, 2, 4, 8
    pool_l = jnp.asarray(rng.normal(size=(P, KH, ps, hd)), jnp.float32)
    ids = jnp.asarray([3, 1, 4], jnp.int32)

    rows = gather_pages(pool_l, ids)
    assert rows.shape == (KH, 3 * ps, hd)
    # row (slot s, offset o) is page ids[s] row o
    np.testing.assert_array_equal(
        np.asarray(rows[:, ps: 2 * ps]), np.asarray(pool_l[1])
    )
    back = scatter_pages(jnp.zeros_like(pool_l), ids, rows)
    np.testing.assert_array_equal(
        np.asarray(back[np.asarray(ids)]), np.asarray(pool_l[np.asarray(ids)])
    )

    # QuantKV pools round-trip bytes and dequantize through paged_view
    dense = jnp.asarray(rng.normal(size=(KH, 3 * ps, hd)), jnp.float32)
    qv, qs = quantize_kv_rows(dense)
    qpool = QuantKV(
        jnp.zeros((P, KH, ps, hd), jnp.int8),
        jnp.ones((P, KH, ps, 1), jnp.float32),
    )
    qpool = scatter_pages(qpool, ids, QuantKV(qv, qs))
    got = gather_pages(qpool, ids)
    np.testing.assert_array_equal(np.asarray(got.q), np.asarray(qv))
    view = paged_view(qpool, ids, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(view), np.asarray(dequant_kv(QuantKV(qv, qs), jnp.float32)),
        rtol=0, atol=0,
    )


# -- paged flash decode kernel (interpret mode) -------------------------------


def _ref_attention(q, k, v, pos):
    """[B,1,H,hd] x per-lane [KH, S, hd] causal reference."""
    b, _, h, hd = q.shape
    kh = k[0].shape[0]
    g = h // kh
    out = np.zeros_like(np.asarray(q))
    for lane in range(b):
        for head in range(h):
            qh = np.asarray(q[lane, 0, head], np.float32)
            kk = np.asarray(k[lane][head // g], np.float32)[: pos[lane] + 1]
            vv = np.asarray(v[lane][head // g], np.float32)[: pos[lane] + 1]
            s = kk @ qh / np.sqrt(hd)
            w = np.exp(s - s.max())
            w /= w.sum()
            out[lane, 0, head] = w @ vv
    return out


@pytest.mark.fast
@pytest.mark.parametrize("quant", [False, True])
def test_paged_flash_decode_matches_dense(quant):
    from dllama_tpu.ops.flash_attention import paged_flash_decode

    rng = np.random.default_rng(1)
    B, H, KH, hd, ps, P = 2, 4, 2, 16, 4, 10
    n_blocks = 4  # 16 positions of logical window per lane
    q = jnp.asarray(rng.normal(size=(B, 1, H, hd)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(P, KH, ps, hd)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(P, KH, ps, hd)), jnp.float32)
    # lane 0 and lane 1 SHARE physical pages 3,4 for their first two
    # blocks — the cross-lane sharing read path; padding slots point at
    # the scratch page and sit beyond each lane's causal frontier
    pt = jnp.asarray([[3, 4, 5, 0], [3, 4, 7, 8]], jnp.int32)
    pos = jnp.asarray([9, 14], jnp.int32)

    if quant:
        kq = QuantKV(*quantize_kv_rows(kp.reshape(P * KH * ps, hd))[:2])
        kq = QuantKV(kq.q.reshape(P, KH, ps, hd), kq.s.reshape(P, KH, ps, 1))
        vq = QuantKV(*quantize_kv_rows(vp.reshape(P * KH * ps, hd))[:2])
        vq = QuantKV(vq.q.reshape(P, KH, ps, hd), vq.s.reshape(P, KH, ps, 1))
        out = paged_flash_decode(q, kq, vq, pt, pos, interpret=True)
        kd = dequant_kv(kq, jnp.float32)
        vd = dequant_kv(vq, jnp.float32)
    else:
        out = paged_flash_decode(q, kp, vp, pt, pos, interpret=True)
        kd, vd = kp, vp

    k_lanes = [gather_pages(kd, pt[lane]) for lane in range(B)]
    v_lanes = [gather_pages(vd, pt[lane]) for lane in range(B)]
    ref = _ref_attention(q, k_lanes, v_lanes, np.asarray(pos))
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


# -- engine seam: publish -> adopt byte parity --------------------------------


CFG = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=4, n_kv_heads=2,
           head_dim=16, vocab_size=256, seq_len=64)


@pytest.fixture(scope="module")
def tiny_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("kvpool")
    mp = str(d / "m.m")
    make_tiny_model(mp, cfg=CFG)
    return mp


def _stream(e, lane, token, pos, steps, seed):
    """Seeded single-lane decode stream (other lane parked): per-lane
    (seed, position) keys make it depend on nothing else."""
    toks, t, p = [], token, pos
    active = [i == lane for i in range(e.batch_size)]
    while len(toks) < steps:
        n = min(4, steps - len(toks))
        rows = e.decode_lanes(
            [t if i == lane else 0 for i in range(e.batch_size)],
            [p if i == lane else 0 for i in range(e.batch_size)],
            n, active,
            [0.8] * e.batch_size, [0.9] * e.batch_size,
            seeds=[seed if i == lane else None for i in range(e.batch_size)],
        )
        toks.extend(r[lane] for r in rows)
        t, p = toks[-1], p + n
    return toks


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_engine_publish_adopt_parity(tiny_model, kv_dtype):
    """KV published from one lane and adopted into ANOTHER produces the
    byte-identical seeded stream a fresh prefill would: full-page
    adoption, and partial-tail adoption resumed by chunked suffix
    prefill (the scheduler's mid-page path). int8 pools round-trip the
    quantized bytes + scales through the same programs."""
    from dllama_tpu.runtime.engine import InferenceEngine

    kw = {"kv_dtype": kv_dtype} if kv_dtype else {}
    e = InferenceEngine(
        tiny_model, tp=1, dtype=jnp.float32, temperature=0.8, batch_size=2,
        **kw,
    )
    ps = 4
    e.init_kv_pool(ps, n_pages=16)
    prompt = [2 + (i * 7) % 250 for i in range(23)]  # 22 fills: 5.5 pages

    # fresh reference on lane 1
    e.prefill_lane(1, prompt, pos0=0)
    expected = _stream(e, 1, prompt[-1], len(prompt) - 1, 10, seed=42)

    # lane 0 prefills the same prompt and publishes its 5 full pages
    e.prefill_lane(0, prompt, pos0=0)
    pages = [1, 2, 3, 4, 5]
    e.kv_publish(0, pages, start_page=0)

    # a later "admission" on lane 1: adopt rows [0, 20), chunk-prefill the
    # unmatched suffix fills [20, 22), decode — byte parity required
    e.reset()
    e.kv_adopt(1, pages)
    fills, cur = prompt[:-1], 20
    while cur < len(fills):
        cur += e.prefill_lane_chunk(1, fills[cur:], cur, budget=8)
    got = _stream(e, 1, prompt[-1], len(prompt) - 1, 10, seed=42)
    assert got == expected

    # whole-prefix adoption parity too (no suffix prefill at all): a
    # 21-token prompt has exactly 5 pages of fills
    p21 = prompt[:21]
    e.reset()
    e.prefill_lane(0, p21, pos0=0)
    exp21 = _stream(e, 0, p21[-1], 20, 8, seed=7)
    e.reset()
    e.kv_adopt(0, pages)  # pages hold fills[0:20] == p21[:-1]'s rows
    got21 = _stream(e, 0, p21[-1], 20, 8, seed=7)
    assert got21 == exp21

    # pool survives engine cache resets/epochs: adopt still works after
    # the cache buffer was rebuilt (pool is never donated by decode)
    e.reset()
    e.kv_adopt(1, pages)
    assert _stream(e, 1, p21[-1], 20, 8, seed=7) == exp21


def test_manager_dedup_cow_and_eviction(tiny_model):
    """PagedKVManager accounting over a live engine: repeat publishes
    dedup to zero new pages (the stored-once guarantee), a mid-page
    divergence COW-forks exactly one page, lane retains block eviction
    until released, and pool pressure LRU-evicts tree leaves."""
    from dllama_tpu.kv.manager import PagedKVManager
    from dllama_tpu.runtime.engine import InferenceEngine

    e = InferenceEngine(
        tiny_model, tp=1, dtype=jnp.float32, temperature=0.0, batch_size=2,
    )
    kv = PagedKVManager(e, page_size=4, n_pages=10)  # 9 usable pages
    ps = kv.page_size

    A = [10 + i for i in range(16)]  # 4 pages
    e.prefill_lane(0, A + [9], pos0=0)  # fills == A
    assert kv.publish(0, A) == 4
    used = kv.pool.stats().used
    assert used == 4 and kv.tree.n_pages == 4

    # stored once: the same tokens publish zero new pages from any lane
    e.prefill_lane(1, A + [9], pos0=0)
    assert kv.publish(1, A) == 0
    assert kv.pool.stats().used == used

    # match pins shared pages for the lane on the spot; adopt is only
    # the device copy; gauges see refcount >= 2
    m, pages = kv.match(0, A + [9])
    assert m == 16 and pages == kv.tree.match(A).pages
    assert kv.pool.stats().shared == 4
    kv.adopt(0, pages)
    assert kv.pool.stats().shared == 4

    # mid-page divergence: B shares 6 tokens (1.5 pages) -> k_shared=1,
    # the divergent page COW-forks, the rest alloc fresh
    B = A[:6] + [200, 201] + [210 + i for i in range(4)]  # 12 toks, 3 pages
    e.prefill_lane(1, B + [9], pos0=0)
    cow0 = kv.pool.stats().cow_forks
    assert kv.publish(1, B) == 2
    assert kv.pool.stats().cow_forks == cow0 + 1
    mb = kv.tree.match(B)
    assert mb.n_tokens == 12
    assert mb.pages[0] == kv.tree.match(A).pages[0]  # slot 0 shared
    assert mb.pages[1] != kv.tree.match(A).pages[1]  # slot 1 forked

    # pool pressure: 4 + 2 used, 3 free of 9. A 4-page publish must evict
    # the LRU unreferenced leaf — but A's pages are lane-retained, so B's
    # tail goes instead
    C = [300 + i for i in range(16)]
    e.prefill_lane(1, C + [9], pos0=0)
    b_ev = kv.c_evictions.value
    assert kv.publish(1, C) == 4
    assert kv.c_evictions.value > b_ev
    assert kv.tree.match(A).n_tokens == 16  # retained: survived
    assert kv.tree.match(B).n_tokens < 12  # evicted (shared head remains)
    kv.check()

    # release the lane; a full reset leaves a clean pool
    kv.release_lane(0)
    assert kv.pool.stats().shared == 0
    dbg = kv.debug()
    assert dbg["pool"]["free"] + dbg["pool"]["used"] == dbg["pool"]["total"]
    assert dbg["radix"]["pages"] == dbg["pool"]["used"]
    kv.reset()
    assert kv.pool.stats().used == 0 and kv.tree.n_pages == 0
    kv.check()


def test_manager_publish_failure_narrows_to_culprit(tiny_model, monkeypatch):
    """A TRANSIENT publish-dispatch failure (pool epoch unchanged: the
    donated buffer was never touched) must release only that publish's
    freshly-allocated pages — survivors' stored prefixes stay intact
    and matchable. Only a POISONING failure (the engine guard rebuilt
    the pool, epoch moved) drops the whole host accounting."""
    from dllama_tpu.kv.manager import PagedKVManager
    from dllama_tpu.runtime.engine import InferenceEngine

    e = InferenceEngine(
        tiny_model, tp=1, dtype=jnp.float32, temperature=0.0, batch_size=2,
    )
    kv = PagedKVManager(e, page_size=4, n_pages=8)
    A = [10 + i for i in range(8)]
    e.prefill_lane(0, A + [9], pos0=0)
    assert kv.publish(0, A) == 2
    pa = kv.tree.match(A).pages
    used0 = kv.pool.stats().used

    def boom(*a, **k):
        raise RuntimeError("injected publish failure")

    monkeypatch.setattr(e, "kv_publish", boom)
    B = [50 + i for i in range(8)]
    assert kv.publish(0, B) == 0  # swallowed, not raised
    # survivor intact: A's leaf and pages untouched, B's fresh pages freed
    assert kv.tree.match(A).n_tokens == 8 and kv.tree.match(A).pages == pa
    assert kv.pool.stats().used == used0
    assert kv.tree.match(B).n_tokens == 0
    kv.check()

    # poisoning failure: the dispatch guard rebuilt the pool buffer and
    # bumped the epoch — every page's device contents are gone, so the
    # host accounting (A included) must drop with them
    def boom_poison(*a, **k):
        e.kv_pool_epoch += 1
        raise RuntimeError("injected poisoning failure")

    monkeypatch.setattr(e, "kv_publish", boom_poison)
    C = [90 + i for i in range(8)]
    assert kv.publish(0, C) == 0
    assert kv.tree.n_pages == 0 and kv.pool.stats().used == 0  # full reset
    kv.check()
