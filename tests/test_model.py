"""Model forward-pass tests: JAX model vs independent numpy oracle, plus
prefill/decode consistency invariants."""

from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.formats import FloatType, ModelReader
from dllama_tpu.formats.model_file import LlmArch
from dllama_tpu.models import forward, init_kv_cache, load_params

from helpers import make_tiny_model
from numpy_model import numpy_forward

TOKENS = [3, 17, 92, 5, 44, 120, 7, 3]


def build(tmp_path, arch=LlmArch.LLAMA, weight_type=FloatType.F32, **kw):
    path = str(tmp_path / "m.m")
    tensors = make_tiny_model(path, arch=arch, weight_type=weight_type, **kw)
    reader = ModelReader(path)
    params = load_params(reader)
    return reader.header, params, tensors


@pytest.mark.parametrize(
    "arch", [LlmArch.LLAMA, LlmArch.QWEN3, LlmArch.QWEN3_MOE]
)
def test_forward_matches_numpy_oracle(tmp_path, arch):
    h, params, tensors = build(tmp_path, arch=arch)
    tokens = jnp.asarray([TOKENS], dtype=jnp.int32)
    cache = init_kv_cache(h, batch_size=1)
    logits, _ = forward(params, h, tokens, jnp.int32(0), cache)
    expected = numpy_forward(tensors, h, TOKENS)
    np.testing.assert_allclose(
        np.asarray(logits)[0], expected, rtol=2e-4, atol=2e-4
    )


def test_forward_llama31_rope_scaling(tmp_path):
    h, params, tensors = build(tmp_path, rope_scaling=True)
    assert h.rope_scaling_factor == 8.0
    tokens = jnp.asarray([TOKENS], dtype=jnp.int32)
    cache = init_kv_cache(h, batch_size=1)
    logits, _ = forward(params, h, tokens, jnp.int32(0), cache)
    expected = numpy_forward(tensors, h, TOKENS)
    np.testing.assert_allclose(
        np.asarray(logits)[0], expected, rtol=2e-4, atol=2e-4
    )


def test_decode_matches_prefill(tmp_path):
    """Feeding tokens one-at-a-time through the cache must reproduce the
    full-prefill logits (the reference's decode loop is exactly this)."""
    h, params, _ = build(tmp_path)
    tokens = jnp.asarray([TOKENS], dtype=jnp.int32)
    cache = init_kv_cache(h, batch_size=1)
    full_logits, _ = forward(params, h, tokens, jnp.int32(0), cache)

    cache = init_kv_cache(h, batch_size=1)
    step_logits = []
    for i, t in enumerate(TOKENS):
        lg, cache = forward(
            params, h, jnp.asarray([[t]], dtype=jnp.int32), jnp.int32(i), cache
        )
        step_logits.append(np.asarray(lg)[0, 0])
    np.testing.assert_allclose(
        np.asarray(full_logits)[0], np.stack(step_logits), rtol=1e-4, atol=1e-4
    )


def test_chunked_prefill_matches_full(tmp_path):
    """Prefill in chunks (the reference's nBatches chunking) == one shot."""
    h, params, _ = build(tmp_path)
    tokens = jnp.asarray([TOKENS], dtype=jnp.int32)
    cache = init_kv_cache(h, batch_size=1)
    full_logits, _ = forward(params, h, tokens, jnp.int32(0), cache)

    cache = init_kv_cache(h, batch_size=1)
    lg1, cache = forward(params, h, tokens[:, :5], jnp.int32(0), cache)
    lg2, cache = forward(params, h, tokens[:, 5:], jnp.int32(5), cache)
    chunked = np.concatenate([np.asarray(lg1), np.asarray(lg2)], axis=1)
    np.testing.assert_allclose(
        np.asarray(full_logits), chunked, rtol=1e-4, atol=1e-4
    )


def test_q40_load_path_matches_oracle(tmp_path):
    """The Q40 model must match the numpy oracle fed the *dequantized*
    tensors exactly — isolates the load path from quantization noise
    (quality itself is validated end-to-end by perplexity mode)."""
    path40 = str(tmp_path / "q40.m")
    make_tiny_model(path40, weight_type=FloatType.Q40, seed=9)
    r40 = ModelReader(path40)
    dequant = {s.name: r40.dense_f32(s.name) for s in r40.specs}
    p40 = load_params(r40)
    tokens = jnp.asarray([TOKENS], dtype=jnp.int32)
    lg40, _ = forward(p40, r40.header, tokens, jnp.int32(0), init_kv_cache(r40.header, 1))
    expected = numpy_forward(dequant, r40.header, TOKENS)
    np.testing.assert_allclose(
        np.asarray(lg40)[0], expected, rtol=2e-4, atol=2e-4
    )


def test_batch_axis(tmp_path):
    """Two identical sequences in the batch produce identical logits."""
    h, params, _ = build(tmp_path)
    tokens = jnp.asarray([TOKENS, TOKENS], dtype=jnp.int32)
    cache = init_kv_cache(h, batch_size=2)
    logits, _ = forward(params, h, tokens, jnp.int32(0), cache)
    np.testing.assert_allclose(
        np.asarray(logits)[0], np.asarray(logits)[1], rtol=1e-6, atol=1e-6
    )


def test_logits_mode_last_matches_all(tmp_path):
    """logits_mode='last' must equal the full computation's final row and
    produce the identical updated cache (prefill chunks only sample from
    their last row; the vocab matmul on the other rows is skipped)."""
    h, params, _ = build(tmp_path)
    tokens = jnp.asarray([TOKENS], dtype=jnp.int32)
    cache_a = init_kv_cache(h, batch_size=1)
    logits_all, cache_all = forward(params, h, tokens, jnp.int32(0), cache_a)
    cache_b = init_kv_cache(h, batch_size=1)
    logits_last, cache_last = forward(
        params, h, tokens, jnp.int32(0), cache_b, logits_mode="last"
    )
    assert logits_last.shape == (1, 1, h.vocab_size)
    np.testing.assert_allclose(
        np.asarray(logits_last)[:, 0], np.asarray(logits_all)[:, -1],
        rtol=1e-6, atol=1e-6,
    )
    np.testing.assert_array_equal(
        np.asarray(cache_last["k"]), np.asarray(cache_all["k"])
    )
    np.testing.assert_array_equal(
        np.asarray(cache_last["v"]), np.asarray(cache_all["v"])
    )


def test_forward_parked_lane_isolation(tmp_path):
    """Per-lane forward with a parked lane (attn_park_threshold): the
    active lane's logits must equal a solo run, the parked lane's writes
    must land only in the padding rows, and its masked attention output
    must be finite."""
    h, params, _ = build(tmp_path)
    s = h.seq_len
    pad = 8
    park = s  # first padding row
    # solo reference: one lane at pos 3
    cache1 = init_kv_cache(h, batch_size=1, seq_len=s + pad)
    tok = jnp.asarray([[7, 9]], dtype=jnp.int32)
    # seed the cache with a short prefix so attention has context
    logits1, cache1 = forward(params, h, tok, jnp.int32(3), cache1)

    cache2 = init_kv_cache(h, batch_size=2, seq_len=s + pad)
    tok2 = jnp.asarray([[7, 9], [1, 2]], dtype=jnp.int32)
    posv = jnp.asarray([3, park], jnp.int32)
    logits2, cache2 = forward(
        params, h, tok2, posv, cache2, attn_park_threshold=park
    )
    np.testing.assert_allclose(
        np.asarray(logits2)[0], np.asarray(logits1)[0], rtol=1e-5, atol=1e-5
    )
    assert np.isfinite(np.asarray(logits2)[1]).all()
    # parked lane wrote ONLY padding rows: its real cache region is zeros
    k2 = np.asarray(cache2["k"])  # [L, B, KH, S+pad, hd]
    assert np.abs(k2[:, 1, :, :s]).max() == 0.0
    assert np.abs(k2[:, 1, :, s : s + 2]).max() > 0.0  # parked writes landed


def test_moe_gather_decode_matches_dense_routing(tmp_path):
    """The decode-path gather MoE (active experts only) must reproduce the
    dense-routing MoE logits exactly: decode T=1 steps vs full prefill."""
    h, params, _ = build(tmp_path, arch=LlmArch.QWEN3_MOE)
    tokens = jnp.asarray([TOKENS], dtype=jnp.int32)
    cache = init_kv_cache(h, batch_size=1)
    # prefill uses dense routing (T=8 > 4)
    full_logits, _ = forward(params, h, tokens, jnp.int32(0), cache)

    # step-by-step decode with the gather path forced on (T=1)
    cache = init_kv_cache(h, batch_size=1)
    step_logits = []
    for i, t in enumerate(TOKENS):
        lg, cache = forward(
            params, h, jnp.asarray([[t]], dtype=jnp.int32), jnp.int32(i), cache,
            moe_gather_max_tokens=4,
        )
        step_logits.append(np.asarray(lg)[0, 0])
    np.testing.assert_allclose(
        np.asarray(full_logits)[0], np.stack(step_logits), rtol=1e-4, atol=1e-4
    )


def test_fused_load_no_mesh_matches_unfused(tmp_path):
    """Params loaded with fuse=2 (tp-interleaved wqkv/w13) run through
    forward with NO mesh must still match the unfused load bit-for-policy:
    the un-interleave factor is the FusedQuantWeight's own static
    metadata, not the mesh's tp, so a fused-load/mesh mismatch cannot
    mis-permute columns."""
    path = str(tmp_path / "m.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=5)
    r = ModelReader(path)
    p_split = load_params(r, weight_format="q40")
    p_fused = load_params(r, weight_format="q40", fuse=2)
    assert "wqkv" in p_fused["layers"] and "w13" in p_fused["layers"]
    tokens = jnp.asarray([TOKENS], dtype=jnp.int32)
    lg_s, _ = forward(
        p_split, r.header, tokens, jnp.int32(0), init_kv_cache(r.header, 1)
    )
    lg_f, _ = forward(
        p_fused, r.header, tokens, jnp.int32(0), init_kv_cache(r.header, 1)
    )
    np.testing.assert_allclose(
        np.asarray(lg_f), np.asarray(lg_s), rtol=1e-5, atol=1e-5
    )


def test_fused_load_indivisible_tp_fails_loudly(tmp_path):
    """fuse that does not divide a constituent's out dim must raise at
    load time, not drop trailing columns."""
    path = str(tmp_path / "m.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=5)
    r = ModelReader(path)
    with pytest.raises(ValueError, match="not divisible"):
        load_params(r, weight_format="q40", fuse=3)  # kv_dim=32 % 3 != 0


def test_streamed_load_matches_stack(tmp_path):
    """The streaming loader (shard-by-shard make_array_from_callback over
    ranged memmap reads) must produce leaf-identical params to the
    host-stack path, for plain, FUSED and MoE-expert Q40 stacks."""
    import os

    import jax
    from jax.sharding import PartitionSpec as P

    from dllama_tpu.formats.model_file import LlmArch
    from dllama_tpu.parallel import make_mesh, shard_params_put

    def load(path, arch, mesh, fuse):
        r = ModelReader(path)
        return load_params(
            r, weight_format="q40", put=shard_params_put(mesh, r.header),
            fuse=fuse,
        )

    # q40-over-tp=2 needs every contraction dim divisible by 32*tp
    dense_cfg = dict(dim=64, hidden_dim=128, n_layers=3, n_heads=4,
                     n_kv_heads=2, head_dim=16, vocab_size=256, seq_len=64)
    moe_cfg = dict(dim=64, hidden_dim=128, moe_hidden_dim=64, n_layers=2,
                   n_heads=4, n_kv_heads=2, head_dim=16, vocab_size=256,
                   seq_len=64, n_experts=4, n_active_experts=2)
    cases = [
        ("plain.m", LlmArch.LLAMA, dense_cfg, 0, make_mesh(tp=2, dp=2)),
        ("fused.m", LlmArch.LLAMA, dense_cfg, 2, make_mesh(tp=2, dp=2)),
        ("moe.m", LlmArch.QWEN3_MOE, moe_cfg, 0, make_mesh(tp=2, dp=2)),
        # pp: the one mesh where the lead (layer) axis slicing is
        # non-trivial — a mis-ordered stage range would pass tp/dp-only
        ("pp.m", LlmArch.LLAMA, dict(dense_cfg, n_layers=4), 2,
         make_mesh(tp=2, pp=2)),
    ]
    for fname, arch, cfg, fuse, mesh in cases:
        path = str(tmp_path / fname)
        make_tiny_model(path, arch=arch, weight_type=FloatType.Q40, cfg=cfg)
        os.environ["DLLAMA_STREAM_LOAD"] = "0"
        try:
            stacked = load(path, arch, mesh, fuse)
        finally:
            del os.environ["DLLAMA_STREAM_LOAD"]
        streamed = load(path, arch, mesh, fuse)
        ls, lt = jax.tree.leaves(streamed), jax.tree.leaves(stacked)
        assert len(ls) == len(lt)
        for a, b in zip(ls, lt):
            assert a.shape == b.shape and a.dtype == b.dtype
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=fname
            )


@pytest.mark.slow
def test_streamed_loader_memory_bound(tmp_path):
    """The 70B fit story's loader half (VERDICT r4 #2): streaming load of
    a model with REAL Llama-70B layer dims (8192 dim / 28672 ffn; vocab
    shrunk so embed doesn't dominate a CI run) must keep the host
    high-water mark near the device bytes — NOT device + whole host
    layer stacks, which is what the pre-r5 np.stack loader cost (at 80
    layers the w13 stack alone is ~37 GB). Measured as subprocess VmHWM,
    streamed vs forced-stack."""
    import json
    import os
    import subprocess
    import sys as _sys

    from dllama_tpu.models.synthetic import write_synth_model

    cfg = dict(dim=8192, hidden_dim=28672, n_layers=4, n_heads=64,
               n_kv_heads=8, head_dim=128, vocab_size=8192, seq_len=2048)
    path = str(tmp_path / "big.m")
    write_synth_model(path, cfg, max_seq_len=2048)

    def probe(stream: str) -> dict:
        out = subprocess.run(
            [_sys.executable,
             str(Path(__file__).parent / "loader_hwm_probe.py"),
             path, "8", "8", stream],
            capture_output=True, timeout=900, text=True,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    streamed = probe("1")
    stacked = probe("0")
    # the stack path holds every [L, in, out] host stack on top of the
    # device buffers; the streamed path must stay within device bytes +
    # the memmapped FILE (clean file-backed pages count in VmHWM once
    # every byte has been read, though they are evictable under
    # pressure) + interpreter/runtime slack
    file_gb = os.path.getsize(path) / 1e9
    # measured runtime overhead (hwm - device - file) is ~0.3 GB on this
    # fixture; 1.6 keeps the bound far from flaking while still well
    # under the ~1.9 GB biggest host stack the streamed path must avoid
    slack_gb = 1.6
    assert streamed["hwm_gb"] < streamed["device_gb"] + file_gb + slack_gb, (
        streamed, file_gb,
    )
    # and it must beat the stack path by at least the biggest stack
    # (w13: 4 layers x 8192 x 57344 int8 ~ 1.9 GB)
    assert stacked["hwm_gb"] - streamed["hwm_gb"] > 1.0, (stacked, streamed)
