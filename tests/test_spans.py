"""obs/spans.py + obs/slo.py + obs/watchdog.py unit tests.

Pure-Python (no engine) against injected fake clocks, local registries
and local flight recorders, so they ride the fast CI lane and are
deterministic: the span math, the window math and every watchdog stall
rule are driven by hand-advanced time, never by sleeps.
"""

import json
import os

import pytest

from dllama_tpu.obs.metrics import MetricsRegistry
from dllama_tpu.obs.recorder import FlightRecorder
from dllama_tpu.obs.slo import SloTracker, resolve_slo_knobs
from dllama_tpu.obs.spans import SpanTracker
from dllama_tpu.obs.watchdog import EngineWatchdog

pytestmark = pytest.mark.fast


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# -- SpanTracker -------------------------------------------------------------


def test_span_lifecycle_and_attrs():
    clk = FakeClock()
    st = SpanTracker(capacity=16, enabled=True, clock=clk)
    h = st.begin("queue", component="scheduler", request_id="r1", lane=2,
                 n_prompt=7)
    clk.t = 0.25
    st.end(h, reused=3)
    (s,) = st.completed()
    assert s["name"] == "queue"
    assert s["component"] == "scheduler"
    assert s["request_id"] == "r1"
    assert s["lane"] == 2
    assert s["t0"] == 0.0
    assert s["dur_s"] == 0.25
    assert s["attrs"] == {"n_prompt": 7, "reused": 3}
    # idempotent end: the error path racing the normal one records once
    st.end(h)
    assert len(st.completed()) == 1
    assert st.completed(request_id="nope") == []


def test_span_context_manager_records_on_raise():
    clk = FakeClock()
    st = SpanTracker(capacity=4, enabled=True, clock=clk)
    with pytest.raises(RuntimeError):
        with st.span("chunk", request_id="r1"):
            clk.t = 1.5
            raise RuntimeError("engine died")
    (s,) = st.completed()
    assert s["dur_s"] == 1.5  # the error still took the time


def test_span_disabled_is_noop():
    st = SpanTracker(capacity=4, enabled=False)
    assert st.begin("x") is None
    st.end(None)  # call sites never branch on enablement
    with st.span("y") as h:
        assert h is None
    assert st.completed() == []
    assert st.total_recorded == 0


def test_span_ring_overflow_records_event():
    rec = FlightRecorder(capacity=64)
    st = SpanTracker(capacity=2, enabled=True, recorder=rec)
    for _ in range(3):
        st.end(st.begin("s"))
    assert st.total_recorded == 3
    assert st.dropped == 1
    evs = rec.events("obs_overflow")
    assert len(evs) == 1  # first drop fires...
    assert evs[0]["what"] == "span_ring"
    for _ in range(2):
        st.end(st.begin("s"))
    assert st.dropped == 3  # ...then every `capacity` further drops
    assert len(rec.events("obs_overflow")) == 2


def test_chrome_trace_shape_and_roundtrip(tmp_path):
    clk = FakeClock()
    st = SpanTracker(capacity=16, enabled=True, clock=clk)
    h = st.begin("queue", component="scheduler", request_id="r1", lane=0)
    clk.t = 0.001
    st.end(h)
    h = st.begin("decode_lanes", component="engine")
    clk.t = 0.003
    st.end(h)
    trace = st.chrome_trace()
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    ms = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert len(xs) == 2
    # pid = component, tid = lane (-1 = no lane), ts/dur in microseconds
    q = next(e for e in xs if e["name"] == "queue")
    assert q["ts"] == 0.0 and q["dur"] == 1000.0 and q["tid"] == 0
    d = next(e for e in xs if e["name"] == "decode_lanes")
    assert d["tid"] == -1 and d["pid"] != q["pid"]
    names = {(e["name"], e["args"]["name"]) for e in ms}
    assert ("process_name", "scheduler") in names
    assert ("process_name", "engine") in names
    # the export is plain JSON a viewer can load back
    path = os.path.join(tmp_path, "tl.json")
    assert st.export_file(path) == 2
    with open(path) as f:
        loaded = json.load(f)
    assert loaded["dllama"]["n_spans"] == 2


def test_request_summary_coverage_and_phases():
    clk = FakeClock()
    st = SpanTracker(capacity=16, enabled=True, clock=clk)

    def record(name, t0, t1, rid="r1"):
        clk.t = t0
        h = st.begin(name, request_id=rid)
        clk.t = t1
        st.end(h)

    record("queue", 0.0, 1.0)
    record("decode", 1.0, 3.0)
    record("sample", 1.5, 2.5)  # nested: must not double-count coverage
    record("other", 0.0, 9.0, rid="r2")  # another request: excluded
    s = st.request_summary("r1")
    assert s["n_spans"] == 3
    assert s["wall_ms"] == 3000.0
    assert s["covered_ms"] == 3000.0
    assert s["coverage"] == 1.0
    assert s["phases"]["queue"]["total_ms"] == 1000.0
    assert s["phases"]["queue"]["share"] == round(1 / 3, 4)
    assert s["phases"]["decode"]["total_ms"] == 2000.0
    # a gap between spans is uncovered wall time
    record("a", 10.0, 11.0, rid="r3")
    record("b", 12.0, 13.0, rid="r3")
    s3 = st.request_summary("r3")
    assert s3["wall_ms"] == 3000.0
    assert s3["covered_ms"] == 2000.0
    assert s3["coverage"] == round(2 / 3, 4)
    assert st.request_summary("missing")["coverage"] is None


# -- SloTracker --------------------------------------------------------------


def test_slo_windows_attainment_and_goodput():
    clk = FakeClock()
    reg = MetricsRegistry()
    slo = SloTracker(ttft_target_ms=100.0, registry=reg, clock=clk)
    clk.t = 1.0
    assert slo.observe_request(ttft_s=0.05, tpot_s=None, n_tokens=20)
    slo.note_tokens(20)
    clk.t = 5.0
    assert not slo.observe_request(ttft_s=0.2, tpot_s=None, n_tokens=30)
    slo.note_tokens(30)
    clk.t = 9.0
    snap = slo.snapshot()
    w10 = snap["windows"]["10s"]
    assert w10["n_requests"] == 2 and w10["n_met"] == 1
    assert w10["ttft_attainment"] == 0.5
    assert w10["attainment"] == 0.5
    # goodput counts ONLY the SLO-met request's tokens; throughput all
    assert w10["goodput_tokens_per_s"] == round(20 / 10.0, 3)
    assert w10["throughput_tokens_per_s"] == round(50 / 10.0, 3)
    # both requests age out of 10s/1m but stay inside 5m
    clk.t = 100.0
    snap = slo.snapshot()
    assert snap["windows"]["10s"]["n_requests"] == 0
    assert snap["windows"]["10s"]["attainment"] == 1.0  # vacuous, finite
    assert snap["windows"]["10s"]["goodput_tokens_per_s"] == 0.0
    assert snap["windows"]["1m"]["n_requests"] == 0
    assert snap["windows"]["5m"]["n_requests"] == 2
    text = reg.render()
    assert 'dllama_slo_ttft_attainment{window="10s"} 1' in text
    assert 'dllama_slo_window_requests{window="5m"} 2' in text


def test_slo_tpot_target_and_unset_targets():
    clk = FakeClock()
    slo = SloTracker(tpot_target_ms=50.0, registry=MetricsRegistry(),
                     clock=clk)
    assert slo.observe_request(ttft_s=99.0, tpot_s=0.01)  # no TTFT target
    assert not slo.observe_request(ttft_s=0.01, tpot_s=0.2)
    none_set = SloTracker(registry=MetricsRegistry(), clock=clk)
    assert none_set.observe_request(ttft_s=None, tpot_s=None)  # vacuous


def test_slo_observe_span():
    class Span:
        finish_reason = "stop"
        n_completion = 11
        ttft_s = 0.05
        total_s = 1.05
        queue_wait_s = 0.01

    clk = FakeClock(t=1.0)
    slo = SloTracker(ttft_target_ms=100.0, tpot_target_ms=200.0,
                     registry=MetricsRegistry(), clock=clk)
    # tpot = (1.05 - 0.05) / 10 = 0.1s <= 200ms
    assert slo.observe_span(Span()) is True
    cancelled = Span()
    cancelled.finish_reason = "cancelled"
    assert slo.observe_span(cancelled) is None  # says nothing about SLOs
    assert slo.snapshot()["windows"]["10s"]["n_requests"] == 1


def test_slo_knob_resolution(monkeypatch):
    monkeypatch.delenv("DLLAMA_SLO_TTFT_MS", raising=False)
    monkeypatch.delenv("DLLAMA_SLO_TPOT_MS", raising=False)
    assert resolve_slo_knobs() == (None, None)
    monkeypatch.setenv("DLLAMA_SLO_TTFT_MS", "250")
    monkeypatch.setenv("DLLAMA_SLO_TPOT_MS", "40")
    assert resolve_slo_knobs() == (250.0, 40.0)
    # explicit beats env, same precedence as the lane knobs
    assert resolve_slo_knobs(ttft_ms=500.0) == (500.0, 40.0)


# -- EngineWatchdog ----------------------------------------------------------


def _watchdog(tmp_path, clk, **kw):
    reg = MetricsRegistry()
    rec = FlightRecorder(capacity=64, postmortem_dir=str(tmp_path))
    wd = EngineWatchdog(clock=clk, registry=reg, recorder=rec, **kw)
    return wd, reg, rec


def test_watchdog_dispatch_hung_postmortem_and_recovery(tmp_path):
    clk = FakeClock()
    wd, reg, rec = _watchdog(tmp_path, clk, dispatch_timeout_s=30.0)
    # n_active=0 keeps the decode-gap rule disarmed so only the in-flight
    # dispatch's age can trip the watchdog here
    wd.beat(n_active=0)
    wd.dispatch_begin("decode_lanes")
    clk.t = 10.0
    assert wd.check_once() is None
    clk.t = 31.0
    assert wd.check_once() == "dispatch-hung"
    assert wd.degraded
    assert wd.status()["reason"] == "dispatch-hung"
    assert "decode_lanes" in wd.status()["detail"]
    text = reg.render()
    assert "dllama_watchdog_degraded 1" in text
    assert 'dllama_watchdog_stalls_total{reason="dispatch-hung"} 1' in text
    # the hang wrote the black box while the process is still alive
    pms = [p for p in os.listdir(tmp_path) if p.startswith("postmortem-")]
    assert len(pms) == 1
    payload = json.loads((tmp_path / pms[0]).read_text())
    assert payload["reason"] == "watchdog"
    assert "dispatch-hung" in payload["error"]
    # edge-triggered: re-checks while stalled pay nothing further
    clk.t = 32.0
    assert wd.check_once() == "dispatch-hung"
    assert len(rec.events("watchdog_stall")) == 1
    assert len(
        [p for p in os.listdir(tmp_path) if p.startswith("postmortem-")]
    ) == 1
    # recovery clears degraded and records the transition
    wd.dispatch_end()
    wd.beat(n_active=0)
    assert wd.check_once() is None
    assert not wd.degraded
    assert rec.events("watchdog_recovered")[0]["reason"] == "dispatch-hung"
    assert "dllama_watchdog_degraded 0" in reg.render()


def test_watchdog_scheduler_stalled(tmp_path):
    clk = FakeClock()
    wd, _, rec = _watchdog(tmp_path, clk, dispatch_timeout_s=30.0)
    wd.beat(n_active=2)
    clk.t = 31.0
    assert wd.check_once() == "scheduler-stalled"
    # an idle scheduler (no busy lanes) is quiet, not stalled
    wd2, _, _ = _watchdog(tmp_path, clk, dispatch_timeout_s=30.0)
    wd2.beat(n_active=0, n_admitting=0)
    clk.t = 100.0
    assert wd2.check_once() is None


def test_watchdog_decode_stalled_scales_with_p99(tmp_path):
    clk = FakeClock()
    wd, _, _ = _watchdog(
        tmp_path, clk, min_stall_s=5.0, stall_factor=20.0,
        block_p99=lambda: 1.0,
    )
    wd.beat(n_active=1)  # arms the decode-gap rule from t=0
    clk.t = 6.0
    wd.beat(n_active=1)
    # gap 6s > min_stall but < 20 x p99(1s): a slow model, not a stall
    assert wd.check_once() is None
    clk.t = 21.0
    wd.beat(n_active=1)
    assert wd.check_once() == "decode-stalled"


def test_watchdog_decode_stalled_min_floor_without_p99(tmp_path):
    clk = FakeClock()
    wd, _, _ = _watchdog(tmp_path, clk, min_stall_s=5.0)
    wd.beat(n_active=1)
    clk.t = 6.0
    wd.beat(n_active=1)  # fresh beat; decode gap is the stale signal
    assert wd.check_once() == "decode-stalled"


def test_watchdog_admission_stalled_and_progress_resets(tmp_path):
    clk = FakeClock()
    wd, _, _ = _watchdog(tmp_path, clk, dispatch_timeout_s=30.0)
    wd.beat(n_admitting=1)
    clk.t = 20.0
    wd.beat(n_admitting=1)
    # a chunk completed: progress timestamp moves, no stall at t=31
    wd.dispatch_begin("prefill_lane_chunk")
    wd.dispatch_end()
    clk.t = 31.0
    wd.beat(n_admitting=1)
    assert wd.check_once() is None
    clk.t = 51.0
    wd.beat(n_admitting=1)
    assert wd.check_once() == "admission-stalled"
