"""Engine + CLI tests: generation invariants and the dllama-compatible
command surface."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.formats import FloatType
from dllama_tpu.formats.model_file import LlmArch
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.tokenizer import Tokenizer

from helpers import REPO_ROOT, make_tiny_model, make_tiny_tokenizer


@pytest.fixture()
def tiny_model(tmp_path):
    mp = str(tmp_path / "m.m")
    tp_ = str(tmp_path / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=64)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    return mp, tp_


def test_generate_deterministic_greedy(tiny_model):
    mp, tp_ = tiny_model
    eng = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    out1, ev1, pr1 = eng.generate([1, 2, 3, 4], max_steps=12)
    eng.reset()
    out2, _, _ = eng.generate([1, 2, 3, 4], max_steps=12)
    assert out1 == out2
    assert len(out1) == 12 - 3  # maxPos - prefill positions
    assert ev1.n_tokens == 3
    assert pr1.n_tokens == len(out1)


def test_generate_tp_matches_single_chip(tiny_model):
    """The engine's sharded decode must produce the same greedy tokens as
    single-chip — end-to-end TP equivalence including sampling."""
    mp, _ = tiny_model
    e1 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    out1, _, _ = e1.generate([5, 6, 7], max_steps=10)
    e4 = InferenceEngine(mp, tp=4, dtype=jnp.float32, temperature=0.0)
    out4, _, _ = e4.generate([5, 6, 7], max_steps=10)
    assert out1 == out4


def test_generate_with_sampling_seeded(tiny_model):
    mp, _ = tiny_model
    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.9, topp=0.9, seed=7)
    out1, _, _ = e.generate([1, 2, 3], max_steps=10)
    e2 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.9, topp=0.9, seed=7)
    out2, _, _ = e2.generate([1, 2, 3], max_steps=10)
    assert out1 == out2


def test_prefill_bucketing_consistent(tiny_model):
    """Bucketed/padded prefill must give the same next tokens as unbucketed."""
    mp, _ = tiny_model
    prompt = list(range(1, 12))  # 11 tokens -> buckets pad to 32 etc.
    ea = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                         prefill_buckets=(4,))
    outa, _, _ = ea.generate(prompt, max_steps=16)
    eb = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                         prefill_buckets=(32,))
    outb, _, _ = eb.generate(prompt, max_steps=16)
    assert outa == outb


def test_max_seq_len_clamps(tiny_model):
    mp, _ = tiny_model
    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, max_seq_len=16, temperature=0.0)
    assert e.header.seq_len == 16
    out, _, _ = e.generate([1, 2, 3], max_steps=100)
    assert len(out) == 16 - 2  # clamped by seq_len, not steps


def _run_cli(args, env_extra=None):
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "dllama_tpu"] + args,
        capture_output=True,
        text=True,
        env=env,
        cwd=REPO_ROOT,
        timeout=600,
    )


def test_cli_inference(tiny_model):
    mp, tp_ = tiny_model
    r = _run_cli(
        ["inference", "--model", mp, "--tokenizer", tp_,
         "--prompt", "hello world", "--steps", "16",
         "--temperature", "0.0", "--dtype", "f32", "--tp", "2"]
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "🔶 Pred" in r.stdout
    assert "tokens/s:" in r.stdout
    assert "Evaluation" in r.stdout and "Prediction" in r.stdout


def test_cli_help_renders():
    """--help must not crash: argparse %-expands help strings, so a bare
    `%` in any of them raises at render time (regression: the --dp help
    carried an unescaped `% dp`)."""
    r = _run_cli(["--help"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "--weight-format" in r.stdout and "q40i4" in r.stdout


def test_cli_perplexity(tiny_model):
    mp, tp_ = tiny_model
    r = _run_cli(
        ["perplexity", "--model", mp, "--tokenizer", tp_,
         "--prompt", "hello world hello world", "--dtype", "f32"]
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "perplexity:" in r.stdout


def test_cli_worker_mode_explains(tiny_model):
    r = _run_cli(["worker"])
    assert r.returncode != 0
    assert "SPMD" in r.stderr or "SPMD" in r.stdout


def test_cli_rejects_gpu_flags(tiny_model):
    mp, tp_ = tiny_model
    r = _run_cli(
        ["inference", "--model", mp, "--tokenizer", tp_, "--prompt", "x",
         "--steps", "4", "--gpu-index", "0"]
    )
    assert r.returncode != 0
    assert "TPU" in (r.stderr + r.stdout)


def test_prefill_bucket_never_pads_past_seq_len(tiny_model):
    """Padded chunk extent must respect seqLen (dynamic_update_slice clamps
    silently otherwise, corrupting earlier cache rows)."""
    mp, _ = tiny_model
    # seq_len=64; prompt of 44 with buckets (8, 32): last chunks must not
    # write a padded 32-wide window past position 64
    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                        max_seq_len=48, prefill_buckets=(8, 32))
    prompt = list(range(1, 45))  # 44 tokens
    out_bucketed, _, _ = e.generate(prompt, max_steps=47)
    e2 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                         max_seq_len=48, prefill_buckets=(8,))
    out_exact, _, _ = e2.generate(prompt, max_steps=47)
    assert out_bucketed == out_exact


def test_quant_weight_format_matches_dense(tiny_model):
    """weight_format='q40' must reproduce the dense-load greedy tokens
    exactly (off-TPU the quant path dequantizes at run time — numerically
    identical to dequant-at-load)."""
    mp, _ = tiny_model
    e_dense = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                              weight_format="dense")
    out_dense, _, _ = e_dense.generate([1, 2, 3, 4], max_steps=12)
    e_quant = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                              weight_format="q40")
    out_quant, _, _ = e_quant.generate([1, 2, 3, 4], max_steps=12)
    assert out_dense == out_quant


def test_quant_weight_format_tp(tmp_path):
    """Quantized weights sharded over a tp=4 mesh reproduce single-chip.
    Dims must divide by 32*tp (the scale tensors shard their block axis)."""
    mp = str(tmp_path / "mq.m")
    cfg = dict(dim=128, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=256, seq_len=64)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    e1 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                         weight_format="q40")
    out1, _, _ = e1.generate([5, 6, 7], max_steps=10)
    e4 = InferenceEngine(mp, tp=4, dtype=jnp.float32, temperature=0.0,
                         weight_format="q40")
    out4, _, _ = e4.generate([5, 6, 7], max_steps=10)
    assert out1 == out4


def test_quant_weight_format_moe_matches_dense(tmp_path):
    """Qwen3-MoE with weight_format='q40' keeps the expert weights
    block-quantized on device (the reference stores experts Q40 too,
    src/llm.cpp:425-499) and must reproduce the dense-load greedy tokens."""
    from dllama_tpu.ops.quant_matmul import QuantWeight

    mp = str(tmp_path / "moe.m")
    make_tiny_model(mp, arch=LlmArch.QWEN3_MOE, weight_type=FloatType.Q40)
    e_dense = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                              weight_format="dense")
    out_dense, _, _ = e_dense.generate([1, 2, 3, 4], max_steps=12)
    e_quant = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                              weight_format="q40")
    # the experts really are stored quantized: int8 values + f32 scales
    w1 = e_quant.params["layers"]["w1"]
    assert isinstance(w1, QuantWeight) and w1.q.dtype == jnp.int8
    assert w1.q.ndim == 4  # [L, E, D, F]
    out_quant, _, _ = e_quant.generate([1, 2, 3, 4], max_steps=12)
    assert out_dense == out_quant


def test_quant_rejects_non_q40(tmp_path):
    mp = str(tmp_path / "f32.m")
    make_tiny_model(mp, weight_type=FloatType.F32)
    with pytest.raises(ValueError, match="q40"):
        InferenceEngine(mp, tp=1, dtype=jnp.float32, weight_format="q40")


def test_prefetch_builder_failure_is_recorded(tiny_model, caplog):
    """A builder exception in the _prefetch daemon thread must not vanish
    silently: it is logged, the key is marked 'prefetch-failed' in
    _compile_origin, the inflight slot is released (so the boundary
    crossing doesn't deadlock on a never-set event), and the engine keeps
    serving (the dispatch path falls back to a synchronous compile)."""
    import logging
    import time

    mp, _ = tiny_model
    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    key = ("block", 99, True, e._attn_window(1))

    def boom():
        raise RuntimeError("synthetic prefetch failure")

    with caplog.at_level(logging.ERROR, logger="dllama_tpu.runtime.engine"):
        e._prefetch(key, boom)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with e._compile_lock:
                if key not in e._inflight:
                    break
            time.sleep(0.01)
    with e._compile_lock:
        assert key not in e._inflight
        assert e._compile_origin.get(key) == "prefetch-failed"
        assert key not in e._compiled
    assert any("prefetch failed" in r.message for r in caplog.records)
    out, _, _ = e.generate([1, 2, 3], max_steps=4)
    assert len(out) > 0


def test_packed_weight_format_matches_q40(tiny_model):
    """weight_format='q40i4' (packed nibbles + f16 scales) reproduces the
    q40 greedy tokens exactly: f16 scales are wire-exact and the nibble
    unpack is lossless, so off-TPU the two dequant paths are bit-identical.
    Also pins the loaded leaf layout (the point of the format: 0.5625 B/w
    on device instead of 1.125)."""
    from dllama_tpu.models.loader import FusedQuantWeight
    from dllama_tpu.ops.quant_matmul import PackedQuantWeight

    mp, _ = tiny_model
    e_q40 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                            weight_format="q40")
    out_q40, _, _ = e_q40.generate([1, 2, 3, 4], max_steps=12)
    e_i4 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                           weight_format="q40i4")
    out_i4, _, _ = e_i4.generate([1, 2, 3, 4], max_steps=12)
    assert out_q40 == out_i4

    wqkv = e_i4.params["layers"]["wqkv"]
    assert isinstance(wqkv, FusedQuantWeight)
    pw = wqkv.weight
    assert isinstance(pw, PackedQuantWeight)
    assert pw.qp.dtype == jnp.int8 and pw.d.dtype == jnp.float16
    n_weights = pw.in_dim * pw.out_dim * pw.qp.shape[0]  # [L, in//2, out]
    assert (pw.qp.nbytes + pw.d.nbytes) / n_weights <= 0.60


def test_packed_weight_format_tp(tmp_path):
    """Packed weights sharded over a tp=4 mesh reproduce single-chip: the
    in//2 (nibble) and in//32 (scale) axes both divide by tp under the
    engine's 32*tp divisibility check, so col shards stay byte-aligned."""
    mp = str(tmp_path / "mq4.m")
    cfg = dict(dim=128, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=256, seq_len=64)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    e1 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                         weight_format="q40i4")
    out1, _, _ = e1.generate([5, 6, 7], max_steps=10)
    e4 = InferenceEngine(mp, tp=4, dtype=jnp.float32, temperature=0.0,
                         weight_format="q40i4")
    out4, _, _ = e4.generate([5, 6, 7], max_steps=10)
    assert out1 == out4


def test_packed_weight_format_moe_keeps_int8_experts(tmp_path):
    """q40i4 on Qwen3-MoE packs the attention/dense weights but leaves the
    expert stacks in the int8 QuantWeight layout the ragged MoE kernels
    consume — and still reproduces the q40 greedy tokens."""
    from dllama_tpu.ops.quant_matmul import PackedQuantWeight, QuantWeight

    mp = str(tmp_path / "moe4.m")
    make_tiny_model(mp, arch=LlmArch.QWEN3_MOE, weight_type=FloatType.Q40)
    e_q40 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                            weight_format="q40")
    out_q40, _, _ = e_q40.generate([1, 2, 3, 4], max_steps=12)
    e_i4 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                           weight_format="q40i4")
    w1 = e_i4.params["layers"]["w1"]
    assert isinstance(w1, QuantWeight) and not isinstance(w1, PackedQuantWeight)
    assert w1.q.dtype == jnp.int8 and w1.q.ndim == 4  # [L, E, D, F]
    wo = e_i4.params["layers"]["wo"]
    assert isinstance(wo, PackedQuantWeight)
    out_i4, _, _ = e_i4.generate([1, 2, 3, 4], max_steps=12)
    assert out_q40 == out_i4


def test_packed_streamed_load_matches_host_stack(tmp_path, monkeypatch):
    """The streamed shard loader (per-shard host pack) and the host-stack
    path produce byte-identical packed param trees."""
    from jax.tree_util import tree_leaves_with_path

    mp = str(tmp_path / "mq4s.m")
    cfg = dict(dim=128, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=256, seq_len=64)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    e_stream = InferenceEngine(mp, tp=2, dtype=jnp.float32, temperature=0.0,
                               weight_format="q40i4")
    monkeypatch.setenv("DLLAMA_STREAM_LOAD", "0")
    e_host = InferenceEngine(mp, tp=2, dtype=jnp.float32, temperature=0.0,
                             weight_format="q40i4")
    a = tree_leaves_with_path(e_stream.params)
    b = tree_leaves_with_path(e_host.params)
    assert len(a) == len(b)
    for (pa, la), (pb, lb) in zip(a, b):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_generate_batch_unequal_prompts_match_single(tiny_model):
    """Per-lane serving: three lanes with different prompt lengths decode
    together (parked prefill + per-lane positions) and must reproduce each
    prompt's single-stream greedy output exactly."""
    mp, _ = tiny_model
    prompts = [[1, 2, 3, 4], [9, 8, 7, 6, 5, 4, 3], [40, 41]]
    singles = []
    e1 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    for p in prompts:
        e1.reset()
        out, _, _ = e1.generate(p, max_steps=20)
        singles.append(out)
    eb = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                         batch_size=3)
    outs = eb.generate_batch(prompts, max_steps=20)
    assert outs == singles, (outs, singles)


def test_engine_moe_lanes_unequal_prompts(tmp_path):
    """Qwen3-MoE through the per-lane serving surface: unequal prompts in
    lanes reproduce single-stream outputs (per-token routing must respect
    lane boundaries)."""
    path = str(tmp_path / "moe.m")
    make_tiny_model(path, arch=LlmArch.QWEN3_MOE, weight_type=FloatType.F32)
    prompts = [[1, 2, 3, 4, 5, 6], [9, 8, 7]]
    e1 = InferenceEngine(path, tp=1, dtype=jnp.float32, temperature=0.0)
    singles = []
    for p in prompts:
        e1.reset()
        out, _, _ = e1.generate(p, max_steps=16)
        singles.append(out)
    eb = InferenceEngine(path, tp=1, dtype=jnp.float32, temperature=0.0,
                         batch_size=2)
    outs = eb.generate_batch(prompts, max_steps=16)
    assert outs == singles, (outs, singles)


def test_prefill_lane_preserves_other_lanes(tiny_model):
    """Prefilling a new request into a free lane must not disturb a lane
    mid-conversation: decode lane 0, prefill lane 1, keep decoding lane 0
    — the token stream must equal an undisturbed run."""
    mp, _ = tiny_model
    prompt = [5, 6, 7, 8, 9]
    e1 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    expected, _, _ = e1.generate(prompt, max_steps=20)

    eb = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                         batch_size=2)
    eb.prefill_lane(0, prompt)
    pos = [len(prompt) - 1, 0]
    toks = [prompt[-1], 0]
    got = []
    rows = eb.decode_lanes(toks, pos, 6, active=[True, False])
    got += [r[0] for r in rows]
    pos[0] += len(rows)
    toks[0] = got[-1]
    # admit a second request mid-stream, then continue lane 0
    eb.prefill_lane(1, [30, 31, 32, 33, 34, 35, 36, 37, 38])
    pos[1], toks[1] = 8, 38
    while pos[0] < 20:
        rows = eb.decode_lanes(toks, pos, 4, active=[True, True])
        if not rows:
            break
        got += [r[0] for r in rows][: 20 - pos[0]]
        pos = [pos[0] + len(rows), pos[1] + len(rows)]
        toks = [rows[-1][0], rows[-1][1]]
    assert got == expected, (got, expected)


def test_perplexity_chunk_size_invariant(tiny_model):
    """Chunked on-device scoring must be invariant to the prefill bucket
    shape (the chunks see earlier chunks only through the KV cache), and
    match a direct full-prompt numpy computation of the NLL."""
    mp, _ = tiny_model
    prompt = [(i * 7 + 3) % 256 for i in range(50)]

    ppls = []
    for buckets in [(4,), (8, 32), (50,)]:
        e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                            prefill_buckets=buckets)
        nll, ppl, n = e.perplexity(prompt)
        assert n == len(prompt) - 1
        ppls.append(ppl)
    assert abs(ppls[0] - ppls[1]) < 1e-3 and abs(ppls[0] - ppls[2]) < 1e-3, ppls

    # oracle: single un-chunked forward, host softmax
    from dllama_tpu.models import forward

    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    cache = e._fresh_cache()
    arr = jnp.asarray([prompt], dtype=jnp.int32)
    logits, _ = forward(e.params, e.header, arr, jnp.int32(0), cache,
                        mesh=e.mesh)
    lg = np.asarray(logits, np.float32)[0]
    mx = lg.max(-1, keepdims=True)
    logprobs = lg - mx - np.log(np.exp(lg - mx).sum(-1, keepdims=True))
    nll_ref = -np.mean(
        [logprobs[i, prompt[i + 1]] for i in range(len(prompt) - 1)]
    )
    assert abs(ppls[0] - float(np.exp(nll_ref))) < 1e-3


def test_topp_mask_matches_host_sampler_support():
    """The on-device top-p mask must keep exactly the token set the host
    (reference-parity) sampler can return — same nucleus, different RNG
    (VERDICT r1 weak #7). Covers generic rows and the topp 0/1 edge cases
    where both paths degrade to the full distribution."""
    from dllama_tpu.runtime.engine import _topp_mask
    from dllama_tpu.runtime.sampler import softmax, topp_support

    rng = np.random.default_rng(3)
    v = 64
    for topp in (0.1, 0.5, 0.9, 0.99):
        for trial in range(5):
            logits = rng.standard_normal(v).astype(np.float32) * 3.0
            probs = softmax(logits / 0.8)
            order, _ = topp_support(probs, topp)  # the host sampler's set
            host_support = set(int(i) for i in order)

            masked = np.asarray(
                _topp_mask(jnp.asarray(probs)[None, :], jnp.float32(topp))
            )[0]
            device_support = set(int(i) for i in np.nonzero(masked > 0)[0])
            assert device_support == host_support, (
                topp, trial, device_support ^ host_support
            )
    # topp <= 0 / >= 1: both paths keep the whole distribution
    logits = rng.standard_normal(v).astype(np.float32)
    probs = softmax(logits)
    for topp in (0.0, 1.0):
        masked = np.asarray(
            _topp_mask(jnp.asarray(probs)[None, :], jnp.float32(topp))
        )[0]
        assert (masked > 0).all()
    # f32-cumsum saturation: topp above the summed mass must keep the
    # whole set (the host's empty-`over` branch), not collapse to top-1
    probs = np.full(v, 1.0 / v, np.float32)
    masked = np.asarray(
        _topp_mask(jnp.asarray(probs)[None, :], jnp.float32(0.999999))
    )[0]
    assert int((masked > 0).sum()) == v, int((masked > 0).sum())


def test_telemetry_report_and_ici():
    from dllama_tpu.models.synthetic import make_header, random_params
    from dllama_tpu.models import init_kv_cache
    from dllama_tpu.utils.telemetry import ici_traffic_per_token, memory_report

    h = make_header("tiny")
    params = random_params(h, dtype=jnp.float32)
    cache = init_kv_cache(h, 1)
    rep2 = memory_report(params, cache, n_devices=2)
    rep8 = memory_report(params, cache, n_devices=8)
    assert rep2.params_bytes > 0 and rep2.cache_bytes > 0
    assert 0 < rep2.replicated_bytes < rep2.total_bytes
    # the replicated portion must not shrink with chip count: per-chip at
    # 8 devices stays above a pure total/8 split by ~the replicated bytes
    assert rep8.per_device_bytes >= rep8.total_bytes // 8
    assert rep8.per_device_bytes - rep8.total_bytes // 8 >= int(
        rep8.replicated_bytes * 0.8
    )
    rep = rep2
    assert ici_traffic_per_token(h, 1) == 0
    t2 = ici_traffic_per_token(h, 2)
    t4 = ici_traffic_per_token(h, 4)
    assert t4 > t2 > 0
    assert ici_traffic_per_token(h, 2, include_logits=False) < t2


def test_generate_batch_lanes_independent(tiny_model):
    """dp lanes decode independent sequences; each lane must match a
    single-lane run of the same prompt."""
    mp, _ = tiny_model
    e2 = InferenceEngine(mp, tp=1, dp=2, batch_size=2, dtype=jnp.float32,
                         temperature=0.0)
    p1, p2 = [1, 2, 3, 4], [9, 8, 7, 6]
    outs = e2.generate_batch([p1, p2], max_steps=14)
    e1 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    ref1, _, _ = e1.generate(p1, max_steps=14)
    e1.reset()
    ref2, _, _ = e1.generate(p2, max_steps=14)
    assert outs[0] == ref1
    assert outs[1] == ref2


def test_attn_window_equivalence(tmp_path):
    """Windowed attention (power-of-2 cache prefix) must reproduce the
    full-cache tokens on a long-seq-len model decoded at short positions."""
    mp = str(tmp_path / "w.m")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=2048)
    make_tiny_model(mp, weight_type=FloatType.F32, cfg=cfg)
    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    assert e._attn_window(5) == 512          # min window
    assert e._attn_window(600) == 1024       # next pow2
    assert e._attn_window(1500) == 2048      # clamped to seq_len
    out_windowed, _, _ = e.generate([1, 2, 3, 4], max_steps=16)

    # force full-cache windows and compare
    e2 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    e2._attn_window = lambda limit: cfg["seq_len"]
    out_full, _, _ = e2.generate([1, 2, 3, 4], max_steps=16)
    assert out_windowed == out_full

    # cross the 512 -> 1024 window boundary mid-generation (the risky edge:
    # window growth + recompile must not drop live cache rows)
    prompt = list(range(1, 509))
    e.reset()
    out_cross, _, _ = e.generate(prompt, max_steps=530)
    e2.reset()
    out_cross_full, _, _ = e2.generate(prompt, max_steps=530)
    assert out_cross == out_cross_full
    assert len(out_cross) == 530 - (len(prompt) - 1)


def test_ici_traffic_accounts_pp():
    from dllama_tpu.models.synthetic import make_header
    from dllama_tpu.utils.telemetry import ici_traffic_per_token

    h = make_header("tiny")
    assert ici_traffic_per_token(h, 1, pp=1) == 0
    t_pp = ici_traffic_per_token(h, 1, pp=2)
    assert t_pp > 0  # tick hand-offs + exit psum
    # pp traffic is per-token tiny next to tp's per-layer all-reduces
    assert t_pp < ici_traffic_per_token(h, 2, include_logits=False)


def test_cache_guard_recovers_from_failed_dispatch(tiny_model):
    """Crash consistency (reference analogue: dllama-api's whole-app
    retry, src/dllama-api.cpp:616-628): a dispatch that raises AFTER
    donating the KV cache must leave the engine usable — the guard swaps
    in a fresh cache (epoch moves) and the next generate produces the
    clean-engine token stream instead of a donated-buffer error."""
    mp, _ = tiny_model
    eng = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    clean, _, _ = eng.generate([1, 2, 3, 4], max_steps=12)
    eng.reset()
    epoch0 = eng.cache_epoch

    real = eng._decode_block_fn

    def poisoned(n_steps, greedy, window=0):
        block = real(n_steps, greedy, window)

        def bad(params, token, cache, pos, rng, temp, topp):
            block(params, token, cache, pos, rng, temp, topp)  # donates
            raise RuntimeError("injected dispatch failure")

        return bad

    eng._decode_block_fn = poisoned
    with pytest.raises(RuntimeError, match="injected"):
        eng.generate([1, 2, 3, 4], max_steps=12)
    eng._decode_block_fn = real

    assert eng.cache_epoch > epoch0  # the donated cache was replaced
    again, _, _ = eng.generate([1, 2, 3, 4], max_steps=12)
    assert again == clean


def test_kv_int8_bounded_quality_and_capacity(tiny_model):
    """VERDICT r4 item 8: kv_dtype=int8 (QuantKV per-row quantization)
    keeps teacher-forced NLL within a tight bound of the f32 cache and
    halves-ish the cache footprint (int8 values + 1/hd scale rows)."""
    mp, _ = tiny_model
    toks = [(i * 11) % 250 + 1 for i in range(40)]
    ef = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    nll_f, _, _ = ef.perplexity(toks)
    bytes_f = sum(
        v.nbytes for v in jax.tree_util.tree_leaves(ef.cache)
    )
    del ef
    e8 = InferenceEngine(
        mp, tp=1, dtype=jnp.float32, temperature=0.0, kv_dtype="int8"
    )
    nll_8, _, _ = e8.perplexity(toks)
    bytes_8 = sum(
        v.nbytes for v in jax.tree_util.tree_leaves(e8.cache)
    )
    assert abs(nll_8 - nll_f) / abs(nll_f) < 0.01, (nll_8, nll_f)
    # f32 reference cache = 4 B/elem; int8 = 1 B + 4/hd scale
    assert bytes_8 < 0.32 * bytes_f, (bytes_8, bytes_f)


def test_kv_int8_composes_with_sp_tp_pp(tiny_model):
    """The quantized cache threads through every parallel axis: sp
    (cyclic layout, both leaves permuted), tp (kv-head sharding), and pp
    (stage-local caches) reproduce the int8 single-device stream —
    quantization is per-row deterministic, so parity is exact."""
    mp, _ = tiny_model
    prompt = [1, 2, 3, 4, 5]
    base = InferenceEngine(
        mp, tp=1, dtype=jnp.float32, temperature=0.0, kv_dtype="int8"
    )
    expected, _, _ = base.generate(prompt, max_steps=14)
    del base
    for kw in (dict(sp=2), dict(tp=2), dict(pp=2), dict(tp=2, sp=2)):
        e = InferenceEngine(
            mp, dtype=jnp.float32, temperature=0.0, kv_dtype="int8", **kw
        )
        got, _, _ = e.generate(prompt, max_steps=14)
        del e
        assert got == expected, (kw, got, expected)


def test_kv_dtype_name_validation(tiny_model):
    mp, _ = tiny_model
    with pytest.raises(ValueError, match="kv_dtype"):
        InferenceEngine(mp, kv_dtype="int4", dtype=jnp.float32)


def test_engine_moe_decode_dedup_parity(tmp_path):
    """moe_decode_dedup=True through the full engine (q40 experts,
    4 concurrent lanes): per-lane streams match the default engine."""
    from dllama_tpu.formats.model_file import LlmArch

    mp = str(tmp_path / "moe.m")
    make_tiny_model(mp, arch=LlmArch.QWEN3_MOE, weight_type=FloatType.Q40,
                    seed=7)
    prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [2, 4]]
    base = InferenceEngine(
        mp, tp=1, dtype=jnp.float32, temperature=0.0, batch_size=4
    )
    expected = base.generate_batch(prompts, max_steps=10)
    del base
    eded = InferenceEngine(
        mp, tp=1, dtype=jnp.float32, temperature=0.0, batch_size=4,
        moe_decode_dedup=True,
    )
    got = eded.generate_batch(prompts, max_steps=10)
    del eded
    assert got == expected, (got, expected)


def test_kv_int8_with_lanes_and_dp(tiny_model):
    """int8 KV under continuous-batching lanes (and lanes sharded over
    dp): per-lane streams match the single-lane int8 runs."""
    mp, _ = tiny_model
    prompts = [[1, 2, 3, 4], [9, 8, 7, 6, 5]]
    singles = []
    e1 = InferenceEngine(
        mp, tp=1, dtype=jnp.float32, temperature=0.0, kv_dtype="int8"
    )
    for p in prompts:
        e1.reset()
        o, _, _ = e1.generate(p, max_steps=14)
        singles.append(o)
    del e1
    for kw in (dict(), dict(dp=2)):
        eb = InferenceEngine(
            mp, dtype=jnp.float32, temperature=0.0, kv_dtype="int8",
            batch_size=2, **kw,
        )
        outs = eb.generate_batch(prompts, max_steps=14)
        del eb
        assert outs == singles, (kw, outs, singles)


def test_window_precompile_no_boundary_stall(tmp_path, monkeypatch):
    """Window-crossing pre-compile (VERDICT r4 #7): decode blocks past
    75% of the current attention window must trigger a BACKGROUND build
    of the next window's program, so the boundary crossing finds it in
    the cache (origin == 'prefetch', no synchronous compile) — and the
    AOT executables must produce the same tokens as the plain jit path."""
    import time as _time

    mp = str(tmp_path / "w.m")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=2048)
    make_tiny_model(mp, weight_type=FloatType.F32, cfg=cfg)
    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    assert e._aot_blocks

    toks = []
    tok, pos = 7, 0
    while pos + 32 <= 512:
        out = e.decode_block(tok, pos, 32)
        toks.extend(out)
        tok, pos = out[-1], pos + 32
    # 75% trigger fired during the tail blocks; wait for the thread
    key = ("block", 32, True, 1024)
    deadline = _time.time() + 120
    while _time.time() < deadline and key not in e._compiled:
        _time.sleep(0.2)
    assert key in e._compiled, "next-window program was not prefetched"
    assert e._compile_origin[key] == "prefetch"
    # the crossing dispatch reuses it (origin unchanged -> no sync compile)
    out = e.decode_block(tok, pos, 32)
    toks.extend(out)
    assert e._compile_origin[key] == "prefetch"

    # token parity vs the plain jit path
    monkeypatch.setenv("DLLAMA_WINDOW_PRECOMPILE", "0")
    e2 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    assert not e2._aot_blocks
    toks2 = []
    tok, pos = 7, 0
    while pos + 32 <= 544:
        out = e2.decode_block(tok, pos, 32)
        toks2.extend(out)
        tok, pos = out[-1], pos + 32
    assert toks == toks2


def test_moe_decode_dedup_auto_resolution(tmp_path, tiny_model):
    """'auto' (default) resolves per the routing-correlation study
    (docs/moe_decode_dedup.md): on iff MoE and >= 8 decode lanes."""
    from dllama_tpu.formats.model_file import LlmArch

    mp_moe = str(tmp_path / "amoe.m")
    make_tiny_model(mp_moe, arch=LlmArch.QWEN3_MOE,
                    weight_type=FloatType.Q40, seed=3)
    e8 = InferenceEngine(mp_moe, tp=1, dtype=jnp.float32, batch_size=8)
    assert e8.moe_decode_dedup is True
    del e8
    e4 = InferenceEngine(mp_moe, tp=1, dtype=jnp.float32, batch_size=4)
    assert e4.moe_decode_dedup is False
    del e4
    mp_dense, _ = tiny_model  # non-MoE: never on
    ed = InferenceEngine(mp_dense, tp=1, dtype=jnp.float32, batch_size=8)
    assert ed.moe_decode_dedup is False


def test_lane_window_precompile_no_boundary_stall(tmp_path):
    """Same boundary-stall pin for decode_lanes — the API server's actual
    serving path: the next window's lane program must arrive via the
    background prefetch, not a synchronous compile at the crossing."""
    import time as _time

    mp = str(tmp_path / "wl.m")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=2048)
    make_tiny_model(mp, weight_type=FloatType.F32, cfg=cfg)
    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                        batch_size=2)
    toks, pos = [5, 7], [0, 0]
    while pos[0] + 32 <= 512:
        out = e.decode_lanes(toks, pos, 32)
        toks = out[-1]
        pos = [p + 32 for p in pos]
    key = ("lane_block", 32, 1024)
    deadline = _time.time() + 120
    while _time.time() < deadline and key not in e._compiled:
        _time.sleep(0.2)
    assert key in e._compiled, "next-window lane program was not prefetched"
    assert e._compile_origin[key] == "prefetch"
    out = e.decode_lanes(toks, pos, 32)
    assert len(out) == 32
    assert e._compile_origin[key] == "prefetch"


def test_lane_seed_reproducible_across_lane_mix(tiny_model):
    """Per-lane seeds (r5, closes r4's 'seed ignored in lane mode'): a
    seeded lane's sampled stream depends only on (seed, positions) — it
    reproduces with DIFFERENT traffic on the other lane and across
    different block splits."""
    mp, _ = tiny_model
    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.8,
                        batch_size=2)
    out1 = e.decode_lanes([5, 9], [0, 0], 12, temperature=[0.8, 0.7],
                          seeds=[42, None])
    lane0_a = [r[0] for r in out1]
    # different other-lane token/temperature/seed: lane 0 must not move
    e.reset()
    out2 = e.decode_lanes([5, 3], [0, 0], 12, temperature=[0.8, 0.9],
                          seeds=[42, 7])
    assert [r[0] for r in out2] == lane0_a
    # same stream when the 12 steps split into 6+6 blocks
    e.reset()
    o1 = e.decode_lanes([5, 9], [0, 0], 6, temperature=[0.8, 0.7],
                        seeds=[42, None])
    o2 = e.decode_lanes([r for r in o1[-1]], [6, 6], 6,
                        temperature=[0.8, 0.7], seeds=[42, None])
    assert [r[0] for r in o1 + o2] == lane0_a
    # and a different seed produces a different stream (sanity)
    e.reset()
    out3 = e.decode_lanes([5, 9], [0, 0], 12, temperature=[0.8, 0.7],
                          seeds=[43, None])
    assert [r[0] for r in out3] != lane0_a


def test_aot_specs_use_init_snapshot(tiny_model):
    """The AOT lowering specs are built from the init-time
    ShapeDtypeStruct snapshot, never from the live trees: a prefetch
    thread reads these specs while the serving thread's dispatch is
    donating (deleting) the live cache buffers. Nulling the live trees
    proves no such read happens."""
    mp, _ = tiny_model
    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    expect_cache = jax.tree.map(lambda x: (x.shape, str(x.dtype)), e.cache)
    live_cache, live_params = e.cache, e.params
    e.cache = None
    e.params = None
    try:
        specs = e._block_arg_specs(8)
    finally:
        e.cache, e.params = live_cache, live_params
    param_specs, tok, cache_specs = specs[0], specs[1], specs[2]
    assert tok.shape == (e.batch_size, 1)
    got_cache = jax.tree.map(lambda s: (s.shape, str(s.dtype)), cache_specs)
    assert got_cache == expect_cache
    assert jax.tree.structure(param_specs) == jax.tree.structure(live_params)
    # and the specs really drive a compile: the engine still generates
    out, _, _ = e.generate([1, 2, 3], max_steps=5)
    assert len(out) > 0


def test_lane_aot_specs_use_init_snapshot(tiny_model):
    """decode_lanes' lowering specs come from the same snapshot (the lane
    scheduler's prefetches race donated dispatches the same way)."""
    mp, _ = tiny_model
    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                        batch_size=2)
    live_cache, live_params = e.cache, e.params
    e.cache = None
    e.params = None
    try:
        specs = e._lane_arg_specs(4)
    finally:
        e.cache, e.params = live_cache, live_params
    assert specs[1].shape == (2, 1)  # token vector is per-lane
    assert specs[3].shape == (2,)  # positions
    rows = e.decode_lanes([1, 2], [0, 0], 4, active=[True, True])
    assert len(rows) == 4 and all(len(r) == 2 for r in rows)


def test_engine_obs_counters(tiny_model):
    """Engine instrumentation: dispatch compiles and step latencies are
    counted, and the window-crossing counter fires exactly on growth."""
    mp, _ = tiny_model
    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    disp = e._m_compiles.labels(origin="dispatch")
    b_disp = disp.value
    b_step = e._m_step.labels(kind="decode_block").count
    b_tpot = e._m_tpot.count
    out, _, _ = e.generate([1, 2, 3], max_steps=8)
    assert len(out) > 0
    assert disp.value > b_disp  # prefill and/or block programs compiled
    assert e._m_step.labels(kind="decode_block").count > b_step
    assert e._m_tpot.count > b_tpot

    crossings = e._m_window_crossings
    e._obs_last_window = None
    b_w = crossings.value
    e._note_window(32)
    e._note_window(32)  # same window: no crossing
    assert crossings.value == b_w
    e._note_window(64)  # growth: one crossing
    assert crossings.value == b_w + 1
    e._note_window(32)  # shrink (fresh request): no crossing
    assert crossings.value == b_w + 1


def test_compile_cache_report_and_cost(tiny_model):
    """Engine introspection behind /v1/debug/compile: every cached
    program is classified by kind with its compile origin, AOT block
    programs carry real XLA cost analysis (even on CPU), and cost_report
    folds them into per-kind figures with the roofline fraction honestly
    absent when the backend's HBM peak is unknown."""
    mp, _ = tiny_model
    eng = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    assert InferenceEngine._key_kind(("block", 8, True, 64)) == "decode_block"
    assert InferenceEngine._key_kind(("lane_block", 8, 64)) == "decode_lanes"
    assert InferenceEngine._key_kind(("lane_prefill", 8, 64)) == "prefill_lane"
    assert InferenceEngine._key_kind(("score", 8, 64)) == "score"
    assert InferenceEngine._key_kind((8, True, 64)) == "prefill"

    eng.generate([1, 2, 3], max_steps=10)
    report = eng.compile_cache_report()
    assert report
    kinds = {e["kind"] for e in report}
    assert "decode_block" in kinds
    for e in report:
        assert e["origin"] in ("dispatch", "prefetch", "prefetch-failed")
        assert e["cost"] == "unavailable" or e["cost"]["bytes_accessed"] > 0
    blocks = [e for e in report if e["kind"] == "decode_block"]
    if eng._aot_blocks:
        assert any(isinstance(e["cost"], dict) for e in blocks)
        assert all(e["compile_seconds"] is not None for e in blocks)

    cost = eng.cost_report()
    if eng._aot_blocks:
        info = cost["kinds"]["decode_block"]
        assert info["bytes_accessed"] > 0 and info["mean_step_s"] > 0
        if cost["hbm_peak_bytes_per_s"] is None:  # CPU test backend
            assert info["roofline_fraction"] is None
        # the per-kind gauges took the same values
        g = eng.obs.gauge(
            "dllama_compiled_step_bytes_accessed", labelnames=("kind",))
        assert g.child_values()[("decode_block",)] == info["bytes_accessed"]


def test_recorder_captures_engine_events(tiny_model):
    """One generate() leaves a coherent event trail in the flight
    recorder: dispatches paired with completes, and the KV-cache epoch
    event from engine init."""
    from dllama_tpu.obs.recorder import get_recorder

    rec = get_recorder()
    base_seq = rec.total_recorded
    mp, _ = tiny_model
    eng = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    eng.generate([1, 2, 3], max_steps=8)
    new = [e for e in rec.events() if e["seq"] > base_seq]
    kinds = [e["kind"] for e in new]
    assert "cache_epoch" in kinds
    assert "step_dispatch" in kinds and "step_complete" in kinds
    completes = [e for e in new if e["kind"] == "step_complete"]
    assert completes and all(e["ms"] >= 0 for e in completes)
    steps = {e.get("step") for e in completes}
    assert "prefill" in steps and "decode_block" in steps
