"""Tensor-parallel equivalence: sharded forward over a 2/4/8-device mesh must
reproduce the single-device logits (the TPU analogue of the reference's
multi-worker-vs-single-node validation; SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dllama_tpu.formats import FloatType, ModelReader
from dllama_tpu.formats.model_file import LlmArch
from dllama_tpu.models import forward, init_kv_cache, load_params
from dllama_tpu.parallel import (
    cache_specs,
    make_mesh,
    param_spec_tree,
    shard_params_put,
    validate_tp,
)

from helpers import make_tiny_model

TOKENS = [3, 17, 92, 5, 44, 120, 7, 3]


def single_device_logits(reader, tokens):
    params = load_params(reader)
    h = reader.header
    cache = init_kv_cache(h, batch_size=tokens.shape[0])
    logits, _ = forward(params, h, tokens, jnp.int32(0), cache)
    return np.asarray(logits)


def sharded_logits(reader, tokens, tp, dp=1):
    h = reader.header
    mesh = make_mesh(tp=tp, dp=dp)
    params = load_params(reader, put=shard_params_put(mesh, h))
    cache = init_kv_cache(h, batch_size=tokens.shape[0])
    cspecs = cache_specs(h)
    cache = {
        k: jax.device_put(v, NamedSharding(mesh, cspecs[k])) for k, v in cache.items()
    }
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))

    @jax.jit
    def run(params, tokens, pos, cache):
        return forward(params, h, tokens, pos, cache)

    logits, new_cache = run(params, tokens, jnp.int32(0), cache)
    return np.asarray(logits), new_cache, mesh


@pytest.mark.parametrize("tp", [2, 4, 8])
def test_tp_matches_single_device(tmp_path, tp):
    path = str(tmp_path / "m.m")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=16, n_kv_heads=8,
               head_dim=16, vocab_size=256, seq_len=32)
    make_tiny_model(path, weight_type=FloatType.F32, cfg=cfg)
    reader = ModelReader(path)
    validate_tp(reader.header, tp)
    tokens = jnp.asarray([TOKENS], dtype=jnp.int32)
    expected = single_device_logits(reader, tokens)
    got, _, _ = sharded_logits(reader, tokens, tp=tp)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)


def test_tp_cache_is_sharded(tmp_path):
    """The updated KV cache must stay sharded on the kv-head axis (no silent
    full replication of the cache)."""
    path = str(tmp_path / "m.m")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=256, seq_len=32)
    make_tiny_model(path, weight_type=FloatType.F32, cfg=cfg)
    reader = ModelReader(path)
    tokens = jnp.asarray([TOKENS], dtype=jnp.int32)
    _, new_cache, mesh = sharded_logits(reader, tokens, tp=4)
    shard = new_cache["k"].sharding
    assert isinstance(shard, NamedSharding)
    # kv-head axis (index 2 of [L, B, KH, S, hd]) sharded over tp
    spec = tuple(shard.spec) + (None,) * (5 - len(tuple(shard.spec)))
    assert spec[2] == "tp", shard.spec


def test_tp_with_dp(tmp_path):
    """dp=2 x tp=4 over 8 devices: batch of two identical sequences."""
    path = str(tmp_path / "m.m")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=256, seq_len=32)
    make_tiny_model(path, weight_type=FloatType.F32, cfg=cfg)
    reader = ModelReader(path)
    tokens = jnp.asarray([TOKENS, TOKENS], dtype=jnp.int32)
    expected = single_device_logits(reader, tokens)
    got, _, _ = sharded_logits(reader, tokens, tp=4, dp=2)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("arch", [LlmArch.QWEN3, LlmArch.QWEN3_MOE])
def test_tp_qwen3_variants(tmp_path, arch):
    path = str(tmp_path / "m.m")
    make_tiny_model(path, arch=arch, weight_type=FloatType.F32)
    reader = ModelReader(path)
    tokens = jnp.asarray([TOKENS], dtype=jnp.int32)
    expected = single_device_logits(reader, tokens)
    got, _, _ = sharded_logits(reader, tokens, tp=2)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("tp,sp", [(1, 2), (2, 2), (1, 4), (2, 4)])
def test_engine_sp_matches_single_device(tmp_path, tp, sp):
    """Engine-level sequence parallelism: greedy tokens with the KV cache
    sequence-sharded over sp (x kv-heads over tp) must equal the tp=1/sp=1
    run — prefill goes through the ring path, decode through the
    merged-stats path."""
    from dllama_tpu.runtime.engine import InferenceEngine

    path = str(tmp_path / "m.m")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=256, seq_len=64)
    make_tiny_model(path, weight_type=FloatType.F32, cfg=cfg)
    e1 = InferenceEngine(path, tp=1, dtype=jnp.float32, temperature=0.0)
    expected, _, _ = e1.generate([1, 2, 3, 4, 5, 6, 7, 8, 9], max_steps=24)
    esp = InferenceEngine(path, tp=tp, sp=sp, dtype=jnp.float32,
                          temperature=0.0)
    # the cache really is sequence-sharded
    from jax.sharding import PartitionSpec as P

    assert esp.cache["k"].sharding.spec == P(None, "dp", "tp", "sp", None)
    got, _, _ = esp.generate([1, 2, 3, 4, 5, 6, 7, 8, 9], max_steps=24)
    assert got == expected, f"tp={tp} sp={sp}: {got} != {expected}"


def test_engine_sp_with_quantized_weights(tmp_path):
    """sp=2 over Q40-format weights: the sequence-sharded cache and the
    quantized matmul fallback (GSPMD off-TPU) must compose."""
    from dllama_tpu.runtime.engine import InferenceEngine

    path = str(tmp_path / "m.m")
    # dims divisible by 32*tp (the quantized col-split shards the scale
    # tensors' block axis)
    cfg = dict(dim=128, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=256, seq_len=64)
    make_tiny_model(path, weight_type=FloatType.Q40, cfg=cfg)
    e1 = InferenceEngine(path, tp=1, dtype=jnp.float32, temperature=0.0,
                         weight_format="q40")
    expected, _, _ = e1.generate([1, 2, 3, 4, 5], max_steps=16)
    esp = InferenceEngine(path, tp=2, sp=2, dtype=jnp.float32,
                          temperature=0.0, weight_format="q40")
    got, _, _ = esp.generate([1, 2, 3, 4, 5], max_steps=16)
    assert got == expected


def test_engine_sp_rejects_bad_seq_len(tmp_path):
    from dllama_tpu.runtime.engine import InferenceEngine

    path = str(tmp_path / "m.m")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=256, seq_len=60)
    make_tiny_model(path, weight_type=FloatType.F32, cfg=cfg)
    with pytest.raises(ValueError, match="divisible by sp"):
        InferenceEngine(path, sp=8, dtype=jnp.float32)


def test_validate_tp_rejects_bad_configs(tmp_path):
    path = str(tmp_path / "m.m")
    make_tiny_model(path)  # n_kv_heads=2
    h = ModelReader(path).header
    with pytest.raises(ValueError, match="power of two"):
        validate_tp(h, 3)
    with pytest.raises(ValueError, match="nKvHeads"):
        validate_tp(h, 4)
    validate_tp(h, 2)  # ok


def test_weight_shards_actually_split(tmp_path):
    """Row-split weights must be distributed, not replicated: each device
    holds 1/tp of wq (the TPU twin of splitRowMatmulWeight)."""
    path = str(tmp_path / "m.m")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=256, seq_len=32)
    make_tiny_model(path, weight_type=FloatType.F32, cfg=cfg)
    reader = ModelReader(path)
    mesh = make_mesh(tp=4)
    params = load_params(reader, put=shard_params_put(mesh, reader.header))
    wq = params["layers"]["wq"]
    shard_shapes = {s.data.shape for s in wq.addressable_shards}
    assert shard_shapes == {(2, 64, 128 // 4)}


def test_psum_q80_error_bound():
    """Q80-compressed all-reduce (the reference's --buffer-float-type q80,
    src/llm.cpp:195) vs the exact f32 psum on a tp=4 mesh: per-32-block
    int8 quantization bounds the relative error (VERDICT r2 #7)."""
    import jax
    from dllama_tpu.utils.compat import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    from dllama_tpu.parallel.collectives import (
        dequantize_q80_blocks,
        psum_q80,
        quantize_q80_blocks,
    )

    rng = np.random.default_rng(31)
    x = jnp.asarray(rng.standard_normal((4, 1, 256)).astype(np.float32))

    # roundtrip: block-local error <= scale/2 = amax/254
    q, s = quantize_q80_blocks(x)
    rt = dequantize_q80_blocks(q, s)
    blocks = np.asarray(x).reshape(4, 1, 8, 32)
    amax = np.abs(blocks).max(axis=-1)
    assert (
        np.abs(np.asarray(rt).reshape(4, 1, 8, 32) - blocks)
        <= amax[..., None] / 254 + 1e-7
    ).all()
    # all-zero blocks stay exactly zero
    z_q, z_s = quantize_q80_blocks(jnp.zeros((1, 64)))
    assert np.asarray(dequantize_q80_blocks(z_q, z_s)).max() == 0.0

    mesh = make_mesh(tp=4)
    exact = shard_map(
        lambda a: jax.lax.psum(a, "tp"), mesh=mesh,
        in_specs=P("tp"), out_specs=P("tp"), check_vma=False,
    )(x)
    compressed = shard_map(
        lambda a: psum_q80(a, "tp"), mesh=mesh,
        in_specs=P("tp"), out_specs=P("tp"), check_vma=False,
    )(x)
    err = np.abs(np.asarray(compressed) - np.asarray(exact)).max()
    scale = np.abs(np.asarray(exact)).max()
    assert err / scale < 2e-2, (err, scale)


def test_qmatmul_tp_col_q80_sync(monkeypatch):
    """The qmatmul_tp 'col' shard_map branch with sync_quant=True must run
    psum_q80 over the per-shard partial sums and land within quantization
    tolerance of the exact psum. Off-TPU the dispatcher would bypass the
    shard_map path entirely, so force it and stub the Pallas kernel entry
    with the reference matmul — the wiring under test is the collective,
    not the kernel."""
    from dllama_tpu.ops import quant_matmul as qm
    from dllama_tpu.formats.quants import q40_to_planar, quantize_q40

    monkeypatch.setattr(qm, "_use_pallas", lambda: True)
    monkeypatch.setattr(
        qm, "qmatmul", lambda x, w, block_n=256: qm.qmatmul_ref(x, w)
    )

    rng = np.random.default_rng(33)
    k_dim, n_dim = 128, 64
    w = rng.standard_normal((n_dim, k_dim)).astype(np.float32) * 0.1
    qv, dv = q40_to_planar(quantize_q40(w), n_dim * k_dim)
    qw = qm.from_planar(qv.reshape(n_dim, k_dim), dv.reshape(n_dim, k_dim // 32))
    x = jnp.asarray(rng.standard_normal((1, 1, k_dim)).astype(np.float32))

    mesh = make_mesh(tp=2)
    exact = qm.qmatmul_tp(x, qw, "col", mesh, sync_quant=False)
    q80 = qm.qmatmul_tp(x, qw, "col", mesh, sync_quant=True)
    scale = float(np.abs(np.asarray(exact)).max())
    err = float(np.abs(np.asarray(q80) - np.asarray(exact)).max())
    assert err / scale < 2e-2, (err, scale)
    assert err > 0.0  # the compressed collective actually ran


def test_lanes_with_sp_mesh(tmp_path):
    """Continuous batching composed with sequence parallelism (VERDICT r2
    weak #3): per-lane prefill + per-lane decode on a tp=2 x sp=2 mesh
    must reproduce each prompt's single-stream tokens."""
    path = str(tmp_path / "m.m")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=256, seq_len=64)
    make_tiny_model(path, weight_type=FloatType.F32, cfg=cfg)
    from dllama_tpu.runtime.engine import InferenceEngine

    prompts = [[1, 2, 3, 4], [9, 8, 7, 6, 5, 4]]  # different lengths
    singles = []
    e1 = InferenceEngine(path, tp=1, dtype=jnp.float32, temperature=0.0)
    for p in prompts:
        e1.reset()
        out, _, _ = e1.generate(p, max_steps=16)
        singles.append(out)
    del e1

    esp = InferenceEngine(
        path, tp=2, sp=2, dtype=jnp.float32, temperature=0.0, batch_size=2
    )
    outs = esp.generate_batch(prompts, max_steps=16)
    assert outs == singles, (outs, singles)


def test_qmatmul_tp_row_fused_shard_map(monkeypatch):
    """The 'row' shard_map branch over a FUSED shard-major-interleaved
    weight: each tp shard must receive its own q|k|v slice and the
    un-interleave must restore the split results. Off-TPU the dispatcher
    bypasses shard_map, so force it (Pallas entry stubbed with the
    reference matmul — the wiring under test is the partitioning)."""
    from dllama_tpu.ops import quant_matmul as qm
    from dllama_tpu.formats.quants import q40_to_planar, quantize_q40
    from dllama_tpu.models.loader import _interleave_concat
    from dllama_tpu.models.transformer import _split_fused

    monkeypatch.setattr(qm, "_use_pallas", lambda: True)
    monkeypatch.setattr(
        qm, "qmatmul", lambda x, w, block_n=256: qm.qmatmul_ref(x, w)
    )

    rng = np.random.default_rng(44)
    tp, k_dim = 2, 128
    dims = (64, 32, 32)

    def qw_for(n_dim, seed):
        r = np.random.default_rng(seed)
        w = r.standard_normal((n_dim, k_dim)).astype(np.float32) * 0.1
        qv, dv = q40_to_planar(quantize_q40(w), n_dim * k_dim)
        return qm.from_planar(
            qv.reshape(n_dim, k_dim), dv.reshape(n_dim, k_dim // 32)
        )

    qws = [qw_for(d, 50 + i) for i, d in enumerate(dims)]
    fused = qm.QuantWeight(
        jnp.asarray(_interleave_concat([np.asarray(w.q) for w in qws], tp)),
        jnp.asarray(_interleave_concat([np.asarray(w.d) for w in qws], tp)),
    )
    x = jnp.asarray(rng.standard_normal((1, 1, k_dim)).astype(np.float32))
    mesh = make_mesh(tp=tp)

    out = qm.qmatmul_tp(x, fused, "row", mesh)
    parts = _split_fused(out, tp, dims)
    for part, w in zip(parts, qws):
        expect = qm.qmatmul_tp(x, w, "row", mesh)
        np.testing.assert_allclose(
            np.asarray(part), np.asarray(expect), rtol=1e-5, atol=1e-5
        )


def test_engine_sp_windowed_decode_parity(tmp_path):
    """sp=2 with a seq_len large enough that decode windows engage
    (window = 512*sp < seq_len): the cyclic cache layout must keep exact
    token parity with the single-device engine across the window."""
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    from helpers import make_tiny_model
    from dllama_tpu.formats import FloatType
    from dllama_tpu.runtime.engine import InferenceEngine

    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=4, n_kv_heads=2,
               head_dim=16, vocab_size=256, seq_len=2048)
    mp = str(tmp_path / "mw.m")
    make_tiny_model(mp, weight_type=FloatType.Q40, seed=21, cfg=cfg)
    prompt = [(i * 7) % 250 + 1 for i in range(9)]
    e1 = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    assert e1._attn_window(10) == 512
    expected, _, _ = e1.generate(prompt, max_steps=24)
    del e1
    esp = InferenceEngine(mp, tp=1, sp=2, dtype=jnp.float32, temperature=0.0)
    # the sp window is a 512-row local prefix per shard, not the full cache
    assert esp._attn_window(10) == 1024 < cfg["seq_len"]
    got, _, _ = esp.generate(prompt, max_steps=24)
    del esp
    assert got == expected, (got, expected)


def test_sp_window_cuts_decode_bytes(tmp_path):
    """VERDICT r3 item 5: per-step sp decode reads must be proportional
    to the window, not seq_len — compiled bytes-accessed of a windowed
    sp decode step is well below the unwindowed one."""
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    from helpers import make_tiny_model
    from dllama_tpu.formats import FloatType
    from dllama_tpu.models import forward, init_kv_cache, load_params
    from dllama_tpu.formats import ModelReader

    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=4, n_kv_heads=2,
               head_dim=16, vocab_size=256, seq_len=4096)
    mp = str(tmp_path / "mb.m")
    make_tiny_model(mp, weight_type=FloatType.Q40, seed=21, cfg=cfg)
    r = ModelReader(mp)
    h = r.header
    params = load_params(r, weight_format="dense")
    mesh = make_mesh(sp=2)
    tok = jnp.asarray([[7]], jnp.int32)

    def compiled_bytes(window):
        cache = init_kv_cache(h, 1)

        def step(p, t, c):
            return forward(
                p, h, t, jnp.int32(600), c, mesh=mesh, attn_window=window
            )

        cost = (
            jax.jit(step, donate_argnums=(2,))  # engine donates the cache
            .lower(params, tok, cache)
            .compile()
            .cost_analysis()
        )
        if isinstance(cost, list):
            cost = cost[0]
        return cost.get("bytes accessed", 0.0)

    b_1k = compiled_bytes(1024)
    b_2k = compiled_bytes(2048)
    b_full = compiled_bytes(0)
    # the cache-read term must scale with the window: each 1024 rows of
    # window are L x KH x 1024 x hd x 4B x {k,v} = 0.52 MB of reads
    row_bytes = 2 * 2 * 16 * 4 * 2  # L * KH * hd * itemsize * (k+v)
    step = 1024 * row_bytes
    assert b_2k - b_1k > 0.8 * step, (b_1k, b_2k)
    assert b_full - b_2k > 0.8 * 2 * step, (b_2k, b_full)  # full = 4096


def test_vocab_sharded_embed_no_table_gather(tmp_path):
    """The embed table is vocab-sharded (sharding.py: P(\"tp\", None)) so a
    tp>1 flat-path forward must NOT lower an all-gather that reassembles
    the [vocab, dim] table on every chip — the lookup masks locally and
    psums the [B, T, D] activation (the reference holds the table on the
    root node only, SYNC_WITH_ROOT, src/llm.cpp:256). The logits
    all-gather over [B, T, vocab] is expected and allowed.

    Thin wrapper over the xlalint collective-census parser
    (analysis/rules_hlo.py) — the regather check that used to live here
    as a one-off regex now guards EVERY compiled program the engine
    builds; this test keeps the targeted flat-forward coverage."""
    from dllama_tpu.analysis.rules_hlo import forbidden_gather_findings

    path = str(tmp_path / "m.m")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=256, seq_len=32)
    make_tiny_model(path, weight_type=FloatType.F32, cfg=cfg)
    reader = ModelReader(path)
    h = reader.header
    mesh = make_mesh(tp=2)
    params = load_params(reader, put=shard_params_put(mesh, h))
    cache = init_kv_cache(h, batch_size=1)
    cspecs = cache_specs(h)
    cache = {
        k: jax.device_put(v, NamedSharding(mesh, cspecs[k]))
        for k, v in cache.items()
    }
    tokens = jnp.asarray([TOKENS], dtype=jnp.int32)

    def step(p, t, c):
        return forward(p, h, t, jnp.int32(0), c)

    txt = jax.jit(step).lower(params, tokens, cache).compile().as_text()
    table_dims = {(cfg["vocab_size"], cfg["dim"]),
                  (cfg["dim"], cfg["vocab_size"])}
    # trailing-two check also rejects batched [.., vocab, dim] variants
    hits = forbidden_gather_findings(txt, table_dims)
    assert not hits, (
        f"all-gather reassembles the full embed/wcls table: {hits}"
    )
    # the per-partition HLO carries the V/tp-row shard; the full table
    # shape must not materialize in ANY op (gather, copy, or otherwise) —
    # replicating `embed` instead makes f32[256,64] appear immediately
    v, dim = cfg["vocab_size"], cfg["dim"]
    assert f"f32[{v // 2},{dim}]" in txt
    assert f"f32[{v},{dim}]" not in txt


def _scatter_operand_dims(hlo_text):
    """Dims of every scatter op's result in an HLO dump (thin wrapper
    over the shared xlalint parser, keeping this module's historical
    helper name)."""
    from dllama_tpu.analysis.rules_hlo import scatter_result_dims

    return [list(d) for d in scatter_result_dims(hlo_text)]


def test_cyclic_write_lowering_isolated():
    """_cache_append_cyclic's T>1 scatter (transformer.py, the flat-GSPMD
    sp write; VERDICT r4 #4) must partition into a SHARD-LOCAL scatter:
    zero collectives, operand rows = S/sp not S. Mirrors the closure's
    exact index math (perm(g) = (g%sp)*shard_rows + g//sp)."""
    SP, B, KH, S, HD, T = 4, 1, 2, 4096, 64, 16
    shard_rows = S // SP
    mesh = make_mesh(sp=SP)
    shard = NamedSharding(mesh, P(None, None, "sp", None))

    def perm(g):
        return (g % SP) * shard_rows + g // SP

    rows = jnp.arange(T, dtype=jnp.int32)

    def write(cache, val, pos):
        return cache.at[:, :, perm(pos + rows)].set(val)

    def write_per_lane(cache, val, pos):
        return jax.vmap(lambda c, u, p: c.at[:, perm(p + rows)].set(u))(
            cache, val, pos
        )

    cache = jax.device_put(jnp.zeros((B, KH, S, HD), jnp.float32), shard)
    val = jnp.ones((B, KH, T, HD), jnp.float32)
    for fn, pos in (
        (write, jnp.int32(600)),
        (write_per_lane, jnp.full((B,), 600, jnp.int32)),
    ):
        txt = (
            jax.jit(fn, donate_argnums=(0,), out_shardings=shard)
            .lower(cache, val, pos)
            .compile()
            .as_text()
        )
        # shard-local means ZERO collectives of any kind (census parser
        # shared with xlalint, analysis/rules_hlo.py)
        from dllama_tpu.analysis.rules_hlo import collective_census

        assert collective_census(txt) == {}, (
            fn.__name__, collective_census(txt)
        )
        dims = _scatter_operand_dims(txt)
        assert dims, f"{fn.__name__}: expected a scatter lowering"
        for d in dims:
            assert S not in d, (fn.__name__, d)  # not a full-S scatter
            assert shard_rows in d, (fn.__name__, d)


def test_cyclic_write_lowering_in_forward(tmp_path):
    """Same pin on the REAL forward: a T>1 prefill chunk on an sp mesh
    compiles with no all-to-all and only shard-local scatters (every
    scatter operand carries the S/sp local row count, never full S)."""
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=4, n_kv_heads=2,
               head_dim=16, vocab_size=256, seq_len=4096)
    mp = str(tmp_path / "cyc.m")
    make_tiny_model(mp, weight_type=FloatType.Q40, seed=23, cfg=cfg)
    r = ModelReader(mp)
    h = r.header
    params = load_params(r, weight_format="dense")
    mesh = make_mesh(sp=2)
    cache = init_kv_cache(h, 1)
    tok = jnp.ones((1, 16), jnp.int32)

    def step(p, t, c):
        return forward(p, h, t, jnp.int32(600), c, mesh=mesh)

    txt = (
        jax.jit(step, donate_argnums=(2,))
        .lower(params, tok, cache)
        .compile()
        .as_text()
    )
    from dllama_tpu.analysis.rules_hlo import collective_census

    assert "all-to-all" not in collective_census(txt)
    dims = _scatter_operand_dims(txt)
    assert dims, "expected the cyclic cache write to lower to a scatter"
    for d in dims:
        assert cfg["seq_len"] not in d, d
        assert cfg["seq_len"] // 2 in d, d


def test_measure_sync_ms_collectives():
    """measure_sync_ms (the reference's per-step sync clock restated for
    XLA, nn-executor.cpp:158-163): a psum-heavy program on the 8-device
    mesh reports nonzero collective time; a collective-free program
    reports ~0."""
    from dllama_tpu.utils.compat import shard_map_compat as shard_map
    from dllama_tpu.utils.telemetry import measure_sync_ms

    mesh = make_mesh(tp=8)
    x = jnp.ones((8, 1024), jnp.float32)

    def with_psum():
        f = shard_map(
            lambda v: jax.lax.psum(v @ v.T, "tp"),
            mesh=mesh,
            in_specs=P("tp", None),
            out_specs=P(None, None),
            check_vma=False,
        )
        out = jax.jit(f)(x)
        np.asarray(out)

    def without():
        out = jax.jit(lambda v: v * 2.0)(x)
        np.asarray(out)

    ms_with = measure_sync_ms(with_psum, steps=2)
    ms_without = measure_sync_ms(without, steps=2)
    if ms_with is None:
        import pytest as _pytest

        _pytest.skip("profiler trace unavailable on this backend")
    assert ms_with > 0.0
    assert (ms_without or 0.0) <= ms_with
