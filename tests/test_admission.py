"""Chunked, stall-free admission (ISSUE 5).

The lane scheduler admits a request one bounded prefill chunk per loop
tick, interleaved with decode blocks, instead of one monolithic
`prefill_lane` that freezes every active stream for the whole prompt.
These tests pin the three contract points:

* token parity — chunked/interleaved admission writes the same KV rows
  as the monolithic path, so a seeded stream is byte-identical (fresh
  lane AND prefix-reuse resume with a pending token);
* the regression the rework fixes — a decode block runs between any two
  admission chunks while an active lane exists, and concurrent
  admissions round-robin fairly;
* the stall model — `dllama_decode_stall_seconds` observes gaps bounded
  by one chunk + one block (fake-clock), never the whole prefill.
"""

import threading
import time

import jax.numpy as jnp
import pytest

from dllama_tpu.runtime.api_server import (
    ApiState,
    ChatMessage,
    InferenceParams,
    LaneJob,
    resolve_lane_knobs,
)
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.tokenizer import Tokenizer

from helpers import make_tiny_model, make_tiny_tokenizer

CFG = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
           head_dim=16, vocab_size=288, seq_len=384)


@pytest.fixture(scope="module")
def tiny_paths(tmp_path_factory):
    d = tmp_path_factory.mktemp("admission")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    make_tiny_model(mp, cfg=CFG)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    return mp, tp_


@pytest.fixture(scope="module")
def sched_state(tiny_paths):
    """A scheduler-backed ApiState driven directly (no HTTP): tests reach
    the recorder, the metrics handles, and the scheduler internals."""
    mp, tp_ = tiny_paths
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=3,
    )
    state = ApiState(
        engine, tok, lane_block_size=4, admission_chunk=6,
    )
    assert state.scheduler is not None
    return state


def _drain(job, timeout=300):
    deltas = []
    deadline = time.time() + timeout
    while True:
        kind, payload = job.events.get(timeout=max(0.1, deadline - time.time()))
        if kind == "delta":
            deltas.append(payload)
        elif kind == "done":
            return "".join(deltas), payload
        else:
            raise AssertionError(f"job errored: {payload}")


def _submit_together(state, *params):
    """Enqueue several jobs atomically so the scheduler's admission pick
    sees them in the same tick (the round-robin fairness scenario)."""
    sched = state.scheduler
    jobs = []
    for p in params:
        job = LaneJob(p)
        job.span = state.tracer.span(path="lanes")
        jobs.append(job)
    with sched.cv:
        sched.pending.extend(jobs)
        state.m_queue_depth.set(len(sched.pending))
        sched.cv.notify()
    return jobs


def _wait_active(state, timeout=300):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if any(state.scheduler.lanes):
            return
        time.sleep(0.02)
    raise AssertionError("no lane became active")


# -- tentpole: token parity ---------------------------------------------------


@pytest.mark.fast
def test_chunked_prefill_token_parity(tiny_paths):
    """Chunked admission (small budget, interleaved with live decode on
    another lane) produces the byte-identical seeded stream of the
    monolithic prefill_lane path — fresh lane AND prefix-reuse resume
    where the conversation's pending final token is fed at the recorded
    end position."""
    mp, _ = tiny_paths
    e = InferenceEngine(
        mp, tp=1, dtype=jnp.float32, temperature=0.8, batch_size=2
    )
    prompt = [2 + (i * 7) % 250 for i in range(23)]
    delta = [3 + (i * 5) % 250 for i in range(9)]

    def decode_stream(token, pos, steps, seed):
        """Seeded lane-0 decode; per-lane seeding makes the stream depend
        only on (seed, positions), not on other-lane traffic."""
        toks, t, p = [], token, pos
        while len(toks) < steps:
            n = min(4, steps - len(toks))
            rows = e.decode_lanes(
                [t, 0], [p, 0], n, [True, False],
                [0.8, 0.8], [0.9, 0.9], seeds=[seed, None],
            )
            toks.extend(r[0] for r in rows)
            t, p = toks[-1], p + n
        return toks

    # -- run A: monolithic admission ------------------------------------
    e.reset()
    e.prefill_lane(0, prompt, pos0=0)
    a1 = decode_stream(prompt[-1], len(prompt) - 1, 12, seed=42)
    resume_pos = len(prompt) - 1 + 12
    # resume: pending token (the last generated one; its KV row was never
    # written) feeds first at the recorded end position
    tokens2 = [a1[-1]] + delta
    e.prefill_lane(0, tokens2, pos0=resume_pos)
    a2 = decode_stream(
        tokens2[-1], resume_pos + len(tokens2) - 1, 8, seed=7
    )

    # -- run B: chunked admission, interleaved with lane-1 decode --------
    e.reset()
    e.prefill_lane(1, [9, 11, 13, 15])
    s1 = {"t": 15, "p": 3}

    def chunked_prefill_interleaved(tokens, pos0):
        fills, cur = tokens[:-1], 0
        while cur < len(fills):
            width = e.prefill_lane_chunk(
                0, fills[cur:], pos0 + cur, budget=3
            )
            assert 0 < width <= 3
            cur += width
            # live traffic between chunks — exactly what the scheduler
            # interleaves; lane 0's KV must come out identical anyway
            rows = e.decode_lanes(
                [0, s1["t"]], [0, s1["p"]], 2, [False, True],
                [0.8, 0.8], [0.9, 0.9], seeds=[None, 5],
            )
            s1["t"], s1["p"] = rows[-1][1], s1["p"] + len(rows)

    chunked_prefill_interleaved(prompt, 0)
    b1 = decode_stream(prompt[-1], len(prompt) - 1, 12, seed=42)
    tokens2b = [b1[-1]] + delta
    chunked_prefill_interleaved(tokens2b, resume_pos)
    b2 = decode_stream(
        tokens2b[-1], resume_pos + len(tokens2b) - 1, 8, seed=7
    )

    assert b1 == a1  # fresh-lane parity
    assert b2 == a2  # prefix-reuse resume (pending token) parity


# -- bugfix regression: decode between chunks, round-robin fairness -----------


def test_decode_runs_between_admission_chunks(sched_state):
    """The old loop admitted pending jobs back-to-back as consecutive full
    prefills before any decode ran. Under the chunked state machine, a
    decode block must run between any two admission chunks while an
    active lane exists — and two concurrent admissions must round-robin
    (strictly alternating chunks while both have fills left)."""
    state = sched_state
    sched, rec = state.scheduler, state.recorder

    job_a = sched.submit(InferenceParams(
        messages=[ChatMessage("user", "hi")], max_tokens=220,
        temperature=0.0,
    ))
    _wait_active(state)  # A is decoding; its lane stays active throughout
    base = rec.total_recorded
    b_chunks = state.m_admission_chunks.value

    long_txt = " ".join(f"tok{i:02d}" for i in range(25))
    jobs = _submit_together(
        state,
        InferenceParams(messages=[ChatMessage("user", long_txt + " b")],
                        max_tokens=3, temperature=0.0),
        InferenceParams(messages=[ChatMessage("user", long_txt + " c")],
                        max_tokens=3, temperature=0.0),
    )
    for job in jobs:
        _drain(job)
    job_a.cancelled = True
    _, reason_a = _drain(job_a)
    assert reason_a in ("cancelled", "length", "stop")

    # Replay the recorder: (op, lane, n_active_lanes_at_dispatch). The
    # admit/finish events bracket each lane's decode-active window, so we
    # know per chunk whether a stream was live at that moment (lane A may
    # legitimately hit its length limit before the admissions finish, at
    # which point back-to-back chunks are fine — nobody is stalled).
    ops, active = [], {0}  # lane A was admitted before `base`
    for ev in rec.events():
        if ev["seq"] <= base:
            continue
        if ev["kind"] == "admit":
            active.add(ev["lane"])
        elif ev["kind"] == "finish":
            active.discard(ev["lane"])
        elif ev["kind"] == "step_dispatch":
            if ev.get("step") == "prefill_lane_chunk":
                ops.append(("chunk", ev["lane"], len(active)))
            elif ev.get("step") == "decode_lanes":
                ops.append(("decode", None, len(active)))
    chunk_idx = [i for i, op in enumerate(ops) if op[0] == "chunk"]
    live_pairs = 0
    # the regression assert: never two admission chunks back-to-back
    # while any lane is actively decoding
    for i, j in zip(chunk_idx, chunk_idx[1:]):
        if ops[i][2] > 0:
            live_pairs += 1
            assert any(ops[x][0] == "decode" for x in range(i + 1, j)), ops
    # ... and the scenario genuinely exercised that: many chunks landed
    # while lane A's stream was live
    assert live_pairs >= 4, ops
    # round-robin fairness: while BOTH admissions still have chunks
    # coming, consecutive chunks never go to the same lane
    lanes_seq = [lane for op, lane, _ in ops if op == "chunk"]
    for i in range(len(lanes_seq) - 1):
        if len(set(lanes_seq[i + 1:])) > 1:
            assert lanes_seq[i + 1] != lanes_seq[i], lanes_seq
    assert state.m_admission_chunks.value - b_chunks == len(lanes_seq)


# -- stall model: chunk events + bounded decode gaps (fake clock) -------------


def test_fake_clock_stall_bounded_by_chunk_plus_block(
    sched_state, monkeypatch
):
    """Fake-clock scheduler run: every engine dispatch (chunk or decode
    block) advances the clock by exactly 1.0 'seconds'. While a long
    prompt admits against an active stream, every
    dllama_decode_stall_seconds observation must then be <= one chunk
    (1.0) + host epsilon — NOT the whole prefill (n_chunks) — and the
    admission must emit exactly ceil(n_fills / chunk_budget) recorder
    chunk events."""
    state = sched_state
    sched, eng, rec = state.scheduler, state.engine, state.recorder

    fake = {"t": 0.0}
    monkeypatch.setattr(sched, "_clock", lambda: fake["t"])
    real_chunk, real_decode = eng.prefill_lane_chunk, eng.decode_lanes

    def chunk_wrapped(*a, **k):
        out = real_chunk(*a, **k)
        fake["t"] += 1.0
        return out

    def decode_wrapped(*a, **k):
        out = real_decode(*a, **k)
        fake["t"] += 1.0
        return out

    monkeypatch.setattr(eng, "prefill_lane_chunk", chunk_wrapped)
    monkeypatch.setattr(eng, "decode_lanes", decode_wrapped)
    samples: list[float] = []
    real_observe = state.m_decode_stall.observe
    monkeypatch.setattr(
        state.m_decode_stall, "observe",
        lambda v: (samples.append(v), real_observe(v))[1],
    )

    job_a = sched.submit(InferenceParams(
        messages=[ChatMessage("user", "go")], max_tokens=220,
        temperature=0.0,
    ))
    _wait_active(state)
    base = rec.total_recorded
    samples.clear()

    long_txt = " ".join(f"w{i:03d}" for i in range(30))
    job_b = sched.submit(InferenceParams(
        messages=[ChatMessage("user", long_txt)], max_tokens=2,
        temperature=0.0,
    ))
    _drain(job_b)
    job_a.cancelled = True
    _drain(job_a)
    # let the loop go idle so the monkeypatched clock is never read again
    deadline = time.time() + 60
    while time.time() < deadline and (sched.admitting or any(sched.lanes)):
        time.sleep(0.02)

    # the radix pool may have matched a stored prefix (the rendered
    # template header is shared across conversations): the chunked
    # prefill covers only the unmatched fill suffix
    admit = next(
        e for e in rec.events()
        if e["seq"] > base and e["kind"] == "admit"
        and e["n_prompt"] == job_b.n_prompt_tokens
    )
    n_fills = job_b.n_prompt_tokens - 1 - admit["reused_prefix_tokens"]
    budget = sched.admission_chunk
    expected_chunks = -(-n_fills // budget)  # ceil
    chunk_events = [
        e for e in rec.events()
        if e["seq"] > base and e["kind"] == "admission_chunk"
    ]
    assert len(chunk_events) == expected_chunks
    assert expected_chunks >= 5  # a genuinely long admission
    assert sum(e["n_tokens"] for e in chunk_events) == n_fills
    assert chunk_events[-1]["done"] and not chunk_events[0]["done"]

    # the stall bound: one chunk (1.0 fake second) + one block of host
    # work; the monolithic path would have shown expected_chunks seconds
    assert samples, "no decode-stall observations"
    assert max(samples) <= 1.5, samples
    assert max(samples) < expected_chunks - 1
    # and the admission really did sit between decode dispatches: at
    # least one observed gap contains a whole chunk
    assert any(s >= 1.0 for s in samples), samples


# -- rehearsal: admission programs pre-compiled off-thread --------------------


def test_admission_rehearsal_precompiles_chunk_programs(sched_state):
    """LaneScheduler startup rehearses the admission path: every prefill
    bucket's lane-prefill chunk program (and the decode block) lands in
    the compile cache via the background prefetch, so the first admission
    under load pays no synchronous compile stall."""
    eng = sched_state.engine
    keys = [
        ("lane_prefill", b, eng._attn_window(b)) for b in eng.prefill_buckets
    ]
    keys.append(
        ("lane_block", sched_state.scheduler.block_size,
         eng._attn_window(sched_state.scheduler.block_size))
    )
    deadline = time.time() + 180
    while time.time() < deadline and any(k not in eng._compiled for k in keys):
        time.sleep(0.2)
    for k in keys:
        assert k in eng._compiled, k
        assert eng._compile_origin[k] in ("prefetch", "dispatch"), (
            k, eng._compile_origin[k],
        )


# -- knobs: CLI flags + env overrides -----------------------------------------


@pytest.mark.fast
def test_lane_knob_resolution(monkeypatch):
    import argparse

    from dllama_tpu.cli import add_engine_args

    parser = argparse.ArgumentParser()
    add_engine_args(parser)
    args = parser.parse_args(
        ["--lane-block-size", "4", "--admission-chunk", "16"]
    )
    assert args.lane_block_size == 4
    assert args.admission_chunk == 16

    monkeypatch.delenv("DLLAMA_LANE_BLOCK", raising=False)
    monkeypatch.delenv("DLLAMA_ADMISSION_CHUNK", raising=False)
    assert resolve_lane_knobs(None, None) == (8, 0)  # 0 = auto
    monkeypatch.setenv("DLLAMA_LANE_BLOCK", "5")
    monkeypatch.setenv("DLLAMA_ADMISSION_CHUNK", "24")
    assert resolve_lane_knobs(None, None) == (5, 24)
    # an explicit flag beats the env override
    assert resolve_lane_knobs(4, 16) == (4, 16)


def test_scheduler_knob_threading(sched_state):
    """The knobs reach the LaneScheduler (no hardcoded block_size=8)."""
    sched = sched_state.scheduler
    assert sched.block_size == 4
    assert sched.admission_chunk == 6
