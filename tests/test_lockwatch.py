"""lockwatch tests (PR 8): lock-order cycle detection, deterministic
seeded interleaving, the env-gated production factory, and the PR 6
match->adopt race replayed as a regression against the real PagePool +
RadixTree.

The PR 6 bug shape: ``match()`` returned radix-tree pages WITHOUT
retaining them; the scheduler ran the adopt copy one tick later. In
that window another lane's publish->evict could free the refcount-1
pages and the pool could hand them to a different request — the late
retain then pinned pages that now hold someone else's KV (silent
cross-request corruption). The fix retains inside ``match()`` under the
manager lock. Here both protocols run under the Interleaver across a
seed sweep: the pre-fix shape corrupts on at least one schedule and
does so identically on replay; the post-fix shape is clean on every
schedule.
"""

import threading

import pytest

from dllama_tpu.analysis import lockwatch
from dllama_tpu.analysis.lockwatch import (
    Interleaver,
    LockOrderViolation,
    LockWatch,
    TrackedLock,
    make_condition,
    make_lock,
)
from dllama_tpu.kv import PagePool, RadixTree

PS = 4


# -- lock-order graph ---------------------------------------------------------


@pytest.mark.fast
def test_cycle_detected_across_threads():
    """A->B in one thread, B->A in another: the second order must raise
    (this schedule is the textbook deadlock shape)."""
    w = LockWatch()
    a, b = TrackedLock("A", w), TrackedLock("B", w)
    itl = Interleaver(seed=3)

    def forward():
        with itl.acquire(a, "A"):
            itl.step("holding-A")
            with itl.acquire(b, "B"):
                itl.step("holding-AB")

    def backward():
        with itl.acquire(b, "B"):
            itl.step("holding-B")
            with itl.acquire(a, "A"):
                itl.step("holding-BA")

    itl.spawn("fwd", forward)
    itl.spawn("bwd", backward)
    with pytest.raises(LockOrderViolation) as ei:
        itl.run()
    msg = str(ei.value)
    assert "closes the cycle" in msg and "A" in msg and "B" in msg


@pytest.mark.fast
def test_consistent_order_is_clean():
    w = LockWatch()
    a, b = TrackedLock("A", w), TrackedLock("B", w)
    for _ in range(3):
        with a, b:
            pass
    assert w.edges() == {"A": {"B"}}


@pytest.mark.fast
def test_three_lock_cycle_detected():
    """A->B, B->C, then C->A: the cycle spans three locks, not a simple
    inversion, so detection must walk the graph transitively."""
    w = LockWatch()
    a, b, c = (TrackedLock(n, w) for n in "ABC")
    with a, b:
        pass
    with b, c:
        pass
    with pytest.raises(LockOrderViolation):
        with c:
            a.acquire()


@pytest.mark.fast
def test_tracked_lock_is_drop_in():
    w = LockWatch()
    lk = TrackedLock("L", w)
    assert not lk.locked()
    assert lk.acquire(blocking=False)
    assert lk.locked()
    assert not lk.acquire(blocking=False)  # held -> non-blocking fails
    lk.release()
    assert not lk.locked()
    # Condition built over a TrackedLock: wait/notify round trip works
    cond = threading.Condition(TrackedLock("C", w))
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5.0)
            hits.append(1)

    t = threading.Thread(target=waiter, daemon=True, name="dllama-t-waiter")
    t.start()
    while True:
        with cond:
            cond.notify_all()
        t.join(timeout=0.05)
        if not t.is_alive():
            break
    assert hits == [1]


@pytest.mark.fast
def test_factory_is_env_gated(monkeypatch):
    monkeypatch.delenv("DLLAMA_LOCKWATCH", raising=False)
    assert isinstance(make_lock("x"), type(threading.Lock()))
    monkeypatch.setenv("DLLAMA_LOCKWATCH", "1")
    lk = make_lock("x")
    assert isinstance(lk, TrackedLock)
    cond = make_condition("y")
    assert isinstance(cond, threading.Condition)
    lockwatch.global_watch().reset()


# -- deterministic interleaving ----------------------------------------------


def _two_thread_trace(seed):
    itl = Interleaver(seed=seed)
    order = []

    def a():
        itl.step("a1")
        order.append("a1")
        itl.step("a2")
        order.append("a2")

    def b():
        itl.step("b1")
        order.append("b1")
        itl.step("b2")
        order.append("b2")

    itl.spawn("a", a)
    itl.spawn("b", b)
    trace = itl.run()
    return trace, order


@pytest.mark.fast
def test_interleaver_is_deterministic_per_seed():
    t1, o1 = _two_thread_trace(7)
    t2, o2 = _two_thread_trace(7)
    assert t1 == t2 and o1 == o2
    # and seeds actually explore different schedules
    seen = {tuple(_two_thread_trace(s)[1]) for s in range(8)}
    assert len(seen) > 1


@pytest.mark.fast
def test_interleaver_propagates_thread_errors():
    itl = Interleaver(seed=0)

    def boom():
        itl.step("pre")
        raise ValueError("from controlled thread")

    itl.spawn("boom", boom)
    with pytest.raises(ValueError, match="from controlled thread"):
        itl.run()


# -- the PR 6 match->adopt race, replayed -------------------------------------


def _race_round(seed: int, retain_in_match: bool):
    """One seeded schedule of victim-vs-evictor over real kv structures.

    Returns (overlap, trace): pages the victim adopted that the attacker
    was simultaneously handed (non-empty == cross-request corruption),
    plus the schedule trace for determinism checks.
    """
    pool = PagePool(10, PS)
    tree = RadixTree(PS)
    prefix = list(range(2 * PS))
    published = pool.alloc(2)
    tree.insert(prefix, published, 0)  # tree holds the only refcount

    itl = Interleaver(seed=seed)
    lock = threading.Lock()  # the manager lock (plain: order not under test)
    result = {}

    def victim():
        # manager.match(): look up the prefix under the lock
        with itl.acquire(lock, "mgr"):
            mr = tree.match(prefix)
            held = list(mr.pages)
            if retain_in_match:  # post-fix: pin pages before the gap
                pool.retain(held)
        itl.step("tick-gap")  # scheduler runs the adopt copy a tick later
        with itl.acquire(lock, "mgr"):
            if not retain_in_match:  # pre-fix: retain at adopt time
                try:
                    pool.retain(held)
                except KeyError:
                    # pages already freed AND not reallocated: loud case
                    result["victim_pages"] = []
                    return
            result["victim_pages"] = held

    def evictor():
        # another lane's publish->evict pressure in the same window
        with itl.acquire(lock, "mgr"):
            tree.evict(2, pool)
        itl.step("between")
        with itl.acquire(lock, "mgr"):
            # pool hands the freed pages straight to a new request
            result["stolen"] = pool.alloc(min(2, pool.free_pages))

    itl.spawn("victim", victim)
    itl.spawn("evictor", evictor)
    trace = itl.run()
    overlap = set(result.get("victim_pages", ())) & set(
        result.get("stolen", ())
    )
    return overlap, trace


@pytest.mark.fast
def test_pr6_race_reproduces_pre_fix_and_is_fixed_post_fix():
    # 64 seeds: the corrupting order (match, evict, realloc, adopt) is
    # one of ~8 equally likely schedules, so a handful of seeds hit it
    seeds = range(64)
    corrupting = [s for s in seeds if _race_round(s, False)[0]]
    # the pre-fix protocol MUST corrupt under some schedule: the victim
    # adopts pages the pool just handed to the attacker
    assert corrupting, "no seed reproduced the pre-fix race"
    # the post-fix protocol (retain inside match) is clean on EVERY
    # schedule, including the ones that corrupted pre-fix
    for s in seeds:
        overlap, _ = _race_round(s, True)
        assert not overlap, f"post-fix protocol corrupted under seed {s}"


@pytest.mark.fast
def test_pr6_race_replay_is_deterministic():
    seed = next(s for s in range(64) if _race_round(s, False)[0])
    o1, t1 = _race_round(seed, False)
    o2, t2 = _race_round(seed, False)
    assert o1 == o2 and t1 == t2  # same seed -> same schedule, same bug
