"""Second-generation speculation (ISSUE 18): cross-lane shared n-gram
store + resident draft model.

The contract is unchanged from ISSUE 10 — speculation must be invisible
in the output — but the draft SOURCES grow:

* radix node identity — every tree node carries a stable ``node_id``,
  ``match`` reports the deepest matching edge's id as the anchor, and an
  edge SPLIT keeps the id on the shared-prefix head, so streams grouped
  under an anchor stay grouped after later inserts carve the edge up;
* shared store — accepted runs publish under the lane's anchor; a
  sibling lane that matched the same node drafts the published
  continuation (never its own), LRU-capped at both levels;
* source ladder — private n-gram vs shared store by longest suffix
  match (ties private), resident draft model when both run dry or when
  a fully rejected n-gram draft put the lane in cooldown (mode
  ``draft``), with one AIMD budget across all three and per-source
  accounting;
* parity — greedy spec-on streams are byte-identical to spec-off for
  BOTH new sources, including rejected-draft rewinds composing with
  pool publish/reuse, mid-stream park/resume, and poison recovery
  (the warm-start satellite: a resumed stream keeps its drafter);
* concurrency — publish-while-draft replays deterministically under the
  seeded Interleaver and is lockwatch-clean.
"""

import time

import jax.numpy as jnp
import pytest

from dllama_tpu.kv.radix import RadixTree
from dllama_tpu.runtime.api_server import (
    ApiState,
    ChatMessage,
    InferenceParams,
)
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.spec import (
    SOURCE_DRAFT,
    NgramDrafter,
    NgramIndex,
    SharedNgramStore,
    resolve_draft_model,
)
from dllama_tpu.tokenizer import Tokenizer

from helpers import make_tiny_model, make_tiny_tokenizer

CFG = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
           head_dim=16, vocab_size=288, seq_len=384)

# natural-language-ish content: non-repetitive, so the PRIVATE n-gram
# index has little to lock onto and the new sources carry the drafting
NL = "walk through how the scheduler shares computed prefixes, step by step"


@pytest.fixture(scope="module")
def tiny_paths(tmp_path_factory):
    d = tmp_path_factory.mktemp("spec2")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    make_tiny_model(mp, cfg=CFG)
    make_tiny_tokenizer(
        tp_, chat_template="<|start_header_id|>", pad_to=CFG["vocab_size"]
    )
    return mp, tp_


def _mk_state(tiny_paths, *, draft=False, **kw):
    mp, tp_ = tiny_paths
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=3,
    )
    if draft:
        # the tiny target doubles as its own resident draft (same
        # tokenizer by construction) — serve() does this via
        # --draft-model; scheduler-level tests load it directly
        engine.init_draft_model(mp)
    state = ApiState(
        engine, tok, lane_block_size=4, admission_chunk=6, **kw
    )
    assert state.scheduler is not None
    return state


@pytest.fixture(scope="module")
def shared_state(tiny_paths):
    return _mk_state(tiny_paths, speculation="shared", spec_k=4)


@pytest.fixture(scope="module")
def draft_state(tiny_paths):
    return _mk_state(tiny_paths, draft=True, speculation="draft", spec_k=4)


@pytest.fixture(scope="module")
def off_state(tiny_paths):
    return _mk_state(tiny_paths)


def _drain(job, timeout=300):
    deltas = []
    deadline = time.time() + timeout
    while True:
        kind, payload = job.events.get(timeout=max(0.1, deadline - time.time()))
        if kind == "delta":
            deltas.append(payload)
        elif kind == "done":
            return "".join(deltas), payload
        else:
            raise AssertionError(f"job errored: {payload}")


def _greedy(content, max_tokens=48):
    return InferenceParams(
        messages=[ChatMessage(role="user", content=content)],
        temperature=0.0, max_tokens=max_tokens, stream=True,
    )


def _source_count(state, source):
    if state.m_spec_source is None:
        return 0.0
    return state.m_spec_source.labels(source=source).value


# -- radix node identity ------------------------------------------------------


@pytest.mark.fast
def test_radix_anchor_reported_and_absent():
    t = RadixTree(4)
    assert t.match([1, 2, 3]).anchor is None  # empty tree: no anchor
    t.insert([1, 2, 3, 4, 5, 6, 7, 8], [10, 11], 0)
    mr = t.match([1, 2, 3, 4, 5, 6, 7, 8])
    assert mr.n_tokens == 8 and mr.anchor is not None
    # a PARTIAL edge match still anchors on that edge
    assert t.match([1, 2, 9]).anchor == mr.anchor
    assert t.match([9, 9, 9]).anchor is None


@pytest.mark.fast
def test_radix_anchor_survives_edge_split():
    """The id streams anchored on must follow the shared prefix through
    a split: the head node inherits it, the tail gets a fresh one."""
    t = RadixTree(4)
    t.insert([1, 2, 3, 4, 5, 6, 7, 8], [10, 11], 0)
    before = t.match([1, 2, 3, 4]).anchor
    # diverge after 4 tokens: splits the single 8-token edge
    t.insert([1, 2, 3, 4, 9, 9, 9, 9], [12, 13], 0)
    assert t.match([1, 2, 3, 4]).anchor == before
    # the two continuations hang off distinct (fresh) identities
    old_tail = t.match([1, 2, 3, 4, 5, 6, 7, 8]).anchor
    new_tail = t.match([1, 2, 3, 4, 9, 9, 9, 9]).anchor
    assert before not in (old_tail, new_tail)
    assert old_tail != new_tail


@pytest.mark.fast
def test_radix_node_ids_unique():
    t = RadixTree(2)
    t.insert([1, 2, 3, 4], [10, 11], 0)
    t.insert([1, 2, 5, 6], [12], 1)
    t.insert([7, 8], [13], 0)
    seen, stack = [], [t.root]
    while stack:
        n = stack.pop()
        seen.append(n.node_id)
        stack.extend(n.children.values())
    assert len(seen) == len(set(seen))


# -- shared store -------------------------------------------------------------


@pytest.mark.fast
def test_shared_store_sibling_lookup_and_self_exclusion():
    st = SharedNgramStore(max_n=3)
    st.publish(7, "a", [1, 2, 3, 4, 5, 6])
    # a sibling with the same anchor drafts a's continuation of (2,3)
    assert st.lookup(7, [2, 3], 3, exclude_stream="b") == [4, 5, 6]
    # ... but a stream never drafts from its own publishes
    assert st.lookup(7, [2, 3], 3, exclude_stream="a") == []
    # unknown anchor: miss
    assert st.lookup(99, [2, 3], 3) == []
    s = st.stats()
    assert s["groups"] == 1 and s["streams"] == 1 and s["tokens"] == 6
    assert s["hits"] == 1 and s["misses"] == 2


@pytest.mark.fast
def test_shared_store_incremental_publish_and_lru():
    st = SharedNgramStore(max_n=2, max_groups=2, max_streams_per_group=2)
    st.publish(1, "a", [1, 2, 3])
    st.publish(1, "a", [4, 5])          # same stream: extends the index
    assert st.lookup(1, [3], 2, exclude_stream="b") == [4, 5]
    st.publish(1, "b", [9, 9])
    st.publish(1, "c", [8, 8])          # 3rd stream: LRU-evicts "a"
    assert st.lookup(1, [3], 2, exclude_stream="z") == []
    st.publish(2, "x", [1])
    st.publish(3, "y", [1])             # 3rd group: LRU-evicts group 1
    assert st.stats()["groups"] == 2
    assert st.lookup(1, [9], 1) == []


@pytest.mark.fast
def test_ngram_index_suffix_lookup():
    ix = NgramIndex(max_n=3)
    ix.extend([5, 6, 7, 8, 5, 6])
    # an EXTERNAL suffix (another lane's context) drives the lookup
    assert ix.lookup_suffix([0, 5, 6], 2) == [7, 8]
    # continuation only at the index's own end: fall back to the
    # previous occurrence rather than running off the edge
    assert ix.lookup_suffix([9, 9], 2) == []


@pytest.mark.fast
def test_drafter_shared_source_ladder():
    store = SharedNgramStore(max_n=3)
    store.publish(5, "other", [1, 2, 3, 4, 5, 6])
    dr = NgramDrafter(
        k_max=3, shared_store=store, stream_id="me", anchor=5,
        anchor_offset=2,
    )
    # private index has no repeat -> the shared sibling supplies a draft
    dr.update([7, 1, 2, 3])
    assert dr.draft() == [4, 5, 6]
    assert dr.last_source == "shared"
    # private hit wins the ladder
    dr2 = NgramDrafter(
        k_max=2, shared_store=store, stream_id="me", anchor=5,
        anchor_offset=0,
    )
    dr2.update([1, 2, 1, 2, 1])
    assert dr2.draft() == [2, 1]
    assert dr2.last_source == "ngram"


@pytest.mark.fast
def test_drafter_publishes_from_anchor_offset_and_rebinds():
    store = SharedNgramStore(max_n=3)
    dr = NgramDrafter(
        k_max=4, shared_store=store, stream_id="s1", anchor=9,
        anchor_offset=3,
    )
    # the first publish seeds the JUNCTION — the last max_n-1 tokens of
    # the shared anchor prefix ride along so a sibling whose suffix
    # still ends in prefix tokens can match the run's opening tokens
    dr.update([1, 2, 3, 4, 5])
    assert store.stats()["tokens"] == 4  # [2, 3] junction + [4, 5] run
    assert store.lookup(9, [4], 1, exclude_stream="zz") == [5]
    # the bridge: a prefix-tail suffix finds the first run token
    assert store.lookup(9, [2, 3], 1, exclude_stream="zz") == [4]
    # rebinding to a new anchor resets the publish cursor
    dr.rebind(12, 1)
    dr.update([1, 2, 3, 4, 5, 6])
    assert store.lookup(12, [5], 1, exclude_stream="zz") == [6]
    # same-anchor rebind is a no-op (no double publish)
    before = store.stats()["tokens"]
    dr.rebind(12, 0)
    dr.update([1, 2, 3, 4, 5, 6])
    assert store.stats()["tokens"] == before


@pytest.mark.fast
def test_drafter_model_budget_gating():
    dr = NgramDrafter(k_max=4, cooldown=2, use_draft_model=True)
    dr.update([1, 2, 3, 4])
    assert dr.draft() == []            # nothing from the n-gram sources
    assert dr.model_budget() == 4      # -> the model gets the full budget
    assert dr.model_budget(budget=2) == 2
    dr.feedback(4, 0)                  # zero acceptance: halve + cooldown
    assert dr.draft() == []
    # the n-gram cooldown re-routes the budget to the model (the model
    # carries none of the just-discredited n-gram evidence)
    assert dr.model_budget() == 2
    dr.last_source = SOURCE_DRAFT      # as the scheduler records it
    dr.feedback(2, 0)                  # a failed MODEL draft must NOT
    assert dr._cooldown == 1           # re-arm the cooldown (no
    dr.draft()                         # model->cooldown->model pin)
    assert dr.model_budget() == 1      # k halved again, cooldown tick
    dr.draft()
    assert dr.model_budget() == 1      # cooldown over: dry-sources path
    dr2 = NgramDrafter(k_max=4, use_draft_model=False)
    dr2.update([1, 2, 3, 4])
    dr2.draft()
    assert dr2.model_budget() == 0     # mode shared: no model drafting
    dr3 = NgramDrafter(k_max=3, use_draft_model=True)
    dr3.update([1, 2, 1, 2])
    assert dr3.draft() == [1, 2, 1]    # n-gram hit: model not consulted
    assert dr3.model_budget() == 0


@pytest.mark.fast
def test_resolve_draft_model(monkeypatch):
    monkeypatch.delenv("DLLAMA_DRAFT_MODEL", raising=False)
    assert resolve_draft_model() is None
    monkeypatch.setenv("DLLAMA_DRAFT_MODEL", "/env/d.m")
    assert resolve_draft_model() == "/env/d.m"
    assert resolve_draft_model("/cli/d.m") == "/cli/d.m"  # explicit wins


@pytest.mark.fast
def test_draft_cli_flags():
    import argparse

    from dllama_tpu.cli import add_engine_args

    parser = argparse.ArgumentParser()
    add_engine_args(parser)
    args = parser.parse_args(
        ["--model", "m", "--speculation", "draft", "--draft-model", "d.m"]
    )
    assert args.speculation == "draft" and args.draft_model == "d.m"
    args = parser.parse_args(["--model", "m", "--speculation", "shared"])
    assert args.speculation == "shared" and args.draft_model is None


# -- publish-while-draft race (seeded replay, lockwatch-clean) ----------------


@pytest.mark.fast
@pytest.mark.parametrize("seed", [0, 3, 11])
def test_shared_store_publish_while_draft_race(seed):
    """A publisher extending an anchor group while a sibling drafts from
    it, replayed under a seeded schedule: every interleaving yields
    either a miss or a prefix of the final continuation — never garbage
    — and identical seeds replay identical schedules."""
    from dllama_tpu.analysis.lockwatch import Interleaver

    def round_():
        store = SharedNgramStore(max_n=3)
        itl = Interleaver(seed=seed)
        results = []

        def publisher():
            store.publish(4, "w", [1, 2, 3, 4])
            itl.step("published-head")
            store.publish(4, "w", [5, 6])
            itl.step("published-tail")
            store.publish(4, "w", [7, 8])

        def drafter():
            for label in ("d1", "d2", "d3"):
                results.append(store.lookup(
                    4, [3, 4], 4, exclude_stream="me"
                ))
                itl.step(label)

        itl.spawn("pub", publisher)
        itl.spawn("draft", drafter)
        trace = itl.run()
        return trace, results

    trace1, res1 = round_()
    trace2, res2 = round_()
    assert trace1 == trace2 and res1 == res2  # seeded replay
    # every interleaving yields a miss or a draft built purely from the
    # tokens published SO FAR: a prefix of the final continuation, or
    # the cyclic extension of a shorter published prefix (e.g.
    # [5, 6, 5, 6] when the lookup lands between the two publishes).
    # Either is safe — every draft token is verified before emission.
    full = [5, 6, 7, 8]
    for d in res1:
        assert d == [] or (
            d[0] == full[0] and set(d) <= set(full)
        ), (res1, trace1)


# -- resident draft model: engine level ---------------------------------------


def test_engine_draft_model_load_and_greedy_parity(tiny_paths):
    """The draft model loads through the normal reader, keeps its own
    cache, and (being the target's own checkpoint here) proposes exactly
    the target's greedy continuation."""
    mp, _ = tiny_paths
    e = InferenceEngine(
        mp, tp=1, dtype=jnp.float32, temperature=0.0, seed=3, batch_size=2
    )
    assert not e.has_draft_model
    e.init_draft_model(mp)
    assert e.has_draft_model and e.draft_seq_len == CFG["seq_len"]

    prompt = [2 + (i * 5) % 250 for i in range(13)]
    pos0, pending = len(prompt) - 1, prompt[-1]
    # prefill_lane takes the FULL prompt and drops the pending token
    # itself; draft_prefill is a raw catch-up and writes every token
    # it is given, so it gets the explicit prompt[:-1] fill rows
    e.prefill_lane(0, prompt, 0)
    ref = [r[0] for r in e.decode_lanes(
        [pending, 0], [pos0, 0], 4, [True, False]
    )]
    e.draft_prefill(0, prompt[:-1], 0)
    props = e.draft_propose([pending, 0], [pos0, 0], [True, False], 4)
    assert props[0] == ref
    # draft programs live under their own compile-cache family
    kinds = {k[0] for k in e._compiled if isinstance(k, tuple)}
    assert "draft_prefill" in kinds and "draft_step" in kinds


def test_engine_draft_model_rejects_vocab_mismatch(tiny_paths, tmp_path):
    mp, _ = tiny_paths
    other = str(tmp_path / "othervocab.m")
    make_tiny_model(other, cfg={**CFG, "vocab_size": 128})
    e = InferenceEngine(
        mp, tp=1, dtype=jnp.float32, temperature=0.0, seed=3, batch_size=2
    )
    with pytest.raises(ValueError, match="vocab"):
        e.init_draft_model(other)
    assert not e.has_draft_model


# -- scheduler parity: shared store -------------------------------------------


def test_shared_mode_fanout_parity_and_source(shared_state, off_state):
    """A seeded fanout — identical greedy requests in sequence — stays
    byte-identical to spec-off while later streams draft from earlier
    streams' published continuations through the shared store."""
    want = _drain(off_state.scheduler.submit(_greedy(NL)))
    outs = [
        _drain(shared_state.scheduler.submit(_greedy(NL)))
        for _ in range(4)
    ]
    assert all(o == want for o in outs), (outs, want)
    # sibling continuations actually flowed: the shared source counted
    # drafts, and the store's gauges show live occupancy
    assert _source_count(shared_state, "shared") > 0
    assert shared_state.g_spec_store_tokens.value > 0
    assert shared_state.g_spec_store_hits.value > 0
    # mode shared never touches the draft model
    assert _source_count(shared_state, "draft") == 0
    assert not shared_state.engine.has_draft_model
    kinds = {
        k[0] for k in shared_state.engine._compiled if isinstance(k, tuple)
    }
    assert "draft_step" not in kinds and "draft_prefill" not in kinds


def test_shared_mode_distinct_prompts_stay_private(shared_state, off_state):
    """Streams with unrelated prompts share no anchor: their outputs
    still match spec-off (the store can only ever LOWER acceptance to
    zero, never corrupt output)."""
    for prompt in ("completely unrelated first topic",
                   "another topic with no common prefix at all"):
        want = _drain(off_state.scheduler.submit(_greedy(prompt, 24)))
        got = _drain(shared_state.scheduler.submit(_greedy(prompt, 24)))
        assert got == want


def test_shared_mode_poison_recovery_warm_parity(shared_state, off_state):
    """A mid-stream decode poison forces the lane through recovery
    admission; the resumed stream keeps its drafter (warm-start
    satellite) and the bytes still match spec-off."""
    from dllama_tpu.runtime.faults import set_fault_plane

    prompt = NL + " and repeat the walk again from the top"
    want = _drain(off_state.scheduler.submit(_greedy(prompt, 40)))
    b_recovered = shared_state.m_lanes_recovered.value
    job = shared_state.scheduler.submit(_greedy(prompt, 40))
    deadline = time.time() + 300
    while job.n_completion < 6 and time.time() < deadline:
        time.sleep(0.02)
    assert job.n_completion >= 6
    set_fault_plane("dispatch:nth=1:kind=poison")
    try:
        got = _drain(job)
    finally:
        set_fault_plane("")
    assert got == want, "recovered spec stream diverged from spec-off"
    assert shared_state.m_lanes_recovered.value > b_recovered
    # the recovery path re-anchored the drafter rather than dropping it
    assert shared_state.scheduler.drafters == {} or all(
        isinstance(d, NgramDrafter)
        for d in shared_state.scheduler.drafters.values()
    )


def test_shared_mode_park_resume_parity(tiny_paths):
    """Oversubscription parks/resumes mid-stream; parked streams carry
    their drafter through _LaneState and the fanout still matches the
    off server byte for byte."""
    on = _mk_state(tiny_paths, speculation="shared", spec_k=4, max_streams=5)
    off = _mk_state(tiny_paths, max_streams=5)

    def fanout(state):
        jobs = [
            state.scheduler.submit(_greedy(NL, 32)) for _ in range(5)
        ]
        return [_drain(j) for j in jobs]

    try:
        want = fanout(off)
        got = fanout(on)
        assert got == want
        assert on.recorder.events(kind="stream_park"), (
            "oversubscription round never parked — parity not exercised"
        )
    finally:
        on.scheduler.stop()
        off.scheduler.stop()


# -- scheduler parity: resident draft model -----------------------------------


def test_draft_mode_stream_parity_and_sources(draft_state, off_state):
    """Draft-model speculation is byte-invisible on a non-repetitive
    prompt (where the n-gram sources run dry and the model drafts), and
    the per-source counter + step-time histogram actually moved."""
    want = _drain(off_state.scheduler.submit(_greedy(NL)))
    got = _drain(draft_state.scheduler.submit(_greedy(NL)))
    assert got == want
    assert _source_count(draft_state, "draft") > 0
    h = draft_state.engine._m_spec_draft_ms
    assert h is not None and h.labels(kind="propose").count > 0
    kinds = {
        k[0] for k in draft_state.engine._compiled if isinstance(k, tuple)
    }
    assert "draft_prefill" in kinds and "draft_step" in kinds


def test_draft_mode_rewind_publish_radix_compose(draft_state):
    """Rejected model drafts rewind, the finished stream publishes only
    verified rows, and the identical follow-up adopts the prefix AND
    streams the same bytes — the three subsystems compose."""
    prompt = "compose rewind publish and reuse in one stream"
    text1, reason1 = _drain(draft_state.scheduler.submit(_greedy(prompt)))
    evs = draft_state.recorder.events(kind="spec_verify")
    assert any(e["accepted"] < e["k"] for e in evs), (
        "expected at least one rejected-draft rewind"
    )
    reused0 = draft_state.m_reused_tokens.value
    text2, reason2 = _drain(draft_state.scheduler.submit(_greedy(prompt)))
    assert (text2, reason2) == (text1, reason1)
    assert draft_state.m_reused_tokens.value > reused0


def test_draft_mode_poison_recovery_parity(draft_state, off_state):
    """Recovery with a resident draft model: the target cache rebuild +
    re-prefill resume must not let stale DRAFT-cache rows leak into
    output (cursors reset, catch-up re-feeds verified history)."""
    from dllama_tpu.runtime.faults import set_fault_plane

    prompt = "recover the draft cache cursors after a poisoned dispatch"
    want = _drain(off_state.scheduler.submit(_greedy(prompt, 40)))
    job = draft_state.scheduler.submit(_greedy(prompt, 40))
    deadline = time.time() + 300
    while job.n_completion < 6 and time.time() < deadline:
        time.sleep(0.02)
    assert job.n_completion >= 6
    set_fault_plane("dispatch:nth=1:kind=poison")
    try:
        got = _drain(job)
    finally:
        set_fault_plane("")
    assert got == want


# -- off stays a pure bypass --------------------------------------------------


@pytest.mark.fast
def test_off_mode_has_no_store_no_draft_no_metrics(off_state):
    sched = off_state.scheduler
    assert sched.spec_store is None and not sched.drafters
    assert off_state.m_spec_source is None
    assert off_state.g_spec_tokens_per_pass is None
    assert off_state.g_spec_store_tokens is None
    assert not off_state.engine.has_draft_model
    kinds = {
        k[0] for k in off_state.engine._compiled if isinstance(k, tuple)
    }
    assert not kinds & {"lane_verify", "draft_prefill", "draft_step"}
