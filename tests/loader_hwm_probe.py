"""Subprocess probe for the streamed-loader host-memory bound test.

Loads a Q40 model onto an 8-device mesh and prints one JSON line with the
process VmHWM and the logical device bytes. Run in a FRESH process per
measurement (VmHWM is a process-lifetime high-water mark).

usage: python loader_hwm_probe.py <model.m> <tp> <fuse> <stream 0|1>
"""

import json
import os
import resource
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402

from dllama_tpu.formats.model_file import ModelReader  # noqa: E402
from dllama_tpu.models import load_params  # noqa: E402
from dllama_tpu.parallel import make_mesh, shard_params_put  # noqa: E402


def main() -> None:
    path, tp, fuse, stream = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
    )
    os.environ["DLLAMA_STREAM_LOAD"] = stream
    r = ModelReader(path)
    mesh = make_mesh(tp=tp)
    params = load_params(
        r, weight_format="q40", dtype=jnp.bfloat16,
        put=shard_params_put(mesh, r.header), fuse=fuse,
    )
    jax.block_until_ready(jax.tree.leaves(params))
    device_bytes = sum(
        sh.data.nbytes
        for leaf in jax.tree.leaves(params)
        for sh in leaf.addressable_shards
    )
    print(
        json.dumps(
            {
                "hwm_gb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6,
                "device_gb": device_bytes / 1e9,
            }
        )
    )


if __name__ == "__main__":
    main()
