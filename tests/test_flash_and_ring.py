"""Flash attention + ring attention equivalence tests (kernel vs jnp
reference; sequence-parallel ring vs single-device — SURVEY.md §4's
cross-implementation pattern applied to the new parallelism axis)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.ops.flash_attention import attention_ref, flash_attention
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.parallel.ring_attention import ring_attention


def make_qkv(b, t, h, kh, hd, s, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, t, h, hd)).astype(np.float32))
    # head-major cache layout [B, KH, S, hd] (see ops/flash_attention.py)
    k = jnp.asarray(rng.standard_normal((b, kh, s, hd)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((b, kh, s, hd)).astype(np.float32))
    return q, k, v


@pytest.mark.parametrize("pos", [0, 5, 24])
def test_flash_matches_reference(pos):
    q, k, v = make_qkv(1, 8, 4, 2, 16, 32)
    ref = attention_ref(q, k, v, jnp.int32(pos))
    out = flash_attention(
        q, k, v, jnp.int32(pos), block_t=8, block_s=8, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("pos", [0, 1, 7, 8, 30, 31])
def test_flash_decode_matches_reference(pos):
    """T=1 decode kernel vs the dense reference across positions, incl.
    block boundaries (block_s=8) and the last cache row."""
    from dllama_tpu.ops.flash_attention import flash_decode

    q, k, v = make_qkv(1, 1, 4, 2, 16, 32, seed=11)
    ref = attention_ref(q, k, v, jnp.int32(pos))
    out = flash_decode(q, k, v, jnp.int32(pos), block_s=8, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("h,kh", [(8, 8), (8, 2), (4, 1)])
def test_flash_decode_gqa_groupings(h, kh):
    """MHA (G=1), GQA (G=4), MQA-ish (G=4 single kv head) and batch > 1."""
    from dllama_tpu.ops.flash_attention import flash_decode

    q, k, v = make_qkv(2, 1, h, kh, 16, 64, seed=12)
    for pos in (3, 40, 63):
        ref = attention_ref(q, k, v, jnp.int32(pos))
        out = flash_decode(q, k, v, jnp.int32(pos), block_s=16, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"h={h} kh={kh} pos={pos}",
        )


def test_flash_decode_stats_matches_jnp_stats():
    """The decode-stats variant (sp decode local step) vs the shared jnp
    partial-state math, across shard offsets — including a shard entirely
    in the query's future (fully-masked stats) and per-lane positions."""
    from dllama_tpu.ops.flash_attention import flash_decode_stats
    from dllama_tpu.ops.jnp_ops import attention_stats

    q, k, v = make_qkv(2, 1, 4, 2, 16, 32, seed=14)
    for pos, s0 in [(20, 0), (20, 16), (10, 16), (3, 0), (31, 16)]:
        acc, m, l = flash_decode_stats(
            q, k, v, jnp.int32(pos), jnp.int32(s0), block_s=8, interpret=True
        )
        acc_r, m_r, l_r = attention_stats(q, k, v, jnp.int32(pos), jnp.int32(s0))
        mask = np.asarray(l_r) > 0
        assert (np.asarray(l) > 0).tolist() == mask.tolist(), (pos, s0)
        if mask.any():
            o = np.asarray(acc) / np.maximum(np.asarray(l)[..., None], 1e-30)
            o_r = np.asarray(acc_r) / np.maximum(
                np.asarray(l_r)[..., None], 1e-30
            )
            np.testing.assert_allclose(
                o[mask], o_r[mask], rtol=1e-5, atol=1e-5, err_msg=f"{pos},{s0}"
            )
            lse = np.asarray(m) + np.log(np.maximum(np.asarray(l), 1e-30))
            lse_r = np.asarray(m_r) + np.log(
                np.maximum(np.asarray(l_r), 1e-30)
            )
            np.testing.assert_allclose(
                lse[mask], lse_r[mask], rtol=1e-5, atol=1e-5
            )
    # per-lane positions: lane 0 deep, lane 1 shallow
    posv = jnp.asarray([24, 5], jnp.int32)
    acc, m, l = flash_decode_stats(
        q, k, v, posv, jnp.int32(0), block_s=8, interpret=True
    )
    for lane, p in enumerate([24, 5]):
        acc_r, m_r, l_r = attention_stats(
            q[lane : lane + 1], k[lane : lane + 1], v[lane : lane + 1],
            jnp.int32(p), jnp.int32(0),
        )
        o = np.asarray(acc[lane]) / np.asarray(l[lane])[..., None]
        o_r = np.asarray(acc_r[0]) / np.asarray(l_r[0])[..., None]
        np.testing.assert_allclose(o, o_r, rtol=1e-5, atol=1e-5)


def test_flash_decode_bf16():
    from dllama_tpu.ops.flash_attention import flash_decode

    q, k, v = make_qkv(1, 1, 4, 2, 32, 64, seed=13)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    ref = attention_ref(q, k, v, jnp.int32(50))
    out = flash_decode(q, k, v, jnp.int32(50), block_s=16, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_flash_multi_batch_gqa():
    q, k, v = make_qkv(2, 16, 8, 2, 16, 64, seed=3)
    ref = attention_ref(q, k, v, jnp.int32(48))
    out = flash_attention(
        q, k, v, jnp.int32(48), block_t=8, block_s=16, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_attention_matches_single_device(sp):
    """Causal self-attention with the sequence ring-sharded over sp chips
    must equal the single-device result exactly."""
    b, t, h, kh, hd = 1, 32, 4, 2, 16
    q, k, v = make_qkv(b, t, h, kh, hd, t, seed=7)
    mesh = make_mesh(sp=sp)
    expected = attention_ref(q, k, v, jnp.int32(t - 1) * 0 + jnp.int32(0))
    # attention_ref treats pos as the position of q[:, 0]; for full
    # self-attention q covers positions 0..t-1 over keys 0..t-1
    out = ring_attention(q, k, v, mesh, q_pos0=0)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-4
    )


def test_ring_attention_gqa_batch():
    b, t, h, kh, hd = 2, 64, 8, 4, 16
    q, k, v = make_qkv(b, t, h, kh, hd, t, seed=11)
    mesh = make_mesh(sp=4)
    expected = attention_ref(q, k, v, jnp.int32(0))
    out = ring_attention(q, k, v, mesh, q_pos0=0)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-4
    )


def test_ring_with_tp_mesh_axes():
    """sp combined with a tp axis in the same mesh (heads whole on the sp
    ring, tp present for the rest of the model)."""
    b, t, h, kh, hd = 1, 32, 4, 2, 16
    q, k, v = make_qkv(b, t, h, kh, hd, t, seed=13)
    mesh = make_mesh(tp=2, sp=4)
    expected = attention_ref(q, k, v, jnp.int32(0))
    out = ring_attention(q, k, v, mesh, q_pos0=0)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-4
    )


def test_moe_pallas_tp_branch_matches_dense():
    """The shard_map TP branch of the ragged MoE path (psum over F-sliced
    experts) vs the dense MoE, on a tp=2 CPU mesh in interpret mode."""
    from dllama_tpu.models.transformer import _moe_ffn, _moe_ffn_pallas
    from dllama_tpu.ops.jnp_ops import silu

    rng = np.random.default_rng(21)
    E, D, F, K = 8, 64, 128, 3
    w1 = jnp.asarray(rng.standard_normal((E, D, F)).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.standard_normal((E, F, D)).astype(np.float32) * 0.1)
    w3 = jnp.asarray(rng.standard_normal((E, D, F)).astype(np.float32) * 0.1)
    gate = jnp.asarray(rng.standard_normal((D, E)).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((1, 1, D)).astype(np.float32))

    mesh = make_mesh(tp=2)
    out = _moe_ffn_pallas(x, gate, w1, w2, w3, K, mesh, interpret=True)
    dense = _moe_ffn(x, gate, w1, w2, w3, K, silu)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense), rtol=1e-4, atol=1e-4
    )


def test_moe_pallas_tp_quantized_and_multitoken():
    """The quantized 9-operand shard_map branch and the dp-sharded
    multi-token branch of _moe_ffn_pallas: tp=2 x dp=2 CPU mesh, interpret
    mode, 4 tokens with per-token routing, Q40 expert weights — vs the
    dense MoE over dequantized experts."""
    from dllama_tpu.formats.quants import q40_to_planar, quantize_q40
    from dllama_tpu.models.transformer import _moe_ffn, _moe_ffn_pallas
    from dllama_tpu.ops.jnp_ops import silu
    from dllama_tpu.ops.quant_matmul import QuantWeight, dequant, from_planar

    rng = np.random.default_rng(22)
    E, D, F, K = 8, 64, 128, 3

    def make_experts(out_dim, in_dim, seed):
        qs, ds = [], []
        for e in range(E):
            w = rng.standard_normal((out_dim, in_dim)).astype(np.float32) * 0.1
            qv, dv = q40_to_planar(quantize_q40(w), out_dim * in_dim)
            qw = from_planar(qv.reshape(out_dim, in_dim),
                             dv.reshape(out_dim, in_dim // 32))
            qs.append(np.asarray(qw.q))
            ds.append(np.asarray(qw.d))
        return QuantWeight(jnp.asarray(np.stack(qs)), jnp.asarray(np.stack(ds)))

    w1, w3 = make_experts(F, D, 1), make_experts(F, D, 2)
    w2 = make_experts(D, F, 3)
    gate = jnp.asarray(rng.standard_normal((D, E)).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((4, 1, D)).astype(np.float32))  # 4 dp lanes

    mesh = make_mesh(tp=2, dp=2)
    out = _moe_ffn_pallas(x, gate, w1, w2, w3, K, mesh, interpret=True)
    dense = _moe_ffn(
        x, gate, dequant(w1, jnp.float32), dequant(w2, jnp.float32),
        dequant(w3, jnp.float32), K, silu,
    )
    # bf16 tolerance: the kernel computes in bf16, the reference in f32
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense), rtol=2e-2, atol=2e-2
    )


def test_flash_stats_matches_jnp_stats():
    """Pallas flash-stats kernel vs the shared jnp partial-state math,
    across query/key offsets (normalized output + log-sum-exp invariants)."""
    from dllama_tpu.ops.flash_attention import flash_attention_stats
    from dllama_tpu.ops.jnp_ops import attention_stats

    q, k, v = make_qkv(1, 16, 4, 2, 16, 32, seed=5)
    for qp, sp in [(0, 0), (16, 0), (0, 16), (40, 16)]:
        acc, m, l = flash_attention_stats(
            q, k, v, jnp.int32(qp), jnp.int32(sp),
            block_t=8, block_s=8, interpret=True,
        )
        acc_r, m_r, l_r = attention_stats(q, k, v, jnp.int32(qp), jnp.int32(sp))
        mask = np.asarray(l_r) > 0
        o = np.asarray(acc) / np.maximum(np.asarray(l)[..., None], 1e-30)
        o_r = np.asarray(acc_r) / np.maximum(np.asarray(l_r)[..., None], 1e-30)
        np.testing.assert_allclose(o[mask], o_r[mask], rtol=1e-5, atol=1e-5)
        lse = np.asarray(m) + np.log(np.maximum(np.asarray(l), 1e-30))
        lse_r = np.asarray(m_r) + np.log(np.maximum(np.asarray(l_r), 1e-30))
        np.testing.assert_allclose(lse[mask], lse_r[mask], rtol=1e-5, atol=1e-5)


def test_flash_stats_per_lane_positions():
    """Vector q_pos0 in the prefill stats kernel: each lane's chunk starts
    at its own position; a strongly negative lane (the engine's parked
    sentinel) yields fully-masked stats."""
    from dllama_tpu.ops.flash_attention import flash_attention_stats
    from dllama_tpu.ops.jnp_ops import attention_stats

    q, k, v = make_qkv(3, 8, 4, 2, 16, 32, seed=15)
    posv = jnp.asarray([0, 16, -64], jnp.int32)  # lane 2 parked
    acc, m, l = flash_attention_stats(
        q, k, v, posv, jnp.int32(0), block_t=8, block_s=8, interpret=True
    )
    for lane, p in enumerate([0, 16]):
        acc_r, m_r, l_r = attention_stats(
            q[lane : lane + 1], k[lane : lane + 1], v[lane : lane + 1],
            jnp.int32(p), jnp.int32(0),
        )
        mask = np.asarray(l_r[0]) > 0
        o = np.asarray(acc[lane]) / np.maximum(
            np.asarray(l[lane])[..., None], 1e-30
        )
        o_r = np.asarray(acc_r[0]) / np.maximum(
            np.asarray(l_r[0])[..., None], 1e-30
        )
        np.testing.assert_allclose(
            o[mask], o_r[mask], rtol=1e-5, atol=1e-5, err_msg=f"lane {lane}"
        )
    # parked lane: zero weight everywhere
    assert float(np.abs(np.asarray(l[2])).max()) == 0.0


def test_ring_with_flash_local_step():
    """Ring attention using the Pallas flash-stats local step (interpret)
    must equal the single-device reference."""
    b, t, h, kh, hd = 1, 32, 4, 2, 16
    q, k, v = make_qkv(b, t, h, kh, hd, t, seed=19)
    mesh = make_mesh(sp=4)
    expected = attention_ref(q, k, v, jnp.int32(0))
    out = ring_attention(q, k, v, mesh, q_pos0=0, use_flash=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-4
    )


def test_moe_pallas_tp_q80_sync_close():
    """The MoE TP branch with Q80-compressed partial-sum psum
    (sync_quant=True; parallel/collectives.psum_q80) must stay within
    quantization tolerance of the exact-psum result on a tp=2 mesh."""
    from dllama_tpu.models.transformer import _moe_ffn_pallas

    rng = np.random.default_rng(23)
    E, D, F, K = 8, 64, 128, 3
    w1 = jnp.asarray(rng.standard_normal((E, D, F)).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.standard_normal((E, F, D)).astype(np.float32) * 0.1)
    w3 = jnp.asarray(rng.standard_normal((E, D, F)).astype(np.float32) * 0.1)
    gate = jnp.asarray(rng.standard_normal((D, E)).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((1, 1, D)).astype(np.float32))

    mesh = make_mesh(tp=2)
    exact = _moe_ffn_pallas(x, gate, w1, w2, w3, K, mesh, interpret=True)
    q80 = _moe_ffn_pallas(
        x, gate, w1, w2, w3, K, mesh, interpret=True, sync_quant=True
    )
    scale = float(np.abs(np.asarray(exact)).max())
    err = float(np.abs(np.asarray(q80) - np.asarray(exact)).max())
    assert err / scale < 2e-2, (err, scale)
    assert err > 0.0  # the compressed path actually took effect


def _rand_moe(rng, E, D, F):
    w1 = jnp.asarray(rng.standard_normal((E, D, F)).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.standard_normal((E, F, D)).astype(np.float32) * 0.1)
    w3 = jnp.asarray(rng.standard_normal((E, D, F)).astype(np.float32) * 0.1)
    gate = jnp.asarray(rng.standard_normal((D, E)).astype(np.float32))
    return w1, w2, w3, gate


def test_moe_grouped_matches_dense_routing():
    """Prefill-scale grouped active-expert MoE (assignments sorted by
    expert, static (tile, segment) schedule) vs the dense-over-all-experts
    path — same routing, bf16 kernel tolerance. Covers partial tiles and
    tiles spanning several expert segments (VERDICT r2 missing #3)."""
    from dllama_tpu.models.transformer import _moe_ffn, _moe_ffn_grouped
    from dllama_tpu.ops.jnp_ops import silu

    rng = np.random.default_rng(41)
    E, D, F = 8, 64, 128
    w1, w2, w3, gate = _rand_moe(rng, E, D, F)
    x = jnp.asarray(rng.standard_normal((2, 20, D)).astype(np.float32))

    out = _moe_ffn_grouped(x, gate, w1, w2, w3, 3, mesh=None, interpret=True)
    dense = _moe_ffn(x, gate, w1, w2, w3, 3, silu)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense), rtol=2e-2, atol=2e-2
    )


def test_moe_grouped_tp_and_q40():
    """Grouped MoE through the tp=2 shard_map branch with Q40 experts vs
    dense routing over dequantized experts."""
    from dllama_tpu.formats.quants import q40_to_planar, quantize_q40
    from dllama_tpu.models.transformer import _moe_ffn, _moe_ffn_grouped
    from dllama_tpu.ops.jnp_ops import silu
    from dllama_tpu.ops.quant_matmul import QuantWeight, dequant, from_planar

    rng = np.random.default_rng(42)
    E, D, F, K = 8, 64, 128, 3

    def make_experts(out_dim, in_dim):
        qs, ds = [], []
        for _ in range(E):
            w = rng.standard_normal((out_dim, in_dim)).astype(np.float32) * 0.1
            qv, dv = q40_to_planar(quantize_q40(w), out_dim * in_dim)
            qw = from_planar(qv.reshape(out_dim, in_dim),
                             dv.reshape(out_dim, in_dim // 32))
            qs.append(np.asarray(qw.q))
            ds.append(np.asarray(qw.d))
        return QuantWeight(jnp.asarray(np.stack(qs)), jnp.asarray(np.stack(ds)))

    w1, w3 = make_experts(F, D), make_experts(F, D)
    w2 = make_experts(D, F)
    gate = jnp.asarray(rng.standard_normal((D, E)).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((2, 24, D)).astype(np.float32))

    mesh = make_mesh(tp=2, dp=2)
    out = _moe_ffn_grouped(x, gate, w1, w2, w3, K, mesh, interpret=True)
    dense = _moe_ffn(
        x, gate, dequant(w1, jnp.float32), dequant(w2, jnp.float32),
        dequant(w3, jnp.float32), K, silu,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense), rtol=3e-2, atol=3e-2
    )


def test_moe_grouped_schedule_dedups_shared_experts():
    """The grouped schedule collapses shared experts to one SEGMENT per
    (tile, unique expert) — the compute-side dedup — and its grid bound
    is tiles + min(E, A) + 1, not tiles + E + 1 (decode-sized batches
    would otherwise pay ~E pure-waste steps). NB the static grid still
    caps the HBM-read saving (empty steps DMA regardless): the full
    analysis and the lax.cond two-tier design that would realize read
    dedup live in docs/moe_decode_dedup.md (VERDICT r3 item 6)."""
    from dllama_tpu.ops.moe_kernel import _GROUP_ROWS, _grouped_schedule

    E, m, k = 128, 8, 4
    # all 8 lanes pick the SAME 4 experts
    top_i = jnp.tile(jnp.asarray([[3, 7, 11, 90]], jnp.int32), (m, 1))
    wts = jnp.full((m, k), 0.25, jnp.float32)
    t_s, w_col, lo, hi, tile, expert = _grouped_schedule(top_i, wts, m, E)
    a = m * k  # 32 assignments -> exactly one 32-row tile
    assert lo.shape[0] == (-(-a // _GROUP_ROWS)) + min(E, a) + 1
    nonempty = np.asarray(hi > lo)
    # one step per unique expert (4), not per assignment (32)
    assert int(nonempty.sum()) == 4, np.asarray(lo)
    loaded = np.asarray(expert)[nonempty]
    assert sorted(set(loaded.tolist())) == [3, 7, 11, 90]


def test_moe_grouped_multilane_decode_parity():
    """The grouped kernel is correct at DECODE shapes (lane-sized m, one
    partial row tile): parity with the ragged per-(token, choice) kernel
    — the correctness harness the two-tier dedup design
    (docs/moe_decode_dedup.md) will reuse."""
    from dllama_tpu.models.transformer import (
        _moe_ffn_grouped,
        _moe_ffn_pallas,
    )

    rng = np.random.default_rng(17)
    E, D, F = 8, 64, 128
    w1, w2, w3, gate = _rand_moe(rng, E, D, F)
    m = 6  # decode-lane scale
    x = jnp.asarray(rng.standard_normal((m, 1, D)).astype(np.float32))

    ragged = _moe_ffn_pallas(x, gate, w1, w2, w3, 3, mesh=None, interpret=True)
    grouped = _moe_ffn_grouped(x, gate, w1, w2, w3, 3, mesh=None, interpret=True)
    np.testing.assert_allclose(
        np.asarray(grouped), np.asarray(ragged), rtol=2e-2, atol=2e-2
    )


def test_flash_stats_strided_matches_jnp():
    """s_stride > 1 (cyclic sp shards: key row j at position
    s_pos0 + j*stride): the flash-stats kernel's strided masks and
    causal-frontier clamp must reproduce the jnp stats math for every
    shard offset, including queries mid-shard and fully-masked shards."""
    from dllama_tpu.ops.flash_attention import flash_attention_stats
    from dllama_tpu.ops.jnp_ops import attention_stats

    q, k, v = make_qkv(1, 16, 4, 2, 16, 32, seed=19)
    for stride, s0, qpos in [(2, 0, 8), (2, 1, 8), (4, 3, 0), (2, 0, 50)]:
        acc, m, l = flash_attention_stats(
            q, k, v, jnp.int32(qpos), jnp.int32(s0),
            block_t=8, block_s=8, interpret=True, s_stride=stride,
        )
        acc_r, m_r, l_r = attention_stats(
            q, k, v, jnp.int32(qpos), jnp.int32(s0), s_stride=stride
        )
        mask = np.asarray(l_r) > 0
        assert (np.asarray(l) > 0).tolist() == mask.tolist(), (stride, s0)
        if mask.any():
            o = np.asarray(acc) / np.maximum(np.asarray(l)[..., None], 1e-30)
            o_r = np.asarray(acc_r) / np.maximum(
                np.asarray(l_r)[..., None], 1e-30
            )
            np.testing.assert_allclose(
                o[mask], o_r[mask], rtol=1e-5, atol=1e-5,
                err_msg=f"stride={stride} s0={s0} qpos={qpos}",
            )


def test_ring_cyclic_flash_local_step():
    """ring_attention_local in cyclic mode with the flash local step ==
    jnp local step (interpret mode, 4 shards)."""
    from dllama_tpu.utils.compat import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P
    from dllama_tpu.parallel.ring_attention import ring_attention_local

    b, t, h, kh, hd, sp = 1, 32, 4, 2, 16, 4
    q, k, v = make_qkv(b, t, h, kh, hd, t, seed=23)
    mesh = make_mesh(sp=sp)
    shard = t // sp

    def run(use_flash):
        def body(qq, kk, vv):
            idx = jax.lax.axis_index("sp")
            return ring_attention_local(
                qq, kk, vv, q_pos0=idx * (t // sp),
                shard_size=jnp.int32(shard), axis_name="sp",
                use_flash=use_flash, interpret=True, cyclic=True,
            )

        return shard_map(
            body, mesh=mesh,
            in_specs=(P(None, "sp", None, None), P(None, None, "sp", None),
                      P(None, None, "sp", None)),
            out_specs=P(None, "sp", None, None),
            check_vma=False,
        )(q, k, v)

    np.testing.assert_allclose(
        np.asarray(run(True)), np.asarray(run(False)),
        rtol=1e-5, atol=1e-5,
    )


def test_moe_two_tier_dedup_matches_ragged():
    """Opt-in two-tier decode dedup: with lanes sharing most experts the
    lax.cond dispatches the small-grid grouped kernel; with distinct
    experts it falls back to the ragged kernel — both must match the
    always-ragged output. The test VERIFIES each regime really lands on
    its branch (u vs the A/2 cap) so a predicate regression cannot pass
    silently."""
    from dllama_tpu.models.transformer import _moe_ffn_pallas, _moe_route

    rng = np.random.default_rng(31)
    E, D, F, K = 64, 64, 128, 3
    w1, w2, w3, gate = _rand_moe(rng, E, D, F)
    m = 8
    cap = (m * K) // 2
    # shared-expert regime: near-identical rows route identically
    x_shared = jnp.asarray(
        np.repeat(rng.standard_normal((1, 1, D)), m, axis=0).astype(
            np.float32
        )
        + rng.standard_normal((m, 1, D)).astype(np.float32) * 1e-3
    )
    # diverse regime: independent rows over E=64 experts spread wide
    x_div = jnp.asarray(rng.standard_normal((m, 1, D)).astype(np.float32))

    def uniques(x):
        ii, _ = _moe_route(x.reshape(m, D), gate, K)
        return len(np.unique(np.asarray(ii)))

    assert uniques(x_shared) <= cap, (uniques(x_shared), cap)
    assert uniques(x_div) > cap, (uniques(x_div), cap)

    for x in (x_shared, x_div):
        base = _moe_ffn_pallas(
            x, gate, w1, w2, w3, K, mesh=None, interpret=True
        )
        two = _moe_ffn_pallas(
            x, gate, w1, w2, w3, K, mesh=None, interpret=True, dedup=True
        )
        np.testing.assert_allclose(
            np.asarray(two), np.asarray(base), rtol=2e-2, atol=2e-2
        )


def _quant_kv_pair(k, v):
    from dllama_tpu.ops.kv_cache import QuantKV, quantize_kv_rows

    kq, ks = quantize_kv_rows(k)
    vq, vs = quantize_kv_rows(v)
    return QuantKV(kq, ks), QuantKV(vq, vs)


def test_flash_stats_quantkv_matches_dequant():
    """QuantKV-native flash stats (int8 planes + [bs, 1] scale refs,
    per-tile dequant in the kernel — VERDICT r4 #3) == jnp stats over the
    dense dequantized view, across offsets, per-lane positions and a
    parked lane."""
    from dllama_tpu.ops.flash_attention import flash_attention_stats
    from dllama_tpu.ops.jnp_ops import attention_stats
    from dllama_tpu.ops.kv_cache import dequant_kv

    q, k, v = make_qkv(1, 16, 4, 2, 16, 32, seed=31)
    qk, qv = _quant_kv_pair(k, v)
    kd, vd = dequant_kv(qk, q.dtype), dequant_kv(qv, q.dtype)
    for qp, sp in [(0, 0), (16, 0), (40, 16)]:
        acc, m, l = flash_attention_stats(
            q, qk, qv, jnp.int32(qp), jnp.int32(sp),
            block_t=8, block_s=8, interpret=True,
        )
        acc_r, m_r, l_r = attention_stats(q, kd, vd, jnp.int32(qp), jnp.int32(sp))
        mask = np.asarray(l_r) > 0
        o = np.asarray(acc) / np.maximum(np.asarray(l)[..., None], 1e-30)
        o_r = np.asarray(acc_r) / np.maximum(np.asarray(l_r)[..., None], 1e-30)
        np.testing.assert_allclose(o[mask], o_r[mask], rtol=1e-5, atol=1e-5)

    # per-lane positions + parked lane over QuantKV
    q3, k3, v3 = make_qkv(3, 8, 4, 2, 16, 32, seed=32)
    qk3, qv3 = _quant_kv_pair(k3, v3)
    posv = jnp.asarray([0, 16, -64], jnp.int32)
    acc, m, l = flash_attention_stats(
        q3, qk3, qv3, posv, jnp.int32(0), block_t=8, block_s=8, interpret=True
    )
    kd3, vd3 = dequant_kv(qk3, q3.dtype), dequant_kv(qv3, q3.dtype)
    for lane, p in enumerate([0, 16]):
        acc_r, m_r, l_r = attention_stats(
            q3[lane : lane + 1], kd3[lane : lane + 1], vd3[lane : lane + 1],
            jnp.int32(p), jnp.int32(0),
        )
        mask = np.asarray(l_r[0]) > 0
        o = np.asarray(acc[lane]) / np.maximum(np.asarray(l[lane])[..., None], 1e-30)
        o_r = np.asarray(acc_r[0]) / np.maximum(np.asarray(l_r[0])[..., None], 1e-30)
        np.testing.assert_allclose(o[mask], o_r[mask], rtol=1e-5, atol=1e-5)
    assert float(np.abs(np.asarray(l[2])).max()) == 0.0


def test_flash_stats_quantkv_strided():
    """QuantKV + s_stride > 1 (cyclic sp shards): the int8-native kernel
    must keep the strided masks/clamp semantics."""
    from dllama_tpu.ops.flash_attention import flash_attention_stats
    from dllama_tpu.ops.jnp_ops import attention_stats
    from dllama_tpu.ops.kv_cache import dequant_kv

    q, k, v = make_qkv(1, 16, 4, 2, 16, 32, seed=33)
    qk, qv = _quant_kv_pair(k, v)
    kd, vd = dequant_kv(qk, q.dtype), dequant_kv(qv, q.dtype)
    for stride, s0, qpos in [(2, 0, 8), (2, 1, 8), (4, 3, 0), (2, 0, 50)]:
        acc, m, l = flash_attention_stats(
            q, qk, qv, jnp.int32(qpos), jnp.int32(s0),
            block_t=8, block_s=8, interpret=True, s_stride=stride,
        )
        acc_r, m_r, l_r = attention_stats(
            q, kd, vd, jnp.int32(qpos), jnp.int32(s0), s_stride=stride
        )
        mask = np.asarray(l_r) > 0
        assert (np.asarray(l) > 0).tolist() == mask.tolist(), (stride, s0)
        if mask.any():
            o = np.asarray(acc) / np.maximum(np.asarray(l)[..., None], 1e-30)
            o_r = np.asarray(acc_r) / np.maximum(np.asarray(l_r)[..., None], 1e-30)
            np.testing.assert_allclose(
                o[mask], o_r[mask], rtol=1e-5, atol=1e-5,
                err_msg=f"stride={stride} s0={s0} qpos={qpos}",
            )


def test_flash_quantkv_no_dense_materialization():
    """The int8 prefill read claim (VERDICT r4 #3 'reads ~half of bf16'):
    (a) the traced program feeds the kernel the int8 planes directly —
    no dense cache-shaped f32/bf16 intermediate exists anywhere in the
    jaxpr; (b) the cache-sized kernel inputs are ~53% the bytes of the
    bf16 dense view (int8 values + f32 per-row scale vs 2B/elem)."""
    from dllama_tpu.ops.flash_attention import flash_attention_stats
    from dllama_tpu.ops.kv_cache import QuantKV

    b, kh, s, hd = 1, 2, 256, 64
    q, k, v = make_qkv(b, 8, 4, kh, hd, s, seed=34)
    qk, qv = _quant_kv_pair(k.astype(jnp.bfloat16), v.astype(jnp.bfloat16))

    def run(qq, kq, ks, vq, vs):
        return flash_attention_stats(
            qq.astype(jnp.bfloat16), QuantKV(kq, ks), QuantKV(vq, vs),
            jnp.int32(0), jnp.int32(0), block_t=8, block_s=128,
        )

    txt = str(jax.make_jaxpr(run)(q, qk.q, qk.s, qv.q, qv.s))
    dense_shape = f"[{b},{kh},{s},{hd}]"
    assert f"i8{dense_shape}" in txt  # int8 planes reach the kernel
    for dt in ("f32", "bf16"):
        assert dt + dense_shape not in txt, (
            f"dense {dt} cache materialized:\n"
            + "\n".join(ln for ln in txt.splitlines() if dense_shape in ln)
        )
    int8_bytes = qk.q.nbytes + qk.s.nbytes
    bf16_bytes = 2 * b * kh * s * hd
    assert int8_bytes / bf16_bytes < 0.55, int8_bytes / bf16_bytes


def test_ring_cyclic_flash_quantkv():
    """ring_attention_local in cyclic mode over a QuantKV shard: flash
    local step (int8-native) == jnp local step (local dequant); the ring
    rotates int8 payloads either way."""
    from dllama_tpu.utils.compat import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P
    from dllama_tpu.ops.kv_cache import QuantKV
    from dllama_tpu.parallel.ring_attention import ring_attention_local

    b, t, h, kh, hd, sp = 1, 32, 4, 2, 16, 4
    q, k, v = make_qkv(b, t, h, kh, hd, t, seed=35)
    qk, qv = _quant_kv_pair(k, v)
    mesh = make_mesh(sp=sp)
    shard = t // sp

    def run(use_flash):
        def body(qq, kk, ks, vv, vs):
            idx = jax.lax.axis_index("sp")
            return ring_attention_local(
                qq, QuantKV(kk, ks), QuantKV(vv, vs),
                q_pos0=idx * (t // sp),
                shard_size=jnp.int32(shard), axis_name="sp",
                use_flash=use_flash, interpret=True, cyclic=True,
            )

        kv_spec = P(None, None, "sp", None)
        return shard_map(
            body, mesh=mesh,
            in_specs=(P(None, "sp", None, None), kv_spec, kv_spec,
                      kv_spec, kv_spec),
            out_specs=P(None, "sp", None, None),
            check_vma=False,
        )(q, qk.q, qk.s, qv.q, qv.s)

    np.testing.assert_allclose(
        np.asarray(run(True)), np.asarray(run(False)), rtol=1e-5, atol=1e-5
    )
