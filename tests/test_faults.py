"""Chaos-plane tests (PR 12): fault-spec parsing, deterministic draws,
and the seeded multi-lane soak.

The soak is the acceptance bar for the self-healing serving path: under
every armed schedule, each request either completes byte-identical to
the fault-free run or fails with a structured retryable error; the
scheduler thread never dies; and the PagePool invariant check passes
after every recovery. Load shedding and graceful drain ride the same
fixtures.
"""

import http.client
import json
import re
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import pytest

from dllama_tpu.formats import FloatType
from dllama_tpu.runtime.api_server import serve
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.faults import (
    FaultPlane,
    FaultSpecError,
    parse_fault_spec,
    set_fault_plane,
)
from dllama_tpu.tokenizer import Tokenizer

from helpers import make_tiny_model, make_tiny_tokenizer


@pytest.fixture(autouse=True)
def _disarm_fault_plane():
    """Every test leaves the process-wide plane unarmed, pass or fail."""
    yield
    set_fault_plane("")


# -- spec parsing -------------------------------------------------------------


def test_parse_valid_specs():
    scheds = parse_fault_spec(
        "dispatch:p=0.05:seed=7,kv_alloc:nth=12,"
        "dispatch:every=40:kind=poison:n=2:op=decode_lanes"
    )
    assert [s.site for s in scheds] == ["dispatch", "kv_alloc", "dispatch"]
    a, b, c = scheds
    assert a.p == 0.05 and a.seed == 7 and a.kind == "transient"
    assert b.nth == 12
    assert c.every == 40 and c.kind == "poison" and c.n == 2
    assert c.op == "decode_lanes"


def test_parse_empty_and_blank_segments():
    assert parse_fault_spec("") == []
    assert [s.site for s in parse_fault_spec("dispatch:nth=1, ,")] == [
        "dispatch"
    ]


@pytest.mark.parametrize(
    "spec",
    [
        "warp_core:p=0.5",            # unknown site
        "dispatch:p=0.5:mean=3",      # unknown key
        "dispatch:p=0.5:kind=flaky",  # unknown kind
        "dispatch",                   # no trigger
        "dispatch:kind=poison",       # no trigger either
        "dispatch:p=0.5:nth=3",       # two triggers
        "dispatch:p=abc",             # bad value
        "dispatch:p=1.5",             # p outside [0, 1]
        "dispatch:nth=0",             # nth must be >= 1
        "dispatch:every=0",           # every must be >= 1
        "dispatch:p",                 # not key=value
    ],
)
def test_parse_rejects(spec):
    with pytest.raises(FaultSpecError):
        parse_fault_spec(spec)


# -- deterministic draws ------------------------------------------------------


def test_unarmed_plane_is_free():
    plane = FaultPlane("")
    assert not plane.armed
    assert plane.draw("dispatch", op="decode_lanes") is None
    assert plane.counts() == {}


def test_nth_fires_exactly_once():
    plane = FaultPlane("dispatch:nth=3")
    fired = [plane.draw("dispatch") is not None for _ in range(10)]
    assert fired == [False, False, True] + [False] * 7
    assert plane.counts() == {"dispatch": 1}


def test_every_is_periodic_and_n_caps():
    plane = FaultPlane("dispatch:every=3:n=2")
    fired = [plane.draw("dispatch") is not None for _ in range(12)]
    # draws 3 and 6 fire; the n=2 cap silences draws 9 and 12
    assert fired == [
        False, False, True, False, False, True,
        False, False, False, False, False, False,
    ]
    assert plane.counts() == {"dispatch": 2}


def test_p_schedule_is_seed_reproducible():
    a = FaultPlane("dispatch:p=0.3:seed=11")
    b = FaultPlane("dispatch:p=0.3:seed=11")
    pa = [a.draw("dispatch") is not None for _ in range(200)]
    pb = [b.draw("dispatch") is not None for _ in range(200)]
    assert pa == pb
    assert any(pa) and not all(pa)


def test_op_filter_restricts_dispatch_schedule():
    plane = FaultPlane("dispatch:op=decode_lanes:nth=1")
    # non-matching ops do not even advance the draw counter
    assert plane.draw("dispatch", op="prefill_lane_chunk") is None
    assert plane.draw("kv_alloc") is None
    fault = plane.draw("dispatch", op="decode_lanes")
    assert fault is not None
    assert fault.site == "dispatch" and fault.op == "decode_lanes"
    assert fault.kind == "transient" and not fault.poison
    assert fault.seq == 1
    assert "decode_lanes" in str(fault)


def test_poison_fault_attributes():
    plane = FaultPlane("kv_alloc:nth=1:kind=poison")
    fault = plane.draw("kv_alloc", op="publish")
    assert fault is not None and fault.poison
    assert "poison" in str(fault)


# -- the chaos server ---------------------------------------------------------


@pytest.fixture(scope="module")
def chaos_server(tmp_path_factory):
    """4-lane CPU server the soak, shed, and recovery tests share."""
    d = tmp_path_factory.mktemp("api_chaos")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=4,
    )
    srv = serve(engine, tok, host="127.0.0.1", port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv
    srv.shutdown()


def _url(srv):
    return f"http://127.0.0.1:{srv.server_address[1]}"


def _ask(srv, prompt, max_tokens=8, priority=None, timeout=300, extra=None):
    """One non-stream completion. Returns ("ok", content) or
    ("error", status, error_dict, retry_after_header)."""
    payload = {
        "messages": [{"role": "user", "content": prompt}],
        "max_tokens": max_tokens,
        "temperature": 0,
    }
    if priority is not None:
        payload["priority"] = priority
    if extra:
        payload.update(extra)
    req = urllib.request.Request(
        _url(srv) + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            body = json.loads(r.read())
        return ("ok", body["choices"][0]["message"]["content"])
    except urllib.error.HTTPError as e:
        err = json.loads(e.read()).get("error", {})
        return ("error", e.code, err, e.headers.get("Retry-After"))


def _ask_many(srv, prompts, max_tokens=8):
    results = [None] * len(prompts)

    def worker(i):
        results[i] = _ask(srv, prompts[i], max_tokens=max_tokens)

    threads = [
        threading.Thread(target=worker, args=(i,))
        for i in range(len(prompts))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert all(r is not None for r in results), "a soak worker hung"
    return results


def _get_json(srv, path):
    with urllib.request.urlopen(_url(srv) + path, timeout=30) as r:
        return json.loads(r.read())


SOAK_PROMPTS = [
    "alpha", "beta particle", "gamma ray burst",
    "delta wing", "epsilon small", "zeta function",
]

# (spec, exact number of requests allowed to fail, or None = any)
SOAK_SCHEDULES = [
    # transient sprinkles: retry/backoff absorbs every one (ISSUE CI bar:
    # completion rate 1.0 for retryable schedules)
    ("dispatch:p=0.05:seed=7", 0),
    ("dispatch:every=7:seed=1", 0),
    # one decode poison: a batched step has no culprit, every lane
    # recovers and every stream stays byte-identical
    ("dispatch:op=decode_lanes:nth=2:kind=poison", 0),
    # admission poison: exactly the culprit lane fails, survivors resume
    ("dispatch:op=prefill_lane_chunk:nth=2:kind=poison", 1),
    # unfiltered poison sprinkle: outcome depends on which dispatch it
    # lands on — hold only the either-or invariant
    ("dispatch:p=0.08:seed=3:kind=poison:n=2", None),
]


def test_chaos_soak(chaos_server):
    """The seeded soak: >= 5 schedules against the 4-lane server."""
    state = chaos_server.state
    sched = state.scheduler
    baseline = {}
    for status, content in _ask_many(chaos_server, SOAK_PROMPTS):
        assert status == "ok"
    # second fault-free round IS the baseline: by now every prompt's
    # prefix is published, so faulted rounds see the same adopt-vs-
    # prefill split the baseline did
    for prompt, (status, content) in zip(
        SOAK_PROMPTS, _ask_many(chaos_server, SOAK_PROMPTS)
    ):
        assert status == "ok"
        baseline[prompt] = content

    for spec, n_fail_expected in SOAK_SCHEDULES:
        plane = set_fault_plane(spec)
        b_recovered = state.m_lanes_recovered.value
        try:
            results = _ask_many(chaos_server, SOAK_PROMPTS)
        finally:
            counts = plane.counts()
            set_fault_plane("")
        n_failed = 0
        for prompt, res in zip(SOAK_PROMPTS, results):
            if res[0] == "ok":
                assert res[1] == baseline[prompt], (
                    f"{spec}: surviving stream diverged for {prompt!r}"
                )
            else:
                n_failed += 1
                _, code, err, retry_after = res
                assert code == 503, (spec, res)
                assert err.get("retryable") is True, (spec, err)
                assert retry_after is not None, (spec, res)
        if n_fail_expected is not None:
            assert n_failed == n_fail_expected, (spec, results)
        if ":nth=" in spec:  # deterministic schedules must have fired
            assert sum(counts.values()) >= 1, (spec, counts)
        if spec.startswith("dispatch:op=decode_lanes"):
            # the poisoned decode had live lanes: they resumed
            assert state.m_lanes_recovered.value > b_recovered
        # the invariants the whole PR hangs on
        assert sched.thread.is_alive(), f"scheduler died under {spec}"
        sched.kv.check()
        assert not sched.admitting and not sched.pending

    # disarmed follow-up round: the server is fully healthy again
    for prompt, res in zip(
        SOAK_PROMPTS, _ask_many(chaos_server, SOAK_PROMPTS)
    ):
        assert res == ("ok", baseline[prompt])


def test_kv_alloc_fault_is_absorbed(chaos_server):
    """A publish-time pool-allocation failure costs future reuse, never
    the response: the stream already served when publish runs. Needs
    prompts the radix tree has NOT seen — a fully dedup'd publish
    returns before it ever allocates (or draws)."""
    state = chaos_server.state
    sched = state.scheduler
    prompts = [f"unseen kv alloc prompt number {i} " * 4 for i in range(6)]
    plane = set_fault_plane("kv_alloc:nth=1")
    try:
        first = _ask_many(chaos_server, prompts)
    finally:
        counts = plane.counts()
        set_fault_plane("")
    assert all(r[0] == "ok" for r in first), first
    assert counts == {"kv_alloc": 1}
    sched.kv.check()
    assert sched.thread.is_alive()
    # the un-published conversation re-prefills to the same bytes
    for (status, content), res in zip(first, _ask_many(chaos_server, prompts)):
        assert res == ("ok", content)


def test_poison_recovery_resumes_stream_byte_identical(chaos_server):
    """Arm a decode poison MID-STREAM: the lane re-prefills its history
    and the client's stream continues byte-identically — the blast-radius
    acceptance check, without soak timing in the way."""
    state = chaos_server.state
    mt = 2 * state.scheduler.block_size + 4
    status, want = _ask(chaos_server, "resume me byte for byte", max_tokens=mt)
    assert status == "ok"
    b_recovered = state.m_lanes_recovered.value

    req = urllib.request.Request(
        _url(chaos_server) + "/v1/chat/completions",
        data=json.dumps({
            "messages": [
                {"role": "user", "content": "resume me byte for byte"}
            ],
            "max_tokens": mt, "temperature": 0, "stream": True,
        }).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    deltas, armed = [], False
    with urllib.request.urlopen(req, timeout=300) as r:
        for raw in r:
            line = raw.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            ev = json.loads(line[len("data: "):])
            delta = ev["choices"][0].get("delta", {}).get("content")
            if delta:
                deltas.append(delta)
            if deltas and not armed:
                # decode is in flight: poison its next dispatch
                set_fault_plane(
                    "dispatch:op=decode_lanes:nth=1:kind=poison"
                )
                armed = True
    plane = set_fault_plane("")
    assert armed
    assert "".join(deltas) == want, "recovered stream diverged"
    assert state.m_lanes_recovered.value > b_recovered
    kinds = {e["kind"]
             for e in _get_json(chaos_server, "/v1/debug/recorder")["events"]}
    assert {"fault_injected", "lane_recovery", "lane_recovered"} <= kinds
    state.scheduler.kv.check()


def test_sse_flush_fault_cancels_only_that_stream(chaos_server):
    """An injected flush failure looks like the client hanging up: the
    stream dies, the lane is reclaimed, the server keeps serving."""
    state = chaos_server.state
    plane = set_fault_plane("sse_flush:nth=1")
    req = urllib.request.Request(
        _url(chaos_server) + "/v1/chat/completions",
        data=json.dumps({
            "messages": [{"role": "user", "content": "doomed stream"}],
            "max_tokens": 8, "temperature": 0, "stream": True,
        }).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=300) as r:
            raw = r.read().decode()
        assert "data: [DONE]" not in raw
    except (urllib.error.HTTPError, http.client.HTTPException, OSError):
        pass  # a torn/short-read connection is an acceptable client view
    assert plane.counts() == {"sse_flush": 1}
    set_fault_plane("")
    assert state.scheduler.thread.is_alive()
    status, _ = _ask(chaos_server, "after the torn stream")
    assert status == "ok"


def test_failed_admission_releases_pages_and_fails_job(chaos_server):
    """Satellite-1 regression: a job that dies MID-ADMISSION (no active
    stream yet) is failed with a structured retryable error — not leaked
    in self.admitting — and its adopted-page retains are released."""
    state = chaos_server.state
    sched = state.scheduler
    prompt = "leak check conversation " * 8  # long enough to span pages
    status, _ = _ask(chaos_server, prompt)  # publish a reusable prefix
    assert status == "ok"
    # same prefix + a fresh suffix: the admission adopts the published
    # pages (retains them) and must still prefill the unseen tail — a
    # fully-matched prompt would skip prefill and never hit the fault
    prompt2 = prompt + " plus an unpublished suffix to prefill"
    engine = state.engine
    real = engine.prefill_lane_chunk

    def boom(*a, **k):
        raise RuntimeError("injected admission failure")

    engine.prefill_lane_chunk = boom
    try:
        res = _ask(chaos_server, prompt2)
    finally:
        engine.prefill_lane_chunk = real
    assert res[0] == "error"
    _, code, err, retry_after = res
    assert code == 503 and err["retryable"] is True
    assert retry_after is not None
    # nothing leaked: no admitting entry, no lane retains, pool invariant
    assert not sched.admitting
    assert sched.kv.debug()["lanes"] == {}
    sched.kv.check()
    status, _ = _ask(chaos_server, prompt2)
    assert status == "ok"


# -- load shedding ------------------------------------------------------------


def test_queue_full_shed_ladder(chaos_server):
    """Admission refuses by priority class once the queue is at depth:
    low sheds at half the threshold, normal at it, high rides out double.
    Sentinels are parked in the pending queue WITHOUT a cv notify, so the
    idle scheduler never observes them — the gate reads only len()."""
    state = chaos_server.state
    sched = state.scheduler
    b_shed = dict(state.m_shed.child_values())
    state.max_queue_depth = 2
    sentinels = [object(), object()]
    with sched.cv:
        sched.pending.extend(sentinels)
    try:
        for priority in ("normal", "low"):
            res = _ask(chaos_server, "shed me", priority=priority)
            assert res[0] == "error"
            _, code, err, retry_after = res
            assert code == 429
            assert "queue_full" in err["message"]
            assert err["retryable"] is True
            assert retry_after == str(err["retry_after_s"])
        # high priority rides out double the threshold (checked via the
        # gate directly: actually admitting a request would pop the
        # sentinels into the scheduler)
        assert state.admission_decision("high") is None
    finally:
        with sched.cv:
            for s in sentinels:
                sched.pending.remove(s)
        state.max_queue_depth = 0
    shed = state.m_shed.child_values()
    assert shed[("queue_full",)] == b_shed.get(("queue_full",), 0) + 2
    # with the queue drained the same request is admitted again
    assert _ask(chaos_server, "shed me no more")[0] == "ok"


def test_degraded_sheds_low_priority_only(chaos_server):
    """While the engine is degraded (watchdog/anomaly), spare capacity
    heals it: priority=low requests shed, normal traffic still lands."""
    state = chaos_server.state
    state.degraded_reasons = lambda: ["watchdog:test_forced"]
    try:
        res = _ask(chaos_server, "background job", priority="low")
        assert res[0] == "error"
        _, code, err, _ = res
        assert code == 429 and "degraded" in err["message"]
        assert _ask(chaos_server, "interactive user")[0] == "ok"
    finally:
        del state.degraded_reasons


def test_bad_priority_rejected(chaos_server):
    res = _ask(chaos_server, "hi", priority="vip")
    assert res[0] == "error" and res[1] == 400


def test_chaos_overload_predictive_admission(chaos_server):
    """Fault plane + overload + predictive admission (ISSUE 20): 3x the
    lane count of mixed-priority, mixed-deadline requests under a
    transient fault sprinkle. The scheduler never dies, every response
    is either a completed stream or a structured retryable error, and
    hopeless budgets are shed as infeasible up front instead of queuing
    to fail slowly."""
    state = chaos_server.state
    sched = state.scheduler
    prompts = [f"overload wave request {i}" for i in range(12)]
    extras = []
    for i in range(12):
        e = {"priority": ("high", "normal", "low")[i % 3]}
        if i % 4 == 0:
            e["deadline_ms"] = 300_000.0  # generous: feasible
        elif i % 4 == 2:
            e["ttft_budget_ms"] = 0.0001  # hopeless: must shed
        extras.append(e)
    hopeless = [i for i in range(12) if i % 4 == 2]
    b_rejected = dict(state.m_admission_rejected.child_values())

    state.admission_predict = True
    plane = set_fault_plane("dispatch:p=0.05:seed=13")
    results = [None] * 12
    try:

        def worker(i):
            results[i] = _ask(chaos_server, prompts[i], extra=extras[i])

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(12)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
    finally:
        set_fault_plane("")
        state.admission_predict = False
    assert all(r is not None for r in results), "an overload worker hung"

    for i, res in enumerate(results):
        if res[0] == "ok":
            continue
        _, code, err, retry_after = res
        assert code in (429, 503), (i, res)
        assert err.get("retryable") is True, (i, err)
        assert retry_after is not None and int(retry_after) >= 1, (i, res)
    # every hopeless budget was refused (never served); the rest
    # completed — the transient sprinkle is absorbed by retry/backoff
    for i in hopeless:
        assert results[i][0] == "error", (i, results[i])
    for i in range(12):
        if i not in hopeless:
            assert results[i][0] == "ok", (i, results[i])
    rejected = state.m_admission_rejected.child_values()
    assert rejected.get(("infeasible",), 0) >= (
        b_rejected.get(("infeasible",), 0) + 2
    )

    # the invariants the chaos plane holds everywhere
    assert sched.thread.is_alive(), "scheduler died under overload"
    t_end = time.time() + 180
    while time.time() < t_end and (sched.admitting or sched.pending):
        time.sleep(0.02)
    assert not sched.admitting and not sched.pending
    sched.kv.check()
    assert _ask(chaos_server, "after the overload wave")[0] == "ok"


# -- graceful drain -----------------------------------------------------------


@pytest.fixture
def drain_server(tmp_path_factory):
    """Function-scoped: draining is sticky, so the drained server must
    not be shared with other tests."""
    d = tmp_path_factory.mktemp("api_drain")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=2,
    )
    srv = serve(engine, tok, host="127.0.0.1", port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv
    srv.shutdown()


def test_graceful_drain(drain_server):
    """POST /v1/drain: admission stops (503 + Retry-After, reason
    draining), the in-flight stream runs to completion, health flips to
    "draining", the gauge holds 1, and ``drained`` fires once idle."""
    state = drain_server.state
    first_delta = threading.Event()
    stream_result = {}

    def streamer():
        req = urllib.request.Request(
            _url(drain_server) + "/v1/chat/completions",
            data=json.dumps({
                "messages": [{"role": "user", "content": "drain survivor"}],
                "max_tokens": 64, "temperature": 0, "stream": True,
            }).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        chunks = []
        with urllib.request.urlopen(req, timeout=300) as r:
            for raw in r:
                chunks.append(raw.decode())
                if "data: " in chunks[-1]:
                    first_delta.set()
        stream_result["raw"] = "".join(chunks)

    t = threading.Thread(target=streamer)
    t.start()
    assert first_delta.wait(timeout=120), "stream never started"

    req = urllib.request.Request(
        _url(drain_server) + "/v1/drain", data=b"", method="POST"
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        body = json.loads(r.read())
    assert body["status"] == "draining"
    assert body["in_flight"] >= 1

    health = _get_json(drain_server, "/v1/health")
    assert health["status"] == "draining"
    assert health["draining_since_unix"] is not None

    res = _ask(drain_server, "too late")
    assert res[0] == "error"
    _, code, err, retry_after = res
    assert code == 503 and "draining" in err["message"]
    assert err["retryable"] is True and retry_after is not None

    with urllib.request.urlopen(_url(drain_server) + "/metrics",
                                timeout=30) as r:
        text = r.read().decode()
    m = re.search(r"^dllama_draining (\d+)", text, re.M)
    assert m and m.group(1) == "1"

    t.join(timeout=300)
    raw = stream_result.get("raw", "")
    assert raw.rstrip().endswith("data: [DONE]"), "in-flight stream cut off"
    assert '"error"' not in raw

    assert state.drained.wait(timeout=60), "drain never completed"
    kinds = [e["kind"]
             for e in _get_json(drain_server, "/v1/debug/recorder")["events"]]
    assert "drain_begin" in kinds and "drain_complete" in kinds
    # idempotent: a second drain reports, never re-arms
    with urllib.request.urlopen(req, timeout=30) as r:
        assert json.loads(r.read())["status"] == "draining"
    assert kinds.count("drain_begin") == 1
