"""bench_diff: the BENCH_*.json trajectory, driven deterministically.

The library functions take the git SHA and timestamp as arguments (only
``main()`` reads the real clock/repo), so the whole
append → diff → regression-gate path runs under fixed inputs here.
"""

import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))
import bench_diff  # noqa: E402

pytestmark = pytest.mark.fast


def _write_bench(d, step_p50=10.0, ttft_p50=200.0):
    (d / "BENCH_DECODE.json").write_text(json.dumps(
        {"tok_s": 100.0,
         "step_ms": {"p50": step_p50, "p90": step_p50 * 1.2,
                     "max": step_p50 * 1.5}}))
    (d / "BENCH_TTFT.json").write_text(json.dumps(
        {"ttft_ms_p50": ttft_p50, "unit": "ms"}))


def test_flatten_numeric_leaves_only():
    flat = bench_diff.flatten({
        "a": 1, "b": {"c": 2.5, "d": "text", "e": True}, "f": None,
    })
    # strings, bools and nulls are not metrics
    assert flat == {"a": 1.0, "b.c": 2.5}


def test_history_append_and_chronological_order(tmp_path):
    hist = str(tmp_path / "hist")
    for i, sha in enumerate(("aaa", "bbb", "ccc")):
        rec = bench_diff.run_record({"DECODE": {"tok_s": float(i)}},
                                    git_sha=sha, timestamp=1000.0 + i)
        path = bench_diff.append_history(hist, rec)
        assert Path(path).exists()
    prev = bench_diff.previous_record(hist, exclude=path)
    assert prev["git_sha"] == "bbb"  # newest other than the just-written


def test_main_first_run_then_regression_gate(tmp_path, capsys):
    bench = tmp_path / "bench"
    bench.mkdir()
    hist = str(tmp_path / "hist")
    _write_bench(bench, step_p50=10.0, ttft_p50=200.0)
    base = ["--bench-dir", str(bench), "--history-dir", hist,
            "--timestamp", "1000", "--git-sha", "aaa"]
    assert bench_diff.main(base) == 0
    assert "first recorded run" in capsys.readouterr().out

    # +10% decode p50: inside the 15% gate, reported but green
    _write_bench(bench, step_p50=11.0)
    assert bench_diff.main(
        ["--bench-dir", str(bench), "--history-dir", hist,
         "--timestamp", "1100", "--git-sha", "bbb"]) == 0
    out = capsys.readouterr().out
    assert "DECODE.step_ms.p50" in out and "no watched regressions" in out

    # +30% decode p50: past the gate -> exit 1; --warn-only -> exit 0
    _write_bench(bench, step_p50=14.3)
    assert bench_diff.main(
        ["--bench-dir", str(bench), "--history-dir", hist,
         "--timestamp", "1200", "--git-sha", "ccc"]) == 1
    assert "REGRESSION DECODE.step_ms.p50" in capsys.readouterr().out
    _write_bench(bench, step_p50=20.0)
    assert bench_diff.main(
        ["--bench-dir", str(bench), "--history-dir", hist,
         "--timestamp", "1300", "--git-sha", "ddd", "--warn-only"]) == 0
    assert "--warn-only" in capsys.readouterr().out


def test_improvement_and_missing_metrics_never_gate(tmp_path):
    prev = bench_diff.run_record(
        {"DECODE": {"step_ms": {"p50": 10.0}}, "TTFT": {"ttft_ms_p50": 200.0}},
        "aaa", 1000.0)
    # faster decode, TTFT section gone entirely: no regression either way
    cur = bench_diff.run_record(
        {"DECODE": {"step_ms": {"p50": 5.0}}}, "bbb", 1100.0)
    assert bench_diff.regressions(prev, cur) == []
    rows = bench_diff.diff_rows(prev, cur)
    by_key = {k: (p, c, d) for k, p, c, d in rows}
    assert by_key["DECODE.step_ms.p50"][2] == pytest.approx(-50.0)
    assert by_key["TTFT.ttft_ms_p50"] == (200.0, None, None)


def test_no_bench_files_is_a_noop(tmp_path, capsys):
    empty = tmp_path / "empty"
    empty.mkdir()
    assert bench_diff.main(
        ["--bench-dir", str(empty),
         "--history-dir", str(tmp_path / "hist")]) == 0
    assert "nothing to do" in capsys.readouterr().out
    assert not (tmp_path / "hist").exists()
