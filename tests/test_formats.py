"""`.m` / `.t` format round-trip tests (reference pattern: converter golden
tests + loadLlmHeader parse, src/llm.cpp:36-116)."""

import numpy as np
import pytest

from dllama_tpu.formats import FloatType, ModelReader, read_llm_header, read_tokenizer
from dllama_tpu.formats.model_file import LlmArch, RopeType

from helpers import TINY, make_tiny_model, make_tiny_tokenizer

# sub-minute CPU-only surface (codecs, tokenizer, native loader,
# interpret-mode kernel parity): the first CI lane runs `pytest -m fast`
pytestmark = pytest.mark.fast



def test_header_roundtrip(tmp_path):
    path = tmp_path / "tiny.m"
    make_tiny_model(path)
    h = read_llm_header(str(path))
    assert h.arch == LlmArch.LLAMA
    assert h.dim == TINY["dim"]
    assert h.hidden_dim == TINY["hidden_dim"]
    assert h.n_layers == TINY["n_layers"]
    assert h.n_heads == TINY["n_heads"]
    assert h.n_kv_heads == TINY["n_kv_heads"]
    assert h.head_dim == TINY["head_dim"]
    assert h.q_dim == 64
    assert h.kv_dim == 32
    assert h.vocab_size == TINY["vocab_size"]
    assert h.seq_len == TINY["seq_len"]
    assert h.weight_type == FloatType.Q40
    assert h.rope_type == RopeType.LLAMA
    assert h.norm_epsilon == pytest.approx(1e-5)


def test_header_max_seq_len_clamp(tmp_path):
    path = tmp_path / "tiny.m"
    make_tiny_model(path)
    h = read_llm_header(str(path), max_seq_len=16)
    assert h.seq_len == 16
    assert h.orig_seq_len == TINY["seq_len"]


def test_qwen3_forces_falcon_rope(tmp_path):
    path = tmp_path / "tiny.m"
    make_tiny_model(path, arch=LlmArch.QWEN3)
    h = read_llm_header(str(path))
    assert h.rope_type == RopeType.FALCON


def test_tensor_roundtrip_f32(tmp_path):
    path = tmp_path / "tiny.m"
    tensors = make_tiny_model(path, weight_type=FloatType.F32)
    r = ModelReader(str(path))
    for name, expected in tensors.items():
        np.testing.assert_array_equal(r.dense_f32(name), expected)


def test_tensor_roundtrip_q40(tmp_path):
    path = tmp_path / "tiny.m"
    tensors = make_tiny_model(path, weight_type=FloatType.Q40)
    r = ModelReader(str(path))
    # F32 tensors exact; Q40 within block-scale tolerance.
    np.testing.assert_array_equal(r.dense_f32("embed"), tensors["embed"])
    w = r.dense_f32("layers.0.q")
    exact = tensors["layers.0.q"]
    assert w.shape == exact.shape
    assert np.abs(w - exact).max() < np.abs(exact).max() / 4
    # Planar view is consistent with the dense dequant.
    q, d = r.planar_q40("layers.0.q")
    manual = (
        q.reshape(-1, 32).astype(np.float32) * d.reshape(-1).astype(np.float32)[:, None]
    ).reshape(w.shape)
    np.testing.assert_allclose(manual, w, rtol=0, atol=0)


def test_moe_plan(tmp_path):
    path = tmp_path / "tiny_moe.m"
    tensors = make_tiny_model(path, arch=LlmArch.QWEN3_MOE)
    r = ModelReader(str(path))
    assert r.header.is_moe
    assert r.header.ff_dim == r.header.moe_hidden_dim
    assert "layers.0.experts.3.w2" in r.by_name
    assert "layers.0.q_norm" in r.by_name
    np.testing.assert_array_equal(
        r.dense_f32("layers.1.moe_gate"), tensors["layers.1.moe_gate"]
    )


def test_file_size_validation(tmp_path):
    path = tmp_path / "tiny.m"
    make_tiny_model(path)
    with open(path, "ab") as f:
        f.write(b"\x00" * 8)
    with pytest.raises(ValueError, match="size mismatch"):
        ModelReader(str(path))


def test_tokenizer_roundtrip(tmp_path):
    path = tmp_path / "tok.t"
    data = make_tiny_tokenizer(str(path), chat_template="<|im_start|>{{x}}")
    rt = read_tokenizer(str(path))
    assert rt.vocab == data.vocab
    assert rt.scores == pytest.approx(data.scores)
    assert rt.bos_id == data.bos_id
    assert rt.add_bos is True
    assert rt.eos_token_ids == data.eos_token_ids
    assert rt.chat_template == "<|im_start|>{{x}}"


def test_old_tokenizer_format(tmp_path):
    # Legacy magic 0x567123 with the fixed 5-field header
    # (reference: src/tokenizer.cpp:57-64).
    import struct

    path = tmp_path / "old.t"
    vocab = [b"a", b"bc", b"<s>"]
    scores = [0.0, 1.5, 0.0]
    with open(path, "wb") as f:
        f.write(struct.pack("<iIIiii", 0x567123, len(vocab), 2, 2, 1, -1))
        for v, s in zip(vocab, scores):
            f.write(struct.pack("<fi", s, len(v)))
            f.write(v)
    rt = read_tokenizer(str(path))
    assert rt.vocab == vocab
    assert rt.bos_id == 2
    assert rt.eos_token_ids == [1]
    assert rt.chat_template is None


def test_planar_q40_range_matches_full(tmp_path):
    """Ranged planar unpack (the streaming loader's numpy fallback unit)
    == the corresponding slice of the full planar unpack."""
    path = str(tmp_path / "r.m")
    make_tiny_model(path, weight_type=FloatType.Q40)
    r = ModelReader(path)
    name = "layers.0.w2"  # (out=64, in=160): 5 blocks/row
    qf, df = r.planar_q40(name)
    for o0, o1, b0, b1 in [(0, 64, 0, 5), (8, 40, 0, 5), (0, 64, 1, 4),
                           (16, 24, 2, 3)]:
        q, d = r.planar_q40_range(name, o0, o1, b0, b1)
        np.testing.assert_array_equal(q, qf[o0:o1, b0 * 32 : b1 * 32])
        np.testing.assert_array_equal(d, df[o0:o1, b0:b1])
    with pytest.raises(ValueError):
        r.planar_q40_range(name, 0, 65, 0, 5)
