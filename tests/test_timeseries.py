"""Fake-clock units for the in-process time-series store, the sampler,
the registry refresh-hook path, and the anomaly monitor (ISSUE 9).

Everything here runs against PRIVATE MetricsRegistry / FlightRecorder
instances and injected clocks — no real time, no shared global state —
so every ring/downsampling/anomaly assertion is deterministic.
"""

import threading
import time

import pytest

from dllama_tpu.obs.anomaly import (
    AnomalyMonitor,
    AnomalyRule,
    EwmaBaseline,
    _level,
    _per_event_rate,
    _slope,
    build_default_rules,
)
from dllama_tpu.obs.metrics import MetricsRegistry
from dllama_tpu.obs.recorder import FlightRecorder
from dllama_tpu.obs.timeseries import (
    DOWNSAMPLE_EVERY,
    MetricsSampler,
    SeriesStore,
    resolve_series_knobs,
)

pytestmark = pytest.mark.fast


def _store(**kw):
    """SeriesStore bound to private registry+recorder (no global state)."""
    reg = kw.pop("registry", MetricsRegistry())
    rec = kw.pop("recorder", FlightRecorder())
    kw.setdefault("interval_s", 1.0)
    return SeriesStore(registry=reg, recorder=rec, **kw), reg, rec


# -- knob resolution --------------------------------------------------------


def test_series_knob_defaults(monkeypatch):
    monkeypatch.delenv("DLLAMA_SERIES_RETENTION_S", raising=False)
    monkeypatch.delenv("DLLAMA_SERIES_INTERVAL_S", raising=False)
    assert resolve_series_knobs() == (3600.0, 1.0)


def test_series_knob_env_and_explicit(monkeypatch):
    monkeypatch.setenv("DLLAMA_SERIES_RETENTION_S", "120")
    monkeypatch.setenv("DLLAMA_SERIES_INTERVAL_S", "0.5")
    assert resolve_series_knobs() == (120.0, 0.5)
    # explicit (the CLI flag) beats env
    assert resolve_series_knobs(retention_s=60.0) == (60.0, 0.5)


# -- registry: flat_values + refresh hooks ----------------------------------


def test_flat_values_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.counter("dllama_t_total", "c").inc(3)
    reg.gauge("dllama_t_g", "g", labelnames=("k",)).labels(k="a").set(7.0)
    h = reg.histogram("dllama_t_h", "h")
    for v in (0.1, 0.2, 0.3, 0.4):
        h.observe(v)
    flat = reg.flat_values()
    assert flat["dllama_t_total"] == ("counter", 3.0)
    assert flat['dllama_t_g{k="a"}'] == ("gauge", 7.0)
    # histograms flatten to rate-able cumulative sum/count plus quantile
    # estimate gauges
    assert flat["dllama_t_h_count"] == ("counter", 4.0)
    kind, total = flat["dllama_t_h_sum"]
    assert kind == "counter" and total == pytest.approx(1.0)
    kind, p50 = flat["dllama_t_h_p50"]
    assert kind == "gauge" and 0.1 <= p50 <= 0.4
    assert "dllama_t_h_p99" in flat


def test_refresh_hooks_keyed_replacement():
    """Registering under an existing name REPLACES the hook — ApiState
    churn against the process-global registry must not stack dead
    closures (the stale-gauge regression this PR fixes structurally)."""
    reg = MetricsRegistry()
    g = reg.gauge("dllama_t_hook", "g")
    calls = []
    reg.add_refresh_hook("h", lambda: calls.append("old"))
    reg.add_refresh_hook("h", lambda: (calls.append("new"), g.set(42.0)))
    reg.run_refresh_hooks()
    assert calls == ["new"]
    assert reg.flat_values()["dllama_t_hook"] == ("gauge", 42.0)
    reg.remove_refresh_hook("h")
    reg.run_refresh_hooks()
    assert calls == ["new"]


def test_refresh_hook_failure_is_contained():
    """One broken refresher logs and is skipped; later hooks still run."""
    reg = MetricsRegistry()
    ran = []
    reg.add_refresh_hook("bad", lambda: 1 / 0)
    reg.add_refresh_hook("good", lambda: ran.append(True))
    reg.run_refresh_hooks()  # must not raise
    assert ran == [True]


def test_refresh_hooks_disabled_registry():
    reg = MetricsRegistry(enabled=False)
    ran = []
    reg.add_refresh_hook("h", lambda: ran.append(True))
    reg.run_refresh_hooks()
    assert ran == []


# -- SeriesStore ------------------------------------------------------------


def test_two_tier_downsampling():
    """Counter series downsample by LAST value, gauge series by MEAN."""
    store, _, _ = _store(tier1_retention_s=10.0, retention_s=100.0)
    for i in range(DOWNSAMPLE_EVERY):
        store.record(
            float(i),
            {
                "c_total": ("counter", float(i + 1)),
                "g": ("gauge", float(i)),
            },
        )
    with store._lock:
        c, g = store._series["c_total"], store._series["g"]
        assert len(c.tier1) == 10 and len(c.tier2) == 1
        # cumulative counter at the bucket edge: exact last value
        assert c.tier2[0] == (9.0, 10.0)
        # gauge mean over 0..9
        assert g.tier2[0] == (9.0, pytest.approx(4.5))


def test_tier_capacities_bound_memory():
    store, _, _ = _store(tier1_retention_s=5.0, retention_s=100.0)
    for i in range(300):
        store.record(float(i), {"g": ("gauge", float(i))})
    with store._lock:
        s = store._series["g"]
        assert len(s.tier1) == 5  # tier1_retention_s / interval_s
        assert len(s.tier2) == 10  # retention_s / (interval * 10)


def test_query_tier_selection_and_cutoff():
    store, _, _ = _store(tier1_retention_s=10.0, retention_s=200.0)
    for i in range(100):
        store.record(float(i), {"g": ("gauge", float(i))})
    # short window -> full-resolution tier, now defaults to newest sample
    q1 = store.query("g", window_s=5.0)
    assert q1["tier"] == "1s" and q1["interval_s"] == 1.0
    assert q1["now"] == 99.0
    # cutoff is inclusive: window 5 back from t=99 keeps t>=94
    assert [t for t, _ in q1["points"]] == [
        94.0, 95.0, 96.0, 97.0, 98.0, 99.0,
    ]
    # long window -> downsampled tier
    q2 = store.query("g", window_s=100.0)
    assert q2["tier"] == "10s" and q2["interval_s"] == 10.0
    assert len(q2["points"]) >= 9
    assert store.query("missing", window_s=10.0) is None


def test_max_series_cap_drops_new_names_once():
    store, reg, rec = _store(max_series=2)
    store.record(0.0, {"a": ("gauge", 1.0), "b": ("gauge", 2.0)})
    store.record(
        1.0,
        {"a": ("gauge", 1.0), "b": ("gauge", 2.0), "c": ("gauge", 3.0)},
    )
    store.record(2.0, {"c": ("gauge", 3.0), "d": ("gauge", 4.0)})
    assert store.names() == ["a", "b"]
    assert store.m_dropped.value == 3
    assert store.g_tracked.value == 2
    # existing series kept sampling through the overflow
    assert store.latest("a") == 1.0
    # the overflow announced itself exactly once
    assert len(rec.events("obs_overflow")) == 1


def test_latest():
    store, _, _ = _store()
    assert store.latest("g") is None
    store.record(0.0, {"g": ("gauge", 5.0)})
    store.record(1.0, {"g": ("gauge", 6.0)})
    assert store.latest("g") == 6.0


# -- MetricsSampler ---------------------------------------------------------


def test_sample_once_runs_hooks_and_callbacks():
    reg = MetricsRegistry()
    g = reg.gauge("dllama_t_live", "g")
    ticks = {"n": 0}

    def refresher():
        ticks["n"] += 1
        g.set(float(ticks["n"]))

    reg.add_refresh_hook("live", refresher)
    store, _, _ = _store(registry=reg)
    fake = {"t": 100.0}
    sampler = MetricsSampler(store, registry=reg, clock=lambda: fake["t"])
    seen = []
    sampler.on_sample.append(seen.append)
    sampler.on_sample.append(lambda now: 1 / 0)  # must be contained

    now = sampler.sample_once()
    assert now == 100.0
    # the hook ran BEFORE the snapshot: the sampled value is current,
    # independent of any /metrics scrape
    assert store.latest("dllama_t_live") == 1.0
    assert seen == [100.0]
    fake["t"] = 101.0
    sampler.sample_once()
    assert store.latest("dllama_t_live") == 2.0
    assert store.m_samples.value == 2


def test_sampler_thread_starts_and_joins():
    """The sampler thread is named, daemonic, and stop() joins it — the
    fast lane runs this under DLLAMA_LOCKWATCH=1 in CI."""
    reg = MetricsRegistry()
    reg.gauge("dllama_t_g", "g").set(1.0)
    store, _, _ = _store(registry=reg, interval_s=0.005)
    sampler = MetricsSampler(store, registry=reg)
    sampler.start()
    t = sampler._thread
    assert t is not None and t.daemon and t.name == "dllama-series-sampler"
    deadline = time.monotonic() + 5.0
    while store.m_samples.value < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert store.m_samples.value >= 2, "sampler thread never ticked"
    sampler.stop()
    assert sampler._thread is None
    assert not t.is_alive()
    sampler.stop()  # idempotent


# -- EWMA / rules -----------------------------------------------------------


def test_ewma_baseline_tracks_mean_and_var():
    b = EwmaBaseline(alpha=0.2)
    for _ in range(200):
        b.update(10.0)
    assert b.mean == pytest.approx(10.0)
    assert b.std == pytest.approx(0.0, abs=1e-9)
    for v in (9.0, 11.0, 9.0, 11.0, 9.0, 11.0):
        b.update(v)
    assert 9.0 < b.mean < 11.0
    assert b.std > 0.0


def test_rule_warmup_and_guards():
    rule = AnomalyRule(
        "t", lambda: None, z_threshold=4.0, min_samples=10,
        min_abs=0.5, rel_frac=1.0,
    )
    b = EwmaBaseline()
    for _ in range(5):
        b.update(1.0)
    # warmup: even a huge spike cannot fire before min_samples
    assert rule.abnormal(b, 100.0) is None
    for _ in range(10):
        b.update(1.0)
    # min_abs/rel_frac floors: a tiny deviation off a near-constant
    # baseline has a huge z but must not alarm
    assert rule.abnormal(b, 1.3) is None
    z = rule.abnormal(b, 100.0)
    assert z is not None and z >= 4.0


def test_rule_low_direction_min_mean():
    rule = AnomalyRule(
        "t", lambda: None, direction="low", min_samples=3, min_mean=1.0,
        min_abs=0.5,
    )
    b = EwmaBaseline()
    for _ in range(10):
        b.update(0.0)
    # an idle signal sitting at zero can never "drop"
    assert rule.abnormal(b, -5.0) is None
    b2 = EwmaBaseline()
    for _ in range(10):
        b2.update(10.0)
    assert rule.abnormal(b2, 0.0) is not None


def test_rule_rejects_bad_direction():
    with pytest.raises(ValueError):
        AnomalyRule("t", lambda: None, direction="sideways")


# -- AnomalyMonitor ---------------------------------------------------------


def _monitor(rule, **kw):
    reg = kw.pop("registry", MetricsRegistry())
    rec = kw.pop("recorder", FlightRecorder())
    fake = {"t": 0.0}
    mon = AnomalyMonitor(
        [rule], registry=reg, recorder=rec, clock=lambda: fake["t"]
    )
    return mon, reg, rec, fake


def test_anomaly_fires_and_recovers_deterministically():
    """The ISSUE 9 acceptance unit: a rule fires on an injected spike
    (incrementing dllama_anomaly_total and the degraded gauge), its
    baseline FREEZES while active, and `recover_ticks` calm ticks later
    it recovers — all under a fake clock."""
    sig = {"v": 1.0}
    rule = AnomalyRule(
        "stall", lambda: sig["v"], z_threshold=4.0, min_samples=20,
        min_abs=0.1, rel_frac=0.5, recover_ticks=3,
    )
    mon, reg, rec, fake = _monitor(rule)
    for i in range(30):
        fake["t"] = float(i)
        assert mon.evaluate() == []
    assert not mon.degraded

    sig["v"] = 50.0
    fake["t"] = 30.0
    assert mon.evaluate() == ["stall"]
    assert mon.degraded and mon.active_signals() == ["stall"]
    assert mon.m_anomalies.labels(signal="stall").value == 1
    assert mon.g_degraded.value == 1.0
    (ev,) = rec.events("anomaly")
    assert ev["signal"] == "stall" and ev["z"] >= 4.0
    frozen_mean = mon._state["stall"].baseline.mean
    st = mon.status()
    assert st["degraded"] and "stall" in st["active"]
    assert st["active"]["stall"]["active_s"] == 0.0

    # still anomalous: stays active, fires NOTHING new (edge-triggered),
    # and the anomaly never teaches the baseline
    fake["t"] = 31.0
    assert mon.evaluate() == []
    assert mon.m_anomalies.labels(signal="stall").value == 1
    assert mon._state["stall"].baseline.mean == frozen_mean

    # recovery hysteresis: recover_ticks consecutive calm ticks clear it
    sig["v"] = 1.0
    for i in range(3):
        fake["t"] = 32.0 + i
        assert mon.evaluate() == []
    assert not mon.degraded
    assert mon.g_degraded.value == 0.0
    assert [e["signal"] for e in rec.events("anomaly_recovered")] == ["stall"]


def test_anomaly_missing_values_count_as_calm():
    """A quiet engine (value_fn -> None: no traffic) must recover."""
    sig = {"v": 1.0}
    rule = AnomalyRule(
        "r", lambda: sig["v"], min_samples=5, min_abs=0.1, recover_ticks=2,
    )
    mon, _, _, fake = _monitor(rule)
    for i in range(10):
        fake["t"] = float(i)
        mon.evaluate()
    sig["v"] = 99.0
    fake["t"] = 10.0
    assert mon.evaluate() == ["r"]
    sig["v"] = None
    for i in range(2):
        fake["t"] = 11.0 + i
        mon.evaluate()
    assert not mon.degraded


def test_anomaly_value_fn_errors_are_contained():
    rule = AnomalyRule("boom", lambda: 1 / 0, min_samples=1)
    mon, _, _, _ = _monitor(rule)
    assert mon.evaluate() == []  # logs, skips, keeps serving
    assert not mon.degraded


# -- signal helpers / default rule set --------------------------------------


def test_per_event_rate_reads_histogram_deltas():
    store, _, _ = _store()
    fn = _per_event_rate(store, "h_sum", "h_count")
    assert fn() is None  # series absent
    store.record(0.0, {"h_sum": ("counter", 1.0), "h_count": ("counter", 2.0)})
    assert fn() is None  # first observation: no previous tick
    store.record(1.0, {"h_sum": ("counter", 4.0), "h_count": ("counter", 4.0)})
    assert fn() == pytest.approx(1.5)  # (4-1)/(4-2)
    store.record(2.0, {"h_sum": ("counter", 4.0), "h_count": ("counter", 4.0)})
    assert fn() is None  # no new observations this tick


def test_slope_and_level():
    store, _, _ = _store()
    slope, level = _slope(store, "g"), _level(store, "g")
    assert slope() is None and level() is None
    store.record(0.0, {"g": ("gauge", 100.0)})
    assert slope() is None and level() == 100.0
    store.record(1.0, {"g": ("gauge", 90.0)})
    assert slope() == pytest.approx(-10.0) and level() == 90.0


def test_default_rules_cover_the_production_signals():
    store, _, _ = _store()
    rules = build_default_rules(store)
    assert [r.signal for r in rules] == [
        "decode_stall", "ttft", "tpot", "kv_free_slope", "goodput",
        "predict_error",
    ]
    # every rule's value_fn is callable against an empty store (returns
    # None, which neither fires nor learns)
    assert all(r.value_fn() is None for r in rules)


def test_kv_free_slope_fires_on_sustained_drain():
    """End-to-end over the real store + default rules: steady KV
    free-page churn teaches the baseline, then a persistent fast drain
    fires kv_free_slope (the leak early-warning)."""
    store, _, _ = _store()
    rules = {r.signal: r for r in build_default_rules(store)}
    rule = rules["kv_free_slope"]
    mon = AnomalyMonitor(
        [rule], registry=MetricsRegistry(), recorder=FlightRecorder(),
        clock=lambda: 0.0,
    )
    free = 10_000.0
    t = 0.0
    for i in range(40):  # slope -1 page/tick: normal churn
        free -= 1.0
        store.record(t, {"dllama_kv_pages_free": ("gauge", free)})
        t += 1.0
        assert mon.evaluate(now=t) == []
    fired = []
    for i in range(5):  # drain 400 pages/tick
        free -= 400.0
        store.record(t, {"dllama_kv_pages_free": ("gauge", free)})
        t += 1.0
        fired += mon.evaluate(now=t)
    assert fired == ["kv_free_slope"]
