"""Fleet tests: prefix-affinity routing units + a live 2-replica smoke.

The pure units (hash ring, route planning, registry state machine) are
marked ``fast`` and run in CI's first lane; the live fleet tests share
one module-scoped 2-replica CPU topology and run as the fast lane's
fleet smoke (``pytest tests/test_fleet.py -m "not fast"``).

ORDER MATTERS in the live section: draining a replica is permanent for
the fixture's lifetime, so the drain/rolling-restart test is LAST.
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import pytest

from dllama_tpu.fleet.affinity import (
    HashRing,
    plan_route,
    prefix_affinity_key,
)
from dllama_tpu.fleet.replicas import (
    DEAD,
    DEGRADED,
    DRAINING,
    HEALTHY,
    ReplicaRegistry,
    ReplicaView,
)

from helpers import make_tiny_model, make_tiny_tokenizer


# ---------------------------------------------------------------------------
# pure units (fast lane)
# ---------------------------------------------------------------------------


def _view(name, state=HEALTHY, max_streams=0, in_flight=0):
    return ReplicaView(
        name=name, base_url=f"http://x/{name}", state=state,
        max_streams=max_streams, in_flight=in_flight,
    )


@pytest.mark.fast
def test_prefix_key_hashes_first_k_only():
    a = prefix_affinity_key([1, 2, 3, 4, 5], k=3)
    # same first 3 ids, different tail -> same key (shared prefix lands
    # on the shared replica)
    assert prefix_affinity_key([1, 2, 3, 9, 9, 9], k=3) == a
    # a change inside the window moves the key
    assert prefix_affinity_key([1, 2, 4, 4, 5], k=3) != a
    # stable across processes: a literal, not hash()-derived
    assert prefix_affinity_key([0], k=1) == prefix_affinity_key([0], k=1)
    with pytest.raises(ValueError):
        prefix_affinity_key([1], k=0)


@pytest.mark.fast
def test_ring_stable_assignment_under_add_remove():
    names = [f"r{i}" for i in range(4)]
    ring = HashRing(names)
    keys = [prefix_affinity_key([i, i + 1, i + 2]) for i in range(200)]
    before = {k: ring.order(k)[0] for k in keys}
    # removing one replica only moves the keys it owned; every other
    # key keeps its target (the consistent-hashing contract)
    ring.remove("r2")
    for k, owner in before.items():
        if owner != "r2":
            assert ring.order(k)[0] == owner
        else:
            assert ring.order(k)[0] != "r2"
    # adding it back restores the original assignment exactly
    ring.add("r2")
    assert {k: ring.order(k)[0] for k in keys} == before
    # order() lists every replica exactly once
    order = ring.order(keys[0])
    assert sorted(order) == sorted(names)


@pytest.mark.fast
def test_ring_spread():
    ring = HashRing([f"r{i}" for i in range(3)])
    owners = [
        ring.order(prefix_affinity_key([i, 2 * i, 3 * i]))[0]
        for i in range(300)
    ]
    counts = {n: owners.count(n) for n in set(owners)}
    # virtual nodes keep the split rough-thirds, not degenerate
    assert len(counts) == 3
    assert all(c > 30 for c in counts.values()), counts


@pytest.mark.fast
def test_plan_route_spill_determinism():
    order = ["r0", "r1", "r2", "r3"]
    views = {
        "r0": _view("r0", state=DRAINING),
        "r1": _view("r1", state=DEGRADED),
        "r2": _view("r2", max_streams=2, in_flight=2),  # saturated
        "r3": _view("r3"),
    }
    plan = plan_route(order, views)
    # healthy first, degraded demoted to last resort, draining and
    # saturated skipped with reasons
    assert plan.target == "r0"
    assert plan.candidates == ["r3", "r1"]
    assert ("r0", "draining") in plan.skipped
    assert ("r2", "saturated") in plan.skipped
    assert plan.spill_reason == "draining"
    # deterministic: same inputs, same plan
    again = plan_route(order, views)
    assert (again.candidates, again.skipped) == (
        plan.candidates, plan.skipped,
    )
    # dead and unknown replicas never appear
    views["r3"] = _view("r3", state=DEAD)
    del views["r1"]
    plan2 = plan_route(order, views)
    assert plan2.candidates == []
    assert ("r3", "dead") in plan2.skipped and ("r1", "dead") in plan2.skipped


@pytest.mark.fast
def test_plan_route_affinity_hit_has_no_spill_reason():
    views = {"r0": _view("r0"), "r1": _view("r1")}
    plan = plan_route(["r0", "r1"], views)
    assert plan.candidates[0] == plan.target == "r0"
    assert plan.spill_reason is None


@pytest.mark.fast
def test_registry_state_machine():
    payloads = {
        "http://a": {"status": "ok", "capacity": {
            "max_streams": 4, "in_flight": 1, "lanes": 2, "parked": 0,
            "kv_native": True,
        }},
        "http://b": {"status": "degraded", "degraded_reasons": ["watchdog"]},
    }
    boom = set()

    def fetch(url):
        if url in boom:
            raise OSError("down")
        return payloads[url]

    t = [0.0]
    reg = ReplicaRegistry(
        {"a": "http://a", "b": "http://b"},
        fetch=fetch, clock=lambda: t[0], fail_threshold=2,
    )
    states = reg.poll_once()
    assert states == {"a": HEALTHY, "b": DEGRADED}
    views = reg.views()
    assert views["a"].max_streams == 4 and views["a"].kv_native
    assert views["a"].in_flight == 1 and not views["a"].saturated
    assert views["b"].degraded_reasons == ("watchdog",)
    # death needs fail_threshold consecutive failures...
    boom.add("http://a")
    assert reg.poll_once()["a"] == HEALTHY
    assert reg.poll_once()["a"] == DEAD
    # ...and one good poll revives
    boom.clear()
    assert reg.poll_once()["a"] == HEALTHY
    # router veto + drain echo are immediate
    reg.mark_dead("a", "connect")
    assert reg.views()["a"].state == DEAD
    reg.poll_once()
    reg.mark_draining("b")
    assert reg.views()["b"].state == DRAINING
    # draining is what the REGISTRY says until health confirms: the next
    # poll of the (still 'degraded'-reporting) fake flips it back
    assert reg.poll_once()["b"] == DEGRADED
    snap = reg.snapshot()
    assert snap["a"]["health"]["status"] == "ok"


@pytest.mark.fast
def test_resolve_fleet_knobs(monkeypatch):
    from dllama_tpu.fleet.router import resolve_fleet_knobs

    monkeypatch.setenv("DLLAMA_FLEET_AFFINITY_K", "7")
    monkeypatch.setenv("DLLAMA_FLEET_STALL_S", "9.5")
    k, fmax, stall, poll = resolve_fleet_knobs()
    assert (k, stall) == (7, 9.5)
    # explicit beats env
    k2, _, stall2, _ = resolve_fleet_knobs(
        affinity_k=3, stall_timeout_s=1.0
    )
    assert (k2, stall2) == (3, 1.0)
    with pytest.raises(ValueError):
        resolve_fleet_knobs(affinity_k=0)


# ---------------------------------------------------------------------------
# fleet observability plane units (fast lane)
# ---------------------------------------------------------------------------


@pytest.mark.fast
def test_resolve_fleet_obs_knobs(monkeypatch):
    from dllama_tpu.fleet.obs import resolve_fleet_obs_knobs

    monkeypatch.setenv("DLLAMA_FLEET_OBS_INTERVAL_S", "0.5")
    monkeypatch.setenv("DLLAMA_FLEET_OBS_LEDGER", "64")
    interval, retention, cap = resolve_fleet_obs_knobs()
    assert (interval, cap) == (0.5, 64)
    # explicit beats env
    interval2, retention2, _ = resolve_fleet_obs_knobs(
        interval_s=2.0, retention_s=60.0
    )
    assert (interval2, retention2) == (2.0, 60.0)
    with pytest.raises(ValueError):
        resolve_fleet_obs_knobs(interval_s=0.0)


@pytest.mark.fast
def test_prom_text_parse_relabel_quantile():
    from dllama_tpu.fleet.obs import (
        histogram_quantile,
        parse_prom_text,
        relabel_prom_text,
    )

    text = (
        "# HELP dllama_tpot_seconds per-token latency\n"
        'dllama_tpot_seconds_bucket{le="0.01"} 4\n'
        'dllama_tpot_seconds_bucket{le="0.02"} 10\n'
        'dllama_tpot_seconds_bucket{le="+Inf"} 10\n'
        'dllama_slo_goodput_tokens_per_s{window="1m"} 42.5\n'
        "dllama_lanes_active 2\n"
        'dllama_router_requests_total{replica="r0",outcome="ok"} 3\n'
    )
    series = parse_prom_text(text)
    assert ("dllama_lanes_active", {}, 2.0) in series
    assert (
        "dllama_slo_goodput_tokens_per_s", {"window": "1m"}, 42.5
    ) in series
    # PromQL-style interpolation: target rank 5 sits 1/6 into the
    # (0.01, 0.02] bucket
    p50 = histogram_quantile(series, "dllama_tpot_seconds", 0.5)
    assert abs(p50 - (0.01 + (1 / 6) * 0.01)) < 1e-9
    assert histogram_quantile(series, "dllama_absent", 0.5) is None
    out = relabel_prom_text(
        text, "r1", skip_prefixes=("dllama_router_",)
    )
    # every kept line gains replica= as FIRST label; comments and the
    # router's own families are dropped (no recursion in data form)
    assert '{replica="r1",window="1m"} 42.5' in out
    assert 'dllama_lanes_active{replica="r1"} 2' in out
    assert "# HELP" not in out and "dllama_router_requests" not in out


@pytest.mark.fast
def test_request_ledger_and_stitching():
    from dllama_tpu.fleet.obs import RequestLedger, stitch_timelines

    ledger = RequestLedger(capacity=2)
    ledger.open("a", "trace-a")
    ledger.touch("a", "r0")
    ledger.touch("a", "r0")  # no-change touches don't duplicate
    ledger.failover("a", from_replica="r0", reason="eof",
                    emitted_tokens=3)
    ledger.close_failover("a", "r1", 0.25)
    ledger.touch("a", "r1")
    e = ledger.get("a")
    assert e["trace_id"] == "trace-a"
    assert e["replicas"] == ["r0", "r1"]
    assert e["failovers"] == [{
        "from": "r0", "to": "r1", "reason": "eof",
        "emitted_tokens": 3, "gap_s": 0.25,
    }]
    # bounded FIFO: two more opens evict the oldest
    ledger.open("b", "t-b")
    ledger.open("c", "t-c")
    assert ledger.get("a") is None
    assert [r["request_id"] for r in ledger.recent()] == ["c", "b"]

    router = {
        "traceEvents": [
            {"ph": "X", "pid": 6, "tid": -1, "ts": 100.0, "dur": 5.0,
             "name": "relay"},
        ],
        "dllama": {"epoch_unix": 1000.0},
    }
    frag = {
        "traceEvents": [
            {"ph": "M", "pid": 101, "tid": 0, "name": "process_name",
             "args": {"name": "r0/http"}},
            {"ph": "X", "pid": 101, "tid": 0, "ts": 50.0, "dur": 5.0,
             "name": "queue"},
        ],
        "dllama": {"epoch_unix": 1002.5},
    }
    merged = stitch_timelines(router, [("r0", frag)])
    assert merged["dllama"]["sources"] == {"router": 1, "r0": 1}
    assert merged["dllama"]["n_spans"] == 2
    xs = {e["name"]: e for e in merged["traceEvents"]
          if e["ph"] == "X"}
    # the fragment's ts rebases onto the router epoch: +2.5s in µs
    assert xs["queue"]["ts"] == 50.0 + 2.5e6
    assert xs["relay"]["ts"] == 100.0  # router events untouched
    # metadata events survive the merge (Perfetto needs the pid names)
    assert any(e["ph"] == "M" for e in merged["traceEvents"])


def _fake_scrape(goodput, p50_ms):
    """Prometheus text whose interpolated TPOT p50 is exactly p50_ms."""
    le = p50_ms * 2.0 / 1000.0  # target rank falls mid-bucket
    return (
        f'dllama_slo_goodput_tokens_per_s{{window="1m"}} {goodput}\n'
        f'dllama_tpot_seconds_bucket{{le="{le}"}} 10\n'
        'dllama_tpot_seconds_bucket{le="+Inf"} 10\n'
    )


@pytest.mark.fast
def test_fleet_anomaly_degrades_router_health(tmp_path):
    """Acceptance: replica-labelled fleet aggregates drive a fleet
    anomaly rule through router /v1/health degraded_reasons, fully
    deterministic — fake clock, fake scrape fetch, no live replicas."""
    from dllama_tpu.fleet.obs import FleetObs
    from dllama_tpu.fleet.router import RouterState
    from dllama_tpu.obs.metrics import MetricsRegistry
    from dllama_tpu.obs.recorder import FlightRecorder
    from dllama_tpu.tokenizer import Tokenizer

    tp_ = str(tmp_path / "t.t")
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    clock = {"t": 0.0}
    replica_reg = ReplicaRegistry(
        {"r0": "http://r0", "r1": "http://r1"},
        fetch=lambda url: {"status": "ok"},
        clock=lambda: clock["t"],
    )
    replica_reg.poll_once()
    skew = {"r1_p50_ms": 10.0}

    def fetch(url):
        p50 = 10.0 if "//r0" in url else skew["r1_p50_ms"]
        return _fake_scrape(50.0, p50)

    fobs = FleetObs(
        replica_reg,
        registry=MetricsRegistry(),
        recorder=FlightRecorder(),
        fetch=fetch,
        clock=lambda: clock["t"],
        interval_s=1.0,
    )
    state = RouterState(replica_reg, Tokenizer(tp_), fleet_obs=fobs)
    # warm the EWMA baselines: both replicas agree, skew = 0
    for _ in range(40):
        clock["t"] += 1.0
        fobs.sampler.sample_once(clock["t"])
    assert not fobs.monitor.degraded
    h = state.health_payload()
    assert h["status"] == "ok" and h["degraded_reasons"] == []
    # the scraped aggregates are live and replica-labelled
    assert fobs.store.latest("dllama_fleet_goodput_tokens_per_s") == 100.0
    assert fobs.store.latest(
        'dllama_fleet_replica_tpot_p50_ms{replica="r1"}'
    ) == 10.0
    fleet_text = fobs.render_fleet()
    assert '{replica="r0",window="1m"} 50.0' in fleet_text
    # r1's TPOT p50 pulls away from its sibling: the skew rule fires
    skew["r1_p50_ms"] = 300.0
    clock["t"] += 1.0
    fobs.sampler.sample_once(clock["t"])
    assert fobs.monitor.degraded
    assert "fleet_tpot_skew" in fobs.monitor.active_signals()
    h = state.health_payload()
    assert h["status"] == "degraded"
    assert "fleet_anomaly:fleet_tpot_skew" in h["degraded_reasons"]
    fobs.close()


# ---------------------------------------------------------------------------
# live 2-replica fleet (the CI fleet smoke)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    from dllama_tpu.fleet.launch import launch_inprocess_fleet

    d = tmp_path_factory.mktemp("fleet")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    cfg = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
    make_tiny_model(mp, cfg=cfg)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    handle = launch_inprocess_fleet(mp, tp_, n_replicas=2, batch_size=2)
    yield handle
    handle.close()


def _post(url, payload, timeout=180):
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    return urllib.request.urlopen(req, timeout=timeout)


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _scrape(url):
    with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
        return r.read().decode()


def _metric(text, name, labels=None):
    """Value of one series (0.0 when the family has no such child)."""
    pattern = re.escape(name) + (re.escape(labels) if labels else "") + r" ([0-9.e+-]+)"
    m = re.search(pattern, text)
    return float(m.group(1)) if m else 0.0


def _stream(url, payload):
    payload = dict(payload)
    payload["stream"] = True
    with _post(url, payload) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        raw = r.read().decode()
    assert raw.rstrip().endswith("data: [DONE]"), raw[-300:]
    events = [
        json.loads(line[len("data: "):])
        for line in raw.splitlines()
        if line.startswith("data: ") and line != "data: [DONE]"
    ]
    assert all("error" not in e for e in events), events
    text = "".join(
        (e["choices"][0].get("delta") or {}).get("content") or ""
        for e in events
    )
    finish = [
        e["choices"][0]["finish_reason"]
        for e in events
        if e["choices"][0].get("finish_reason")
    ]
    assert len(finish) == 1, events
    return text, finish[0]


def test_router_tokenization_matches_replica(fleet):
    """No tokenizer round-trip drift: the router's affinity tokenization
    must count exactly the tokens replica admission counts."""
    msgs = [{"role": "user", "content": "hello world, count my tokens"}]
    with _post(fleet.router_url, {"messages": msgs, "max_tokens": 2,
                                  "temperature": 0}) as r:
        data = json.loads(r.read())
    expected = fleet.router.state.prompt_tokens(msgs)
    assert data["usage"]["prompt_tokens"] == len(expected)


def test_affinity_routes_repeated_prefix_to_one_replica(fleet):
    before = _scrape(fleet.router_url)
    msgs = [{"role": "user", "content": "the affinity prompt"}]
    for _ in range(3):
        with _post(fleet.router_url, {"messages": msgs, "max_tokens": 3,
                                      "temperature": 0}) as r:
            json.loads(r.read())
    after = _scrape(fleet.router_url)
    hits = (
        _metric(after, "dllama_router_affinity_hits_total")
        - _metric(before, "dllama_router_affinity_hits_total")
    )
    assert hits == 3.0
    # all three served by the SAME replica -> the radix tree reused the
    # repeated prompt at least twice (the engine-side payoff affinity
    # routing exists for; registry is process-global so any port works)
    radix = (
        _metric(after, "dllama_prefix_cache_hits_total")
        - _metric(before, "dllama_prefix_cache_hits_total")
    )
    assert radix >= 2.0


def test_replica_health_capacity_block(fleet):
    for name, url in fleet.replica_urls.items():
        h = _get(url + "/v1/health")
        assert h["replica"] == name
        cap = h["capacity"]
        assert cap["lanes"] == 2
        assert cap["max_streams"] >= cap["lanes"]
        assert cap["in_flight"] >= 0 and cap["parked"] >= 0
        assert isinstance(cap["kv_native"], bool)


def test_fleet_endpoint_aggregates(fleet):
    fl = _get(fleet.router_url + "/v1/fleet")
    assert set(fl["replicas"]) == {"r0", "r1"}
    assert fl["aggregate"]["lanes_total"] == 4
    assert fl["aggregate"]["states"].get("healthy") == 2
    assert fl["router"]["routing"] == "affinity"
    for rep in fl["replicas"].values():
        assert rep["state"] == "healthy"
        assert rep["health"]["capacity"]["lanes"] == 2


def test_router_health(fleet):
    h = _get(fleet.router_url + "/v1/health")
    assert h["status"] == "ok" and h["role"] == "router"
    assert h["replicas"] == {"r0": "healthy", "r1": "healthy"}


def test_midstream_failover_byte_identical(fleet):
    """The tentpole: kill the serving replica at its 3rd SSE flush; the
    router must resume on the sibling and the client must read the exact
    fault-free byte stream."""
    from dllama_tpu.runtime.faults import set_fault_plane

    url = fleet.router_url
    p = {"messages": [{"role": "user", "content": "tell me a story"}],
         "max_tokens": 16, "temperature": 0}
    base_text, base_finish = _stream(url, p)
    # which replica owns this prompt? ask the plan, not the metrics
    state = fleet.router.state
    plan = state.route(state.prompt_tokens(p["messages"]))
    target = plan.target
    before = _scrape(url)
    set_fault_plane(f"sse_flush:op={target}:nth=3:n=1")
    try:
        ft_text, ft_finish = _stream(url, p)
    finally:
        set_fault_plane(None)
    assert (ft_text, ft_finish) == (base_text, base_finish)
    after = _scrape(url)
    assert (
        _metric(after, "dllama_router_failovers_total")
        - _metric(before, "dllama_router_failovers_total")
    ) == 1.0
    assert (
        _metric(after, "dllama_router_requests_total",
                f'{{replica="{target}",outcome="died"}}')
        - _metric(before, "dllama_router_requests_total",
                  f'{{replica="{target}",outcome="died"}}')
    ) == 1.0


def _stream_with_headers(url, payload):
    """Like ``_stream`` but also returns the response headers (the
    router echoes x-dllama-request / x-dllama-trace)."""
    payload = dict(payload)
    payload["stream"] = True
    with _post(url, payload) as r:
        headers = {k.lower(): v for k, v in r.headers.items()}
        raw = r.read().decode()
    events = [
        json.loads(line[len("data: "):])
        for line in raw.splitlines()
        if line.startswith("data: ") and line != "data: [DONE]"
    ]
    assert all("error" not in e for e in events), events
    text = "".join(
        (e["choices"][0].get("delta") or {}).get("content") or ""
        for e in events
    )
    return text, headers


def test_trace_propagation_and_stitched_timeline(fleet):
    """Satellite 3 + tentpole acceptance: a seeded mid-stream failover
    leaves the SAME trace id in both replicas' trace sinks, and
    /v1/fleet/timeline merges router + both replicas into one Perfetto
    trace whose relay spans have zero overlap and whose gap is an
    explicit attributed ``failover`` span."""
    from dllama_tpu.runtime.faults import set_fault_plane

    url = fleet.router_url
    p = {"messages": [{"role": "user", "content": "stitch my timeline"}],
         "max_tokens": 16, "temperature": 0}
    state = fleet.router.state
    victim = state.route(state.prompt_tokens(p["messages"])).target
    sibling = next(n for n in fleet.replica_urls if n != victim)
    set_fault_plane(f"sse_flush:op={victim}:nth=3:n=1")
    try:
        text, headers = _stream_with_headers(url, p)
    finally:
        set_fault_plane(None)
    assert text
    rid = headers["x-dllama-request"]
    trace = headers["x-dllama-trace"]
    assert rid.startswith("req-") and trace.startswith("trace-")
    # the propagated trace id landed in BOTH replicas' trace sinks
    by_name = dict(fleet.replicas)
    for name in (victim, sibling):
        recs = [
            r for r in by_name[name].state.tracer.records()
            if r.get("request_id") == rid
        ]
        assert recs, f"{name} recorded no trace for {rid}"
        assert all(r["trace_id"] == trace for r in recs)
    # ONE merged timeline: router + both replica fragments
    tl = _get(f"{url}/v1/fleet/timeline?request_id={rid}")
    d = tl["dllama"]
    assert d["trace_id"] == trace
    assert d["replicas"] == [victim, sibling]
    assert "fetch_errors" not in d
    assert d["sources"]["router"] > 0
    assert d["sources"][victim] > 0 and d["sources"][sibling] > 0
    xs = [e for e in tl["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in xs}
    assert {"tokenize", "route_plan", "relay", "failover",
            "catch_up_synthesis"} <= names
    relays = sorted(
        (e for e in xs if e["name"] == "relay"), key=lambda e: e["ts"]
    )
    assert len(relays) == 2
    assert relays[0]["args"]["replica"] == victim
    assert relays[0]["args"]["outcome"] == "died"
    assert relays[1]["args"]["replica"] == sibling
    assert relays[1]["args"]["resumed"] is True
    (fail,) = [e for e in xs if e["name"] == "failover"]
    assert fail["args"]["from_replica"] == victim
    assert fail["args"]["to_replica"] == sibling
    eps = 1.0  # µs rounding slop
    # zero overlap: the victim relay ended before the sibling relay
    # began, and the failover span is attributed to that gap
    assert relays[0]["ts"] + relays[0]["dur"] <= relays[1]["ts"] + eps
    assert fail["ts"] >= relays[0]["ts"] + relays[0]["dur"] - eps
    assert (fail["ts"] + fail["dur"]
            <= relays[1]["ts"] + relays[1]["dur"] + eps)
    # the ledger attributed the hop and its client-visible gap
    assert d["failovers"][0]["from"] == victim
    assert d["failovers"][0]["to"] == sibling
    assert d["failovers"][0]["gap_s"] > 0
    # replica fragment events (pid-namespaced >= 100) carry the
    # propagated request id
    rep_events = [e for e in xs if e.get("pid", 0) >= 100]
    assert rep_events
    assert all(
        e["args"].get("request_id") == rid for e in rep_events
    )
    # recovery latency booked in the router gap histogram
    m = _scrape(url)
    assert _metric(m, "dllama_router_failover_gap_seconds_count") >= 1.0
    # the fleet postmortem dump: router events + every replica's ring
    dump = _get(url + "/v1/fleet/debug/recorder")
    assert set(dump["replicas"]) == {"r0", "r1"}
    for repd in dump["replicas"].values():
        assert "events" in repd
    events = dump["router"]["events"]
    fo = [e for e in events if e["kind"] == "router_failover"][-1]
    assert fo["trace_id"] == trace and fo["request_id"] == rid
    # both replicas adopted the SAME trace id at admission
    adopts = [
        e for e in events
        if e["kind"] == "trace_adopt" and e.get("trace_id") == trace
    ]
    assert {e.get("replica") for e in adopts} == {victim, sibling}
    assert any(e.get("resumed") for e in adopts)


def test_router_fleet_metrics_reexport(fleet):
    """Router /metrics = its own families + every replica's series
    re-exported with a replica label, plus the fleet aggregates."""
    state = fleet.router.state
    # scrape synchronously (the background sampler also does this, but
    # the test must not depend on its timing)
    ok = state.fleet.scrape_once()
    assert ok == {"r0": True, "r1": True}
    m = _scrape(fleet.router_url)
    # replica-labelled re-export of a replica-side family
    assert re.search(
        r'dllama_http_requests_total\{replica="r0",', m
    ), m[:2000]
    # fleet aggregates are present and sane
    assert _metric(m, "dllama_fleet_replicas", '{state="healthy"}') == 2.0
    assert _metric(m, "dllama_fleet_goodput_tokens_per_s") >= 0.0
    skew = _metric(m, "dllama_fleet_tpot_skew_ms")
    assert skew >= 0.0
    assert _metric(
        m, "dllama_fleet_scrapes_total", '{outcome="ok"}'
    ) >= 2.0
    # per-replica TPOT p50 gauges exist for both replicas
    for name in ("r0", "r1"):
        assert re.search(
            r"dllama_fleet_replica_tpot_p50_ms\{replica=\"%s\"\}" % name,
            m,
        )
    # the router's series endpoint serves the fleet store + monitor
    idx = _get(fleet.router_url + "/v1/debug/series")
    assert "dllama_fleet_goodput_tokens_per_s" in idx["names"]
    assert idx["anomaly"]["degraded"] is False
    q = _get(
        fleet.router_url
        + "/v1/debug/series?name=dllama_fleet_goodput_tokens_per_s"
        "&window=600"
    )
    assert q["points"], q
    # the fleet dashboard serves the self-contained page
    with urllib.request.urlopen(
        fleet.router_url + "/dashboard", timeout=30
    ) as r:
        page = r.read().decode()
    assert "dllama_fleet_goodput_tokens_per_s" in page


def test_fleet_chaos_every_stream_completes(fleet):
    """Seeded fleet chaos: multiple concurrent streams while one replica
    drops TWO of them mid-flight — every client still reads its exact
    fault-free bytes (completion rate 1.0)."""
    from dllama_tpu.runtime.faults import set_fault_plane

    url = fleet.router_url
    prompts = [
        {"messages": [{"role": "user", "content": f"chaos stream {i}"}],
         "max_tokens": 12, "temperature": 0}
        for i in range(4)
    ]
    baseline = [_stream(url, p) for p in prompts]
    state = fleet.router.state
    targets = {
        json.dumps(p["messages"]): state.route(
            state.prompt_tokens(p["messages"])
        ).target
        for p in prompts
    }
    victim = next(iter(targets.values()))
    results: list = [None] * len(prompts)
    errors: list = []

    def run(i):
        try:
            results[i] = _stream(url, prompts[i])
        except Exception as e:  # noqa: BLE001 - collected and asserted below
            errors.append((i, repr(e)))

    set_fault_plane(f"sse_flush:op={victim}:nth=2:n=2")
    try:
        threads = [
            threading.Thread(target=run, args=(i,))
            for i in range(len(prompts))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
    finally:
        set_fault_plane(None)
    assert not errors, errors
    # completion rate 1.0, byte-identical to the fault-free round
    assert results == baseline


def test_drain_rolling_restart_last(fleet):
    """LAST live test (drain is permanent for the fixture): drain one
    replica through the router mid-run; its response reports in-flight +
    drained, a `drained` recorder event fires, and the fleet keeps
    serving on the sibling."""
    url = fleet.router_url
    # keep a stream in flight on the victim while we drain it
    state = fleet.router.state
    msgs = [{"role": "user", "content": "the affinity prompt"}]
    victim = state.route(state.prompt_tokens(msgs)).target
    hold: list = []

    def long_stream():
        hold.append(_stream(url, {"messages": msgs, "max_tokens": 24,
                                  "temperature": 0}))

    t = threading.Thread(target=long_stream)
    t.start()
    time.sleep(0.3)  # let it admit
    req = urllib.request.Request(
        f"{url}/v1/drain?replica={victim}", data=b"", method="POST"
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        first = json.loads(r.read())
    assert first["status"] == "draining" and first["replica"] == victim
    assert "in_flight" in first and "drained" in first
    t.join(timeout=180)
    assert hold, "in-flight stream must finish during drain"
    # poll the replica directly until drain completes
    victim_url = fleet.replica_urls[victim]
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        req = urllib.request.Request(
            f"{victim_url}/v1/drain", data=b"", method="POST"
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            poll = json.loads(r.read())
        if poll["drained"]:
            break
        time.sleep(0.1)
    assert poll["drained"] and poll["in_flight"] == 0
    events = _get(victim_url + "/v1/debug/recorder")["events"]
    kinds = [e["kind"] for e in events]
    assert "drain_begin" in kinds and "drained" in kinds
    drained_ev = [e for e in events if e["kind"] == "drained"][-1]
    assert drained_ev["in_flight"] == 0 and drained_ev["drain_s"] >= 0
    # the registry sees it, and traffic still flows on the sibling
    fleet.registry.poll_once()
    assert fleet.registry.views()[victim].state == DRAINING
    text, finish = _stream(url, {"messages": msgs, "max_tokens": 4,
                                 "temperature": 0})
    assert finish in ("stop", "length")
    sibling = next(n for n in fleet.replica_urls if n != victim)
    m = _scrape(url)
    assert _metric(
        m, "dllama_router_requests_total",
        f'{{replica="{sibling}",outcome="ok"}}',
    ) >= 1.0
