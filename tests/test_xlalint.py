"""xlalint self-tests (PR 11): each compiled-program rule fires on a
deliberately broken executable and stays quiet on the healthy one.

Two layers:

* parser/rule units against a synthetic HLO dump (no compilation) and
  against REAL CPU-compiled toy programs seeded with one violation
  each — a dropped donation, a full-table all-gather, a host callback,
  an f32 accumulate-and-store upcast, a blown cost budget;
* a clean-engine smoke: a tiny real engine pre-compiles its admission
  program set (``rehearse_admission(wait=True)``) and
  ``xlalint_report()`` must show zero new findings — the same gate
  ``python -m dllama_tpu.analysis --hlo`` runs in CI — plus strict-mode
  raise behavior through the engine's own per-compile hook.
"""

import json
import pathlib
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dllama_tpu.analysis.core import apply_baseline, load_baseline
from dllama_tpu.analysis.rules_hlo import (
    CollectiveCensusRule,
    CostBudgetRule,
    DonationRule,
    DtypePolicyRule,
    HostRoundTripRule,
    collective_census,
    custom_call_targets,
    dot_store_dtypes,
    f32_upcast_store_dots,
    forbidden_gather_findings,
    gather_result_shapes,
    input_output_alias_count,
    scatter_result_dims,
)
from dllama_tpu.analysis.xlalint import (
    FamilyPolicy,
    HloFinding,
    all_hlo_rules,
    lint_programs,
    make_program,
    write_baseline_fingerprints,
)

from helpers import make_tiny_model

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]


def _compile(fn, *args, donate=()):
    return jax.jit(fn, donate_argnums=donate).lower(*args).compile()


def _findings(txt, rule, **prog_kw):
    return lint_programs([make_program(txt, **prog_kw)], [rule])


# -- parsers on a synthetic dump (no compilation) ---------------------------

SYNTHETIC = """\
HloModule jit_step, input_output_alias={ {0}: (2, {}, may-alias), {1}: (3, {}, may-alias) }, entry_computation_layout={(f32[4,64])->f32[4,64]}

ENTRY %main.42 (p0: f32[4,64], p1: bf16[64,64]) -> f32[4,64] {
  %p0 = f32[4,64]{1,0} parameter(0)
  %p1 = bf16[64,64]{1,0} parameter(1)
  %convert.1 = f32[64,64]{1,0} convert(bf16[64,64]{1,0} %p1)
  %dot.2 = f32[4,64]{1,0} dot(f32[4,64]{1,0} %p0, f32[64,64]{1,0} %convert.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %all-gather.3 = f32[256,64]{1,0} all-gather(f32[128,64]{1,0} %p0), replica_groups={{0,1}}, dimensions={0}, metadata={op_name="all-gather decoy in a string"}
  %all-reduce.4 = f32[4,64]{1,0} all-reduce(f32[4,64]{1,0} %dot.2), to_apply=%region_0.7
  %custom-call.5 = f32[4]{0} custom-call(f32[4,64]{1,0} %p0), custom_call_target="xla_python_cpu_callback"
  %custom-call.6 = f32[4]{0} custom-call(f32[4,64]{1,0} %p0), custom_call_target="tpu_custom_call"
  %constant.7 = f64[] constant(1)
  %scatter.8 = f32[2,1024,16]{2,1,0} scatter(f32[2,1024,16]{2,1,0} %p0, s32[16,1]{1,0} %p0, f32[2,16,16]{2,1,0} %p0), to_apply=%region_1.9
  %all-gather-start.9 = (f32[128,64]{1,0}, f32[256,64]{1,0}) all-gather-start(f32[128,64]{1,0} %p0), dimensions={0}
  %all-gather-done.10 = f32[256,64]{1,0} all-gather-done(%all-gather-start.9)
}
"""


@pytest.mark.fast
def test_synthetic_parsers():
    # async pair counts ONCE; the metadata decoy string never matches
    assert collective_census(SYNTHETIC) == {"all-gather": 2, "all-reduce": 1}
    shapes = gather_result_shapes(SYNTHETIC)
    assert ("f32", (256, 64)) in shapes and len(shapes) == 2
    assert input_output_alias_count(SYNTHETIC) == 2
    assert custom_call_targets(SYNTHETIC) == [
        "xla_python_cpu_callback", "tpu_custom_call",
    ]
    assert scatter_result_dims(SYNTHETIC) == [(2, 1024, 16)]
    assert f32_upcast_store_dots(SYNTHETIC) == ["dot.2"]
    assert "f32" in dot_store_dtypes(SYNTHETIC)
    assert forbidden_gather_findings(SYNTHETIC, {(256, 64)}) == [
        ("f32", (256, 64)), ("f32", (256, 64)),
    ]


@pytest.mark.fast
def test_synthetic_rules_fire_and_policies_gate():
    # census: all-gather banned for copy families, fine for forward
    fs = _findings(
        SYNTHETIC, CollectiveCensusRule(), family="kv_adopt",
        policy=FamilyPolicy(allowed_collectives=frozenset()),
    )
    assert {"all-gather", "all-reduce"} <= {
        f.message.split("'")[1] for f in fs
    }
    assert not _findings(SYNTHETIC, CollectiveCensusRule())
    # census: full-table regather + size cap
    fs = _findings(
        SYNTHETIC, CollectiveCensusRule(),
        policy=FamilyPolicy(forbidden_gather_dims=frozenset({(256, 64)})),
    )
    assert any("reassembles a full sharded table" in f.message for f in fs)
    fs = _findings(
        SYNTHETIC, CollectiveCensusRule(),
        policy=FamilyPolicy(max_allgather_elements=1000),
    )
    assert any("exceeds the family size cap" in f.message for f in fs)
    # host: the python callback flags, the Pallas kernel target does NOT
    fs = _findings(SYNTHETIC, HostRoundTripRule())
    msgs = " ".join(f.message for f in fs)
    assert "xla_python_cpu_callback" in msgs
    assert "tpu_custom_call" not in msgs
    assert "f64 tensor" in msgs  # constant.7
    assert not _findings(
        SYNTHETIC, HostRoundTripRule(),
        policy=FamilyPolicy(forbid_host=False, forbid_f64=False),
    )
    # dtype: the bf16 -> f32 store upcast fires only when the policy asks
    fs = _findings(
        SYNTHETIC, DtypePolicyRule(),
        policy=FamilyPolicy(forbid_f32_upcast_store=True),
    )
    assert any("accumulate-and-store" in f.message for f in fs)
    assert not _findings(SYNTHETIC, DtypePolicyRule())
    # dtype: store-width cap (f32 store > 16-bit limit)
    fs = _findings(
        SYNTHETIC, DtypePolicyRule(),
        policy=FamilyPolicy(max_dot_store_bits=16),
    )
    assert any("wider than the 16-bit family limit" in f.message for f in fs)
    # donation: 2 aliases present, 2 expected -> quiet; 3 expected -> fires
    assert not _findings(SYNTHETIC, DonationRule(), expected_aliases=2)
    fs = _findings(SYNTHETIC, DonationRule(), expected_aliases=3)
    assert fs and "donation dropped" in fs[0].message


@pytest.mark.fast
def test_cost_budget_rule_and_finding_fingerprints():
    cost = {"flops": 100.0, "bytes_accessed": 1000.0}
    assert not _findings(
        SYNTHETIC, CostBudgetRule(), cost=cost,
        bytes_budget=2000.0, flops_budget=200.0,
    )
    fs = _findings(
        SYNTHETIC, CostBudgetRule(), cost=cost,
        bytes_budget=500.0, flops_budget=50.0,
    )
    assert len(fs) == 2
    assert all("roofline budget" in f.message for f in fs)
    # raw numbers live in detail (rendered) but NOT in the fingerprint,
    # so a backend that shifts bytes_accessed does not churn the baseline
    f = fs[0]
    assert isinstance(f, HloFinding)
    assert ">" in f.render() and "e+" in f.render()
    assert "e+" not in f.fingerprint()
    drifted = HloFinding(
        rule=f.rule, path=f.path, line=1, message=f.message, detail="other"
    )
    assert drifted.fingerprint() == f.fingerprint()


@pytest.mark.fast
def test_program_cost_ceilings_math():
    from dllama_tpu.obs.cost import program_cost_ceilings

    fwd = program_cost_ceilings(
        "decode_lanes", steps=8, tokens=4,
        param_bytes=1e6, cache_bytes=2e5, param_elems=2.5e5,
        cache_elems=5e4,
    )
    # slack(8) * steps(8) * (param + (1+tokens)*cache bytes)
    assert fwd["bytes_accessed"] == pytest.approx(8 * 8 * 2e6)
    assert fwd["flops"] > 8 * 8 * 2 * 2.5e5 * 4
    copy = program_cost_ceilings(
        "kv_adopt", cache_bytes=2e5, pool_bytes=3e5, cache_elems=5e4
    )
    # copy programs: bytes scale with the two buffers, flops ~allowance
    assert copy["bytes_accessed"] == pytest.approx(8 * 5e5)
    assert copy["flops"] < fwd["flops"]


# -- seeded violations on REAL compiled programs ----------------------------

@pytest.mark.fast
def test_dropped_donation_fires_on_real_executable():
    c = jnp.zeros((128,), jnp.float32)
    honored = _compile(lambda c: c * 2.0, c, donate=(0,)).as_text()
    dropped = _compile(lambda c: c * 2.0, c).as_text()
    assert input_output_alias_count(honored) == 1
    assert input_output_alias_count(dropped) == 0
    assert not _findings(honored, DonationRule(), expected_aliases=1)
    fs = _findings(dropped, DonationRule(), expected_aliases=1)
    assert fs and "donation dropped: 1 of 1" in fs[0].message


@pytest.mark.fast
def test_full_table_allgather_fires_on_real_executable():
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    table = jax.device_put(
        jnp.ones((256, 64), jnp.float32), NamedSharding(mesh, P("tp", None))
    )

    def regather(w):
        # the classic slip: force the sharded table replicated on-chip
        return jax.lax.with_sharding_constraint(
            w + 1.0, NamedSharding(mesh, P(None, None))
        )

    txt = _compile(regather, table).as_text()
    assert ("f32", (256, 64)) in gather_result_shapes(txt)
    fs = _findings(
        txt, CollectiveCensusRule(),
        policy=FamilyPolicy(
            forbidden_gather_dims=frozenset({(256, 64), (64, 256)})
        ),
    )
    assert fs and "reassembles a full sharded table 256x64" in fs[0].message


@pytest.mark.fast
def test_host_callback_fires_on_real_executable():
    def fn(x):
        return jax.pure_callback(
            lambda a: np.asarray(a) * 2.0,
            jax.ShapeDtypeStruct((4,), jnp.float32),
            x,
        )

    txt = _compile(fn, jnp.ones((4,), jnp.float32)).as_text()
    fs = _findings(txt, HostRoundTripRule())
    assert fs, custom_call_targets(txt)
    assert any("host-transfer custom-call" in f.message for f in fs)


@pytest.mark.fast
def test_f32_upcast_store_fires_on_real_executable():
    a = jnp.ones((8, 16), jnp.bfloat16)
    b = jnp.ones((16, 8), jnp.bfloat16)

    def upcast(a, b):
        return jnp.dot(a.astype(jnp.float32), b.astype(jnp.float32))

    def stays16(a, b):
        return jnp.dot(a, b)  # bf16 store (whatever the accumulator)

    pol = FamilyPolicy(forbid_f32_upcast_store=True)
    txt = _compile(upcast, a, b).as_text()
    fs = _findings(txt, DtypePolicyRule(), policy=pol)
    assert fs and "accumulate-and-store" in fs[0].message
    assert not _findings(
        _compile(stays16, a, b).as_text(), DtypePolicyRule(), policy=pol
    )


@pytest.mark.fast
def test_cost_budget_fires_on_real_executable():
    from dllama_tpu.obs.cost import extract_cost

    w = jnp.ones((128, 128), jnp.float32)
    compiled = _compile(lambda w: w @ w, w)
    cost = extract_cost(compiled)
    assert cost is not None and cost["flops"] > 0
    fs = _findings(
        compiled.as_text(), CostBudgetRule(), cost=cost,
        bytes_budget=1.0, flops_budget=1.0,
    )
    assert len(fs) == 2


@pytest.mark.fast
def test_xlalint_baseline_prune_helpers(tmp_path):
    bp = tmp_path / "xlalint-baseline.json"
    write_baseline_fingerprints(bp, ["r::p::gone", "r::p::alive"])
    baseline = load_baseline(bp)
    live = [HloFinding(rule="r", path="p", line=1, message="alive")]
    new, old, stale = apply_baseline(live, baseline)
    assert not new and len(old) == 1 and stale == {"r::p::gone"}
    write_baseline_fingerprints(bp, baseline - stale)
    assert json.loads(bp.read_text())["findings"] == ["r::p::alive"]


@pytest.mark.fast
def test_hlo_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "dllama_tpu.analysis", "--hlo",
         "--list-rules"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    for r in all_hlo_rules():
        assert r.name in proc.stdout


# -- clean-engine smoke (the CI gate, in-process) ---------------------------

@pytest.fixture(scope="module")
def tiny_engine(tmp_path_factory):
    from dllama_tpu.runtime.engine import InferenceEngine

    mp = str(tmp_path_factory.mktemp("xlalint") / "tiny.m")
    make_tiny_model(mp)
    eng = InferenceEngine(
        mp, dtype=jnp.float32, temperature=0.0, batch_size=2,
        prefill_buckets=(8,),
    )
    eng.init_kv_pool(page_size=8)
    eng.rehearse_admission(block_size=8, wait=True)
    return eng


@pytest.mark.fast
def test_clean_engine_zero_new_findings(tiny_engine):
    rep = tiny_engine.xlalint_report()
    assert rep["new_findings"] == [], rep["new_findings"]
    assert rep["n_programs"] >= 3  # prefill bucket + decode block + kv
    families = {p["family"] for p in rep["programs"]}
    assert {"prefill_lane", "decode_lanes", "kv_adopt", "kv_publish"} <= (
        families
    )
    # every AOT program carried a cost and a positive budget
    for p in rep["programs"]:
        assert p["bytes_budget"] > 0 and p["flops_budget"] > 0
        assert p["expected_aliases"] >= 1


@pytest.mark.fast
def test_engine_strict_mode_raises_through_compile_hook(tiny_engine):
    from dllama_tpu.analysis.xlalint import XlalintError

    class FakeExecutable:
        def as_text(self):
            # a lane program with NO input_output_alias: donation dropped
            return "HloModule broken\nENTRY %main { ROOT %r = f32[1]{0} parameter(0) }\n"

        def cost_analysis(self):
            return {"flops": 0.0, "bytes accessed": 0.0}

    key = ("lane_prefill", 999, 64)
    with tiny_engine._compile_lock:
        tiny_engine._compiled[key] = FakeExecutable()
    old_mode = tiny_engine._xlalint_mode
    try:
        tiny_engine._xlalint_mode = "strict"
        with pytest.raises(XlalintError, match="donation"):
            tiny_engine._xlalint_after_compile(key)
        # warn mode: same finding only logs (and counts) — no raise
        tiny_engine._xlalint_mode = "warn"
        tiny_engine._xlalint_after_compile(key)
        # off: hook is a no-op even on the broken program
        tiny_engine._xlalint_mode = "off"
        tiny_engine._xlalint_after_compile(key)
    finally:
        tiny_engine._xlalint_mode = old_mode
        with tiny_engine._compile_lock:
            del tiny_engine._compiled[key]
