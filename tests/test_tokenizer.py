"""Tokenizer / chat / EOS-detector / sampler tests.

EOS-detector cases are ports of the reference's tokenizer-test.cpp
(testEosDetectorWithPadding and friends); the rest follow the reference's
golden + roundtrip style.
"""

import numpy as np
import pytest

from dllama_tpu.tokenizer import (
    ChatItem,
    ChatTemplateGenerator,
    ChatTemplateType,
    EosDetector,
    EosResult,
    Tokenizer,
)
from dllama_tpu.runtime.sampler import Sampler, XorshiftRng, softmax

from helpers import make_tiny_tokenizer

# sub-minute CPU-only surface (codecs, tokenizer, native loader,
# interpret-mode kernel parity): the first CI lane runs `pytest -m fast`
pytestmark = pytest.mark.fast



@pytest.fixture()
def tok(tmp_path):
    data = make_tiny_tokenizer(str(tmp_path / "tok.t"))
    return Tokenizer(data)


# -- encode -------------------------------------------------------------------


def test_encode_merges_by_score(tok):
    # vocab has: he(1) ll(2) llo(3) hello(4) " wor"(5) " world"(6)...
    # byte-accumulate gives single bytes; merge loop should reach "hello"," world".
    ids = tok.encode("hello world", is_start=False, add_special_tokens=False)
    assert [tok.vocab[i] for i in ids] == [b"hello", b" world"]


def test_encode_bos(tok):
    ids = tok.encode("hi", is_start=True)
    assert ids[0] == tok.bos_id
    assert [tok.vocab[i] for i in ids[1:]] == [b"hi"]


def test_encode_special_tokens(tok):
    ids = tok.encode("<s>hi</s>", is_start=False, add_special_tokens=True)
    assert [tok.vocab[i] for i in ids] == [b"<s>", b"hi", b"</s>"]


def test_encode_special_disabled(tok):
    ids = tok.encode("<s>", is_start=False, add_special_tokens=False)
    # falls back to byte/merge path; no special id in result
    assert all(i < tok.regular_vocab_size for i in ids)


def test_encode_utf8_bytes(tok):
    text = "héllo 😃"
    ids = tok.encode(text, is_start=False, add_special_tokens=False)
    assert b"".join(tok.vocab[i] for i in ids) == text.encode("utf-8")


# -- decode -------------------------------------------------------------------


def test_decode_streaming_multibyte(tok):
    # 😃 = 4 bytes: stream one byte-token at a time; text must appear only
    # when the sequence completes (reference: dev_testDecoderEmoji).
    bs = "😃".encode("utf-8")
    tok.reset_decoder()
    outs = [tok.decode(b) for b in bs]
    assert outs[:-1] == [None, None, None]
    assert outs[-1] == "😃"


def test_decode_bos_eos(tok):
    assert tok.decode(tok.bos_id) is None
    assert tok.decode(tok.eos_token_ids[0]) is None  # nothing pending


def test_decode_eos_flushes_partial(tok):
    bs = "é".encode("utf-8")
    tok.reset_decoder()
    assert tok.decode(bs[0]) is None
    out = tok.decode(tok.eos_token_ids[0])
    assert out == "�"  # partial sequence recovered as replacement char


def test_decode_invalid_utf8_recovers(tok):
    tok.reset_decoder()
    out = tok.decode(0xFF)  # lone invalid byte
    assert out == "�"
    assert tok.decode(ord("Y")) == "Y"


def test_encode_decode_roundtrip(tok):
    text = "the world said héllo 😃!"
    ids = tok.encode(text, is_start=False, add_special_tokens=False)
    assert tok.decode_tokens(ids) == text


# -- chat templates -----------------------------------------------------------


def test_template_detection_llama3():
    jinja = "{% set content = '<|start_header_id|>' + role %}"
    g = ChatTemplateGenerator(ChatTemplateType.UNKNOWN, jinja, "<eos>")
    assert g.type == ChatTemplateType.LLAMA3


def test_template_detection_unknown_raises():
    with pytest.raises(ValueError):
        ChatTemplateGenerator(ChatTemplateType.UNKNOWN, "no markers here", "<eos>")


def test_template_llama3_render():
    g = ChatTemplateGenerator(ChatTemplateType.LLAMA3, None, "<|eot_id|>")
    out = g.generate(
        [ChatItem("system", "be nice"), ChatItem("user", "hi")],
        append_generation_prompt=True,
    )
    assert out.content == (
        "<|start_header_id|>system<|end_header_id|>\n\nbe nice<|eot_id|>"
        "<|start_header_id|>user<|end_header_id|>\n\nhi<|eot_id|>"
        "<|start_header_id|>assistant<|end_header_id|>\n\n"
    )


def test_template_llama2_system_fold():
    g = ChatTemplateGenerator(ChatTemplateType.LLAMA2, None, "</s>")
    out = g.generate(
        [ChatItem("system", "S"), ChatItem("user", "U")], append_generation_prompt=True
    )
    assert out.content == "[INST] <<SYS>>\nS\n<</SYS>>\n\nU [/INST]</s>"


def test_template_deepseek_public_prompt():
    g = ChatTemplateGenerator(ChatTemplateType.DEEP_SEEK3, None, "")
    out = g.generate([ChatItem("user", "hi")], append_generation_prompt=True)
    assert out.content.endswith("<｜Assistant｜><think>\n")
    assert out.public_prompt == "<think>\n"


# -- EOS detector (ported from tokenizer-test.cpp) ---------------------------

TEST_EOS_ID = 10000


def make_detector():
    return EosDetector(
        [TEST_EOS_ID, TEST_EOS_ID + 1], ["<eos>", "<stop>"], padding_left=1, padding_right=1
    )


def test_eos_exact_stop():
    d = make_detector()
    assert d.append(1, "<") == EosResult.MAYBE_EOS
    assert d.append(2, "eo") == EosResult.MAYBE_EOS
    assert d.append(3, "s>") == EosResult.EOS
    assert d.get_delta() is None


def test_eos_stop_with_trailing_space():
    d = make_detector()
    assert d.append(1, "<") == EosResult.MAYBE_EOS
    assert d.append(2, "stop") == EosResult.MAYBE_EOS
    assert d.append(3, "> ") == EosResult.EOS
    assert d.get_delta() is None


def test_eos_plain_text():
    d = make_detector()
    assert d.append(1, " ") == EosResult.NOT_EOS
    assert d.get_delta() == " "


def test_eos_with_left_padding():
    d = make_detector()
    assert d.append(1, "!<") == EosResult.MAYBE_EOS
    assert d.append(2, "eos") == EosResult.MAYBE_EOS
    assert d.append(3, "> ") == EosResult.EOS
    assert d.get_delta() == "!"


def test_eos_false_alarm():
    d = make_detector()
    assert d.append(1, "<eo") == EosResult.MAYBE_EOS
    assert d.append(2, "s>XY") == EosResult.NOT_EOS
    assert d.get_delta() == "<eos>XY"


def test_eos_token_id_flush():
    d = make_detector()
    assert d.append(1, "<eo") == EosResult.MAYBE_EOS
    assert d.append(TEST_EOS_ID, None) == EosResult.EOS
    assert d.get_delta() == "<eo"


def test_eos_token_id_empty():
    d = make_detector()
    assert d.append(TEST_EOS_ID, None) == EosResult.EOS
    assert d.get_delta() is None


def test_eos_reset_none_piece():
    d = make_detector()
    assert d.append(1, "x") == EosResult.NOT_EOS
    assert d.get_delta() == "x"
    d.reset()
    assert d.append(2, None) == EosResult.NOT_EOS
    assert d.get_delta() is None


def test_eos_long_padding():
    d = EosDetector([TEST_EOS_ID], ["|end|"], padding_left=5, padding_right=5)
    assert d.append(1, "lipsum") == EosResult.NOT_EOS
    assert d.get_delta() == "lipsum"
    d.reset()
    assert d.append(1, "lorem") == EosResult.NOT_EOS
    assert d.get_delta() == "lorem"


# -- sampler ------------------------------------------------------------------


def test_xorshift_known_sequence():
    # Deterministic across runs & implementations (u64 wraparound semantics).
    rng = XorshiftRng(12345)
    seq = [rng.random_u32() for _ in range(4)]
    rng2 = XorshiftRng(12345)
    assert [rng2.random_u32() for _ in range(4)] == seq
    assert all(0 <= v < 2**32 for v in seq)
    assert len(set(seq)) == 4


def test_sampler_greedy():
    s = Sampler(vocab_size=8, temperature=0.0, topp=0.9, seed=1)
    logits = np.array([0, 1, 5, 2, 0, 0, 0, 0], dtype=np.float32)
    assert s.sample(logits) == 2


def test_sampler_temperature_distribution():
    s = Sampler(vocab_size=4, temperature=1.0, topp=0.0, seed=42)
    logits = np.array([10.0, 0.0, 0.0, 0.0], dtype=np.float32)
    counts = [s.sample(logits) for _ in range(50)]
    assert counts.count(0) >= 48  # overwhelming mass on token 0


def test_sampler_topp_restricts_tail():
    s = Sampler(vocab_size=5, temperature=1.0, topp=0.5, seed=7)
    logits = np.array([5.0, 4.9, -10, -10, -10], dtype=np.float32)
    for _ in range(30):
        assert s.sample(logits.copy()) in (0, 1)


def test_softmax_normalized():
    p = softmax(np.array([1.0, 2.0, 3.0], dtype=np.float32))
    assert p.sum() == pytest.approx(1.0, abs=1e-6)
    assert p[2] > p[1] > p[0]
