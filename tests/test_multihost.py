"""Multi-host (DCN) bootstrap test: two real OS processes join through
`initialize_multihost` (the SPMD replacement for the reference's
root/worker TCP handshake, src/nn/nn-network.cpp:295-379) and run a
cross-process psum over a global mesh — the collective rides the
distributed runtime's data plane (Gloo on CPU; ICI/DCN on TPU pods),
exactly the path a v5e-16+ pod launch takes."""

import os
import socket
import subprocess
import sys

from helpers import REPO_ROOT

_WORKER = r"""
import sys
sys.path.insert(0, sys.argv[3])
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1])
from dllama_tpu.parallel.mesh import initialize_multihost
initialize_multihost(
    coordinator_address=f"127.0.0.1:{sys.argv[2]}", num_processes=2,
    process_id=pid,
)
import numpy as np
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()

mesh = Mesh(np.asarray(jax.devices()).reshape(2), ("tp",))
# each process contributes its own shard (value = pid + 1); the psum must
# see both shards -> 3.0 everywhere
garr = jax.make_array_from_single_device_arrays(
    (16,), NamedSharding(mesh, P("tp")),
    [jax.device_put(np.full(8, pid + 1.0, np.float32),
                    jax.local_devices()[0])],
)
out = jax.jit(
    shard_map(lambda a: jax.lax.psum(a, "tp"), mesh=mesh,
              in_specs=P("tp"), out_specs=P("tp"))
)(garr)
local = np.asarray(out.addressable_shards[0].data)
assert np.allclose(local, 3.0), local
print(f"proc {pid} psum ok", flush=True)
"""


def test_two_process_multihost_psum(tmp_path):
    port = _free_port()
    script = tmp_path / "mh_worker.py"
    script.write_text(_WORKER)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        # one local device per process (the conftest's 8-device flag would
        # otherwise leak in and give 16 global devices)
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    # the coordinator (process 0) must be up before/while 1 dials in;
    # launch both and join
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port), REPO_ROOT],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert "psum ok" in out, out


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
