"""Multi-host (DCN) bootstrap test: two real OS processes join through
`initialize_multihost` (the SPMD replacement for the reference's
root/worker TCP handshake, src/nn/nn-network.cpp:295-379) and run a
cross-process psum over a global mesh — the collective rides the
distributed runtime's data plane (Gloo on CPU; ICI/DCN on TPU pods),
exactly the path a v5e-16+ pod launch takes."""

import os
import socket
import subprocess
import sys

import pytest

from helpers import REPO_ROOT

# heavyweight end-to-end surface: run with the full suite / CI;
# deselect via -m 'not slow' for the fast local loop
pytestmark = pytest.mark.slow

_WORKER = r"""
import sys
sys.path.insert(0, sys.argv[3])
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1])
from dllama_tpu.parallel.mesh import initialize_multihost
initialize_multihost(
    coordinator_address=f"127.0.0.1:{sys.argv[2]}", num_processes=2,
    process_id=pid,
)
import numpy as np
import jax.numpy as jnp
from dllama_tpu.utils.compat import shard_map_compat as shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()

mesh = Mesh(np.asarray(jax.devices()).reshape(2), ("tp",))
# each process contributes its own shard (value = pid + 1); the psum must
# see both shards -> 3.0 everywhere
garr = jax.make_array_from_single_device_arrays(
    (16,), NamedSharding(mesh, P("tp")),
    [jax.device_put(np.full(8, pid + 1.0, np.float32),
                    jax.local_devices()[0])],
)
out = jax.jit(
    shard_map(lambda a: jax.lax.psum(a, "tp"), mesh=mesh,
              in_specs=P("tp"), out_specs=P("tp"))
)(garr)
local = np.asarray(out.addressable_shards[0].data)
assert np.allclose(local, 3.0), local
print(f"proc {pid} psum ok", flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_two_workers(tmp_path, worker_src, marker, extra_argv=(), timeout=300):
    """Launch the worker script as 2 coordinated processes and assert both
    exit 0 printing `marker`. On a per-process timeout, kills the stragglers
    and surfaces the output of EVERY process that already finished (a fast
    assert in one worker otherwise hangs its peer in a collective, and the
    bare TimeoutExpired would hide the root cause)."""
    port = _free_port()
    script = tmp_path / "mh_worker.py"
    script.write_text(worker_src)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        # one local device per process (the conftest's 8-device flag would
        # otherwise leak in and give 16 global devices)
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    # the coordinator (process 0) must be up before/while 1 dials in;
    # launch both and join
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port), REPO_ROOT,
             *extra_argv],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    timed_out = None
    for pid, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
        except subprocess.TimeoutExpired:
            timed_out = pid
            for q in procs:
                q.kill()
            out, _ = p.communicate()
            outs.append(out)
    if timed_out is not None:
        raise AssertionError(
            f"proc {timed_out} timed out after {timeout}s; collected "
            "outputs:\n"
            + "\n".join(f"--- proc {i} ---\n{o}" for i, o in enumerate(outs))
        )
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert marker in out, out


def _single_process_expected(n_steps=6, prompt=(1, 2, 3, 4, 5), fwd=None):
    """Greedy single-process token stream on the synthetic tiny model —
    the oracle every cross-process worker must reproduce."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dllama_tpu.models import forward, init_kv_cache
    from dllama_tpu.models.synthetic import make_header, random_params

    h = make_header("tiny")
    params = random_params(h, dtype=jnp.float32, seed=3)
    cache = init_kv_cache(h, 1)
    prompt = list(prompt)

    @jax.jit
    def step(params, tokens, cache, pos):
        logits, cache = forward(params, h, tokens, pos, cache)
        return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32), cache

    _, cache = step(
        params, jnp.asarray([prompt[:-1]], jnp.int32), cache, jnp.int32(0)
    )
    pos, tok, expected = len(prompt) - 1, prompt[-1], []
    for _ in range(n_steps):
        nxt, cache = step(
            params, jnp.asarray([[tok]], jnp.int32), cache, jnp.int32(pos)
        )
        tok = int(np.asarray(nxt)[0])
        pos += 1
        expected.append(tok)
    return expected


def test_two_process_multihost_psum(tmp_path):
    _run_two_workers(tmp_path, _WORKER, "psum ok", timeout=180)


# Full cross-process INFERENCE: the reference's worker path runs the whole
# model over the wire (src/app.cpp:306-365, nn-network.cpp:295-379); the
# SPMD analogue is a tp=2 global mesh spanning two OS processes, sharded
# params/KV cache built per-process from the same seed, and greedy decode
# whose all-reduces cross the process boundary on every layer. Token-exact
# parity with a single-process run is asserted in the parent.
_INFER_WORKER = r"""
import sys
sys.path.insert(0, sys.argv[3])
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1])
expected = [int(t) for t in sys.argv[4].split(",")]
from dllama_tpu.parallel.mesh import initialize_multihost, make_mesh
initialize_multihost(
    coordinator_address=f"127.0.0.1:{sys.argv[2]}", num_processes=2,
    process_id=pid,
)
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from dllama_tpu.models import forward, init_kv_cache
from dllama_tpu.models.synthetic import make_header, random_params
from dllama_tpu.parallel.sharding import cache_specs

assert jax.process_count() == 2 and jax.device_count() == 2
mesh = make_mesh(tp=2)
h = make_header("tiny")
# same seed on both processes -> identical global params, tp-sharded
params = random_params(h, dtype=jnp.float32, seed=3, mesh=mesh)
rep = NamedSharding(mesh, P())
cache_sh = {k: NamedSharding(mesh, v) for k, v in cache_specs(h).items()}
cache = jax.jit(
    lambda: init_kv_cache(h, 1), out_shardings=cache_sh
)()

def _fwd(params, tokens, cache, pos):
    logits, cache = forward(params, h, tokens, pos, cache, mesh=mesh)
    return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32), cache

step = jax.jit(_fwd, out_shardings=(rep, cache_sh))

def put_tokens(rows):
    arr = np.asarray(rows, np.int32)
    return jax.make_array_from_callback(arr.shape, rep, lambda idx: arr[idx])

prompt = [1, 2, 3, 4, 5]
_, cache = step(params, put_tokens([prompt[:-1]]), cache, jnp.int32(0))
pos, tok, outs = len(prompt) - 1, prompt[-1], []
for _ in range(len(expected)):
    nxt, cache = step(params, put_tokens([[tok]]), cache, jnp.int32(pos))
    tok = int(np.asarray(nxt.addressable_shards[0].data)[0])
    pos += 1
    outs.append(tok)
assert outs == expected, f"proc {pid}: {outs} != {expected}"
print(f"proc {pid} inference ok", flush=True)
"""


def test_two_process_inference_token_parity(tmp_path):
    """Prefill + 6 greedy decode steps on a tp=2 mesh spanning two OS
    processes must reproduce the single-process tokens exactly."""
    expected = _single_process_expected()
    _run_two_workers(
        tmp_path, _INFER_WORKER, "inference ok",
        extra_argv=[",".join(str(t) for t in expected)],
    )


# Pipeline stages SPANNING PROCESSES: the reference's cluster story is TP
# workers over TCP, capped at nNodes <= nKvHeads (src/app.cpp:236-240);
# pipeline stages have no such cap and their ppermute hand-offs are the
# smallest cross-node payload in the model — this pins that the pp
# schedule's collectives (activation ring + exit psum) really run over
# the distributed data plane (Gloo here; DCN on a pod), token-exact.
_PP_WORKER = r"""
import sys
sys.path.insert(0, sys.argv[3])
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1])
expected = [int(t) for t in sys.argv[4].split(",")]
from dllama_tpu.parallel.mesh import initialize_multihost, make_mesh
initialize_multihost(
    coordinator_address=f"127.0.0.1:{sys.argv[2]}", num_processes=2,
    process_id=pid,
)
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from dllama_tpu.models import init_kv_cache
from dllama_tpu.models.synthetic import make_header, random_params
from dllama_tpu.parallel.pipeline import forward_pp
from dllama_tpu.parallel.sharding import cache_specs

assert jax.process_count() == 2 and jax.device_count() == 2
mesh = make_mesh(pp=2)
h = make_header("tiny")
params = random_params(h, dtype=jnp.float32, seed=3, mesh=mesh)
rep = NamedSharding(mesh, P())
cache_sh = {
    k: NamedSharding(mesh, v) for k, v in cache_specs(h, pp=True).items()
}
cache = jax.jit(
    lambda: init_kv_cache(h, 1), out_shardings=cache_sh
)()

def _fwd(params, tokens, cache, pos):
    logits, cache = forward_pp(params, h, tokens, pos, cache, mesh)
    return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32), cache

step = jax.jit(_fwd, out_shardings=(rep, cache_sh))

def put_tokens(rows):
    arr = np.asarray(rows, np.int32)
    return jax.make_array_from_callback(arr.shape, rep, lambda idx: arr[idx])

prompt = [1, 2, 3, 4, 5]
_, cache = step(params, put_tokens([prompt[:-1]]), cache, jnp.int32(0))
pos, tok, outs = len(prompt) - 1, prompt[-1], []
for _ in range(len(expected)):
    nxt, cache = step(params, put_tokens([[tok]]), cache, jnp.int32(pos))
    tok = int(np.asarray(nxt.addressable_shards[0].data)[0])
    pos += 1
    outs.append(tok)
assert outs == expected, f"proc {pid}: {outs} != {expected}"
print(f"proc {pid} pp inference ok", flush=True)
"""


def test_two_process_pipeline_token_parity(tmp_path):
    """Greedy decode over pp=2 stages living in DIFFERENT OS processes
    must reproduce the single-process tokens exactly (stage hand-offs +
    exit psum over the distributed data plane)."""
    expected = _single_process_expected()
    _run_two_workers(
        tmp_path, _PP_WORKER, "pp inference ok",
        extra_argv=[",".join(str(t) for t in expected)],
    )
