"""dlint fixture: trace-purity must stay quiet — effects happen outside
the traced function; in-trace debugging uses the sanctioned tools."""
import time
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def step(x):
    jax.debug.print("x = {}", x)  # sanctioned in-trace output
    return x * 2


def dispatch(x):
    t0 = time.monotonic()  # fine: host code, not traced
    y = step(x)
    return y, time.monotonic() - t0
