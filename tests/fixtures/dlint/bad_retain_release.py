"""dlint fixture: retain-release MUST fire here (leaked alloc on an early
return, and a retain exposed to a risky device call with no protection)."""


class Manager:
    def leaky_match(self, tokens):
        pages = self.pool.alloc(2)
        if not tokens:
            return 0  # BAD: `pages` leaks on this path
        self.pool.release(pages)
        return len(pages)

    def unprotected_publish(self, lane, pages):
        self.pool.retain(pages)
        self.engine.kv_publish(lane, pages)  # BAD: raise here leaks the retain
        self.pool.release(pages)
