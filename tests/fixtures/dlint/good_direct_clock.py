"""dlint fixture: direct-clock must stay quiet — the bare reference as a
default is the injection point, and all reads go through it."""
import time


class Window:
    def __init__(self, clock=time.monotonic, wall_clock=time.time):
        self._clock = clock
        self._t0 = clock()
        self.epoch_unix = wall_clock()

    def elapsed(self):
        return self._clock() - self._t0
