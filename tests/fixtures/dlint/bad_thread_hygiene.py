"""dlint fixture: thread-hygiene MUST fire here (anonymous, non-daemon,
fire-and-forget, and a stored thread with no stop path)."""
import threading


def fire_and_forget(work):
    threading.Thread(target=work).start()  # BAD: all three violations


class Looper:
    def __init__(self):
        # BAD: stored but Looper has no stop/close/shutdown/join method
        self.thread = threading.Thread(
            target=self._run, daemon=True, name="dllama-loop"
        )

    def _run(self):
        pass
