"""dlint fixture: thread-hygiene must stay quiet — named + daemonized +
joined (directly, via list iteration, or via a class stop path)."""
import threading


def run_workers(work, n):
    threads = [
        threading.Thread(
            target=work, daemon=True, name=f"dllama-worker-{i}"
        )
        for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


class Looper:
    def __init__(self):
        self.thread = threading.Thread(
            target=self._run, daemon=True, name="dllama-loop"
        )

    def _run(self):
        pass

    def stop(self):
        self.thread.join(timeout=1.0)
