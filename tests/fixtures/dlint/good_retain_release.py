"""dlint fixture: retain-release must stay quiet here — every exit path
releases, transfers ownership, or returns the pages to the caller."""


class Manager:
    def balanced_match(self, lane, tokens):
        pages = self.pool.alloc(2)
        if not tokens:
            self.pool.release(pages)
            return 0
        self._lane_pages[lane] = pages  # ownership transfer: lane map owns it
        return len(pages)

    def protected_publish(self, lane, pages):
        self.pool.retain(pages)
        try:
            self.engine.kv_publish(lane, pages)  # protected by finally
        finally:
            self.pool.release(pages)

    def handed_to_caller(self, n):
        pages = self.pool.alloc(n)
        return pages  # caller owns the refcount now

    def stored_in_tree(self, tokens, n):
        pages = self.pool.alloc(n)
        self.tree.insert(tokens, pages, 0)  # tree owns it now
