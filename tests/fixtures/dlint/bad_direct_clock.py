"""dlint fixture: direct-clock MUST fire here (module takes clock= but a
code path reads the real clock anyway)."""
import time


class Window:
    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._t0 = clock()

    def elapsed(self):
        return time.monotonic() - self._t0  # BAD: bypasses the injected clock
