"""dlint fixture: guarded-attrs must stay quiet here (every access locked,
plus the sanctioned conventions: __init__, *_locked helpers, suppression)."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def peek(self):
        with self._lock:
            return self._n

    def _drain_locked(self):
        # caller holds self._lock (project suffix convention)
        return self._n

    def peek_racy(self):
        return self._n  # dlint: disable=guarded-attrs — monitoring read; a stale value is fine
