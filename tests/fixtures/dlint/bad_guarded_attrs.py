"""dlint fixture: guarded-attrs MUST fire here (unlocked read/write of a
lock-guarded attribute). Never imported; parsed by tests/test_analysis.py."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._log = []

    def bump(self):
        with self._lock:
            self._n += 1
            self._log.append(self._n)

    def peek(self):
        return self._n  # BAD: guarded read without the lock

    def clobber(self):
        self._n = 0  # BAD: guarded write without the lock
