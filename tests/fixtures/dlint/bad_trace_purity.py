"""dlint fixture: trace-purity MUST fire here (host side effects inside a
jitted function, including one reached transitively)."""
import time
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def step(x):
    t0 = time.monotonic()  # BAD: clock read burns into the trace
    print("tracing", t0)   # BAD: prints once at trace time
    return helper(x)


def helper(x):
    # transitively traced via step(); still impure
    t = time.perf_counter()  # BAD
    return x * t
