"""obs/ unit tests: metrics registry rendering, lifecycle spans, JSONL
tracing, and the telemetry Counter migration. Pure-Python (no engine), so
they ride the fast CI lane."""

import json
import threading

import pytest

from dllama_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS_S,
    MetricsRegistry,
)
from dllama_tpu.obs.trace import NULL_SPAN, Tracer, read_jsonl

pytestmark = pytest.mark.fast


# -- metrics registry --------------------------------------------------------


def test_counter_gauge_basic():
    reg = MetricsRegistry()
    c = reg.counter("t_requests_total", "requests")
    c.inc()
    c.inc(2)
    assert c.value == 3
    g = reg.gauge("t_depth", "queue depth")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value == 4


def test_registration_idempotent_and_type_checked():
    reg = MetricsRegistry()
    a = reg.counter("t_x_total")
    b = reg.counter("t_x_total")
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("t_x_total")


def test_histogram_buckets_cumulative():
    reg = MetricsRegistry()
    h = reg.histogram("t_lat_seconds", "latency", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 2.0, 100.0):  # le is inclusive: 0.1 -> first
        h.observe(v)
    text = reg.render()
    assert 't_lat_seconds_bucket{le="0.1"} 2' in text
    assert 't_lat_seconds_bucket{le="1"} 3' in text
    assert 't_lat_seconds_bucket{le="10"} 4' in text
    assert 't_lat_seconds_bucket{le="+Inf"} 5' in text
    assert "t_lat_seconds_count 5" in text
    assert f"t_lat_seconds_sum {0.05 + 0.1 + 0.5 + 2.0 + 100.0}" in text


def test_labeled_families_and_escaping():
    reg = MetricsRegistry()
    c = reg.counter("t_by_path_total", 'paths with "quotes"', labelnames=("path",))
    c.labels(path="/v1/chat").inc()
    c.labels(path='we"ird\npath').inc(2)
    with pytest.raises(ValueError):
        c.labels(wrong="x")
    with pytest.raises(ValueError):
        c.inc()  # labeled family has no default child
    text = reg.render()
    assert '# HELP t_by_path_total paths with "quotes"' in text
    assert 't_by_path_total{path="/v1/chat"} 1' in text
    assert 't_by_path_total{path="we\\"ird\\npath"} 2' in text


def test_render_prometheus_text_format_shape():
    """Every family renders a HELP+TYPE header and every sample line is
    `name{labels} value` — the subset of the 0.0.4 exposition format a
    stock Prometheus scraper requires."""
    reg = MetricsRegistry()
    reg.counter("t_a_total", "a").inc()
    reg.gauge("t_b", "b").set(1.5)
    reg.histogram("t_c_seconds", "c").observe(0.2)
    lines = reg.render().splitlines()
    assert lines, "empty render"
    names = set()
    for line in lines:
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            names.add(line.split()[2])
            continue
        name, value = line.rsplit(" ", 1)
        float(value)  # parses as a number
        base = name.split("{")[0]
        base = base.removesuffix("_bucket").removesuffix("_sum")
        base = base.removesuffix("_count")
        assert base in names, line  # samples follow their family header
    assert len(DEFAULT_LATENCY_BUCKETS_S) + 1 == sum(
        1 for line in lines if line.startswith("t_c_seconds_bucket")
    )


def test_disabled_registry_is_noop():
    reg = MetricsRegistry(enabled=False)
    c = reg.counter("t_n_total")
    h = reg.histogram("t_n_seconds")
    c.inc()
    h.observe(1.0)
    assert c.value == 0 and h.count == 0
    reg.enable()
    c.inc()
    assert c.value == 1


def test_registry_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter("t_mt_total")
    h = reg.histogram("t_mt_seconds", buckets=(1.0,))

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.5)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000


# -- spans + tracer ----------------------------------------------------------


def test_span_lifecycle_and_record():
    tr = Tracer(capacity=4)
    span = tr.span(path="lanes")
    qw = span.mark_admitted(lane=2, reused_prefix_tokens=7)
    assert qw >= 0 and span.lane == 2
    span.set_prefill_seconds(0.25)
    ttft = span.mark_first_token()
    assert ttft is not None and ttft >= qw
    assert span.mark_first_token() is None  # single-shot
    rec = span.finish("stop", n_prompt=11, n_completion=3)
    assert span.finish("length") is None  # idempotent: first reason wins
    assert rec["finish_reason"] == "stop" and rec["cancelled"] is False
    assert rec["reused_prefix_tokens"] == 7
    assert rec["n_prompt_tokens"] == 11 and rec["n_completion"] == 3
    assert rec["prefill_s"] == 0.25
    assert rec["queue_wait_s"] is not None and rec["ttft_s"] is not None
    assert tr.records() == [rec]
    assert span.ttft_ms == pytest.approx(rec["ttft_s"] * 1000)


def test_span_cancelled_flag():
    tr = Tracer()
    span = tr.span()
    span.mark_admitted()
    rec = span.finish("cancelled", n_completion=2)
    assert rec["cancelled"] is True
    # TTFT never happened: recorded honestly as null
    assert rec["ttft_s"] is None


def test_null_span_is_inert():
    tr_len_before = NULL_SPAN.finish("stop")
    assert tr_len_before is None
    assert NULL_SPAN.mark_admitted(lane=1) == 0.0
    assert NULL_SPAN.mark_first_token() is None


def test_tracer_ring_bound_and_jsonl(tmp_path):
    sink = str(tmp_path / "trace.jsonl")
    tr = Tracer(capacity=3, sink_path=sink)
    for i in range(5):
        span = tr.span(request_id=f"r{i}")
        span.mark_admitted()
        span.finish("stop", n_prompt=1, n_completion=i)
    assert [r["request_id"] for r in tr.records()] == ["r2", "r3", "r4"]
    tr.close()
    # the sink kept ALL records (the ring only bounds memory)
    recs = read_jsonl(sink)
    assert [r["request_id"] for r in recs] == [f"r{i}" for i in range(5)]
    assert all(json.dumps(r) for r in recs)  # each line round-trips
    # export dumps the current ring
    out = str(tmp_path / "export.jsonl")
    assert tr.export(out) == 3
    assert len(read_jsonl(out)) == 3


def test_telemetry_counter_on_registry():
    from dllama_tpu.obs.metrics import get_registry
    from dllama_tpu.utils.telemetry import Counter

    c = Counter("t_decode")
    c.add(10.0, n=2)
    c.add(5.0)
    assert c.n == 3 and c.total_ms == 15.0
    assert c.rate == pytest.approx(3 * 1000.0 / 15.0)
    reg = get_registry()
    assert reg.counter("dllama_t_decode_events_total").value == 3
    assert reg.counter("dllama_t_decode_ms_total").value == 15.0
    # anonymous counters keep the old purely-local behavior
    anon = Counter()
    anon.add(1.0)
    assert anon.n == 1
