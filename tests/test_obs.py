"""obs/ unit tests: metrics registry rendering, lifecycle spans, JSONL
tracing, and the telemetry Counter migration. Pure-Python (no engine), so
they ride the fast CI lane."""

import json
import threading

import pytest

from dllama_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS_S,
    MetricsRegistry,
)
from dllama_tpu.obs.trace import NULL_SPAN, Tracer, read_jsonl

pytestmark = pytest.mark.fast


# -- metrics registry --------------------------------------------------------


def test_counter_gauge_basic():
    reg = MetricsRegistry()
    c = reg.counter("t_requests_total", "requests")
    c.inc()
    c.inc(2)
    assert c.value == 3
    g = reg.gauge("t_depth", "queue depth")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value == 4


def test_registration_idempotent_and_type_checked():
    reg = MetricsRegistry()
    a = reg.counter("t_x_total")
    b = reg.counter("t_x_total")
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("t_x_total")


def test_histogram_buckets_cumulative():
    reg = MetricsRegistry()
    h = reg.histogram("t_lat_seconds", "latency", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 2.0, 100.0):  # le is inclusive: 0.1 -> first
        h.observe(v)
    text = reg.render()
    assert 't_lat_seconds_bucket{le="0.1"} 2' in text
    assert 't_lat_seconds_bucket{le="1"} 3' in text
    assert 't_lat_seconds_bucket{le="10"} 4' in text
    assert 't_lat_seconds_bucket{le="+Inf"} 5' in text
    assert "t_lat_seconds_count 5" in text
    assert f"t_lat_seconds_sum {0.05 + 0.1 + 0.5 + 2.0 + 100.0}" in text


def test_labeled_families_and_escaping():
    reg = MetricsRegistry()
    c = reg.counter("t_by_path_total", 'paths with "quotes"', labelnames=("path",))
    c.labels(path="/v1/chat").inc()
    c.labels(path='we"ird\npath').inc(2)
    with pytest.raises(ValueError):
        c.labels(wrong="x")
    with pytest.raises(ValueError):
        c.inc()  # labeled family has no default child
    text = reg.render()
    assert '# HELP t_by_path_total paths with "quotes"' in text
    assert 't_by_path_total{path="/v1/chat"} 1' in text
    assert 't_by_path_total{path="we\\"ird\\npath"} 2' in text


def test_render_prometheus_text_format_shape():
    """Every family renders a HELP+TYPE header and every sample line is
    `name{labels} value` — the subset of the 0.0.4 exposition format a
    stock Prometheus scraper requires."""
    reg = MetricsRegistry()
    reg.counter("t_a_total", "a").inc()
    reg.gauge("t_b", "b").set(1.5)
    reg.histogram("t_c_seconds", "c").observe(0.2)
    lines = reg.render().splitlines()
    assert lines, "empty render"
    names = set()
    for line in lines:
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            names.add(line.split()[2])
            continue
        name, value = line.rsplit(" ", 1)
        float(value)  # parses as a number
        base = name.split("{")[0]
        base = base.removesuffix("_bucket").removesuffix("_sum")
        base = base.removesuffix("_count")
        assert base in names, line  # samples follow their family header
    assert len(DEFAULT_LATENCY_BUCKETS_S) + 1 == sum(
        1 for line in lines if line.startswith("t_c_seconds_bucket")
    )


def test_disabled_registry_is_noop():
    reg = MetricsRegistry(enabled=False)
    c = reg.counter("t_n_total")
    h = reg.histogram("t_n_seconds")
    c.inc()
    h.observe(1.0)
    assert c.value == 0 and h.count == 0
    reg.enable()
    c.inc()
    assert c.value == 1


def test_registry_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter("t_mt_total")
    h = reg.histogram("t_mt_seconds", buckets=(1.0,))

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.5)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000


# -- spans + tracer ----------------------------------------------------------


def test_span_lifecycle_and_record():
    tr = Tracer(capacity=4)
    span = tr.span(path="lanes")
    qw = span.mark_admitted(lane=2, reused_prefix_tokens=7)
    assert qw >= 0 and span.lane == 2
    span.set_prefill_seconds(0.25)
    ttft = span.mark_first_token()
    assert ttft is not None and ttft >= qw
    assert span.mark_first_token() is None  # single-shot
    rec = span.finish("stop", n_prompt=11, n_completion=3)
    assert span.finish("length") is None  # idempotent: first reason wins
    assert rec["finish_reason"] == "stop" and rec["cancelled"] is False
    assert rec["reused_prefix_tokens"] == 7
    assert rec["n_prompt_tokens"] == 11 and rec["n_completion"] == 3
    assert rec["prefill_s"] == 0.25
    assert rec["queue_wait_s"] is not None and rec["ttft_s"] is not None
    assert tr.records() == [rec]
    assert span.ttft_ms == pytest.approx(rec["ttft_s"] * 1000)


def test_span_cancelled_flag():
    tr = Tracer()
    span = tr.span()
    span.mark_admitted()
    rec = span.finish("cancelled", n_completion=2)
    assert rec["cancelled"] is True
    # TTFT never happened: recorded honestly as null
    assert rec["ttft_s"] is None


def test_null_span_is_inert():
    tr_len_before = NULL_SPAN.finish("stop")
    assert tr_len_before is None
    assert NULL_SPAN.mark_admitted(lane=1) == 0.0
    assert NULL_SPAN.mark_first_token() is None


def test_tracer_ring_bound_and_jsonl(tmp_path):
    sink = str(tmp_path / "trace.jsonl")
    tr = Tracer(capacity=3, sink_path=sink)
    for i in range(5):
        span = tr.span(request_id=f"r{i}")
        span.mark_admitted()
        span.finish("stop", n_prompt=1, n_completion=i)
    assert [r["request_id"] for r in tr.records()] == ["r2", "r3", "r4"]
    tr.close()
    # the sink kept ALL records (the ring only bounds memory)
    recs = read_jsonl(sink)
    assert [r["request_id"] for r in recs] == [f"r{i}" for i in range(5)]
    assert all(json.dumps(r) for r in recs)  # each line round-trips
    # export dumps the current ring
    out = str(tmp_path / "export.jsonl")
    assert tr.export(out) == 3
    assert len(read_jsonl(out)) == 3


def test_telemetry_counter_on_registry():
    from dllama_tpu.obs.metrics import get_registry
    from dllama_tpu.utils.telemetry import Counter

    c = Counter("t_decode")
    c.add(10.0, n=2)
    c.add(5.0)
    assert c.n == 3 and c.total_ms == 15.0
    assert c.rate == pytest.approx(3 * 1000.0 / 15.0)
    reg = get_registry()
    assert reg.counter("dllama_t_decode_events_total").value == 3
    assert reg.counter("dllama_t_decode_ms_total").value == 15.0
    # anonymous counters keep the old purely-local behavior
    anon = Counter()
    anon.add(1.0)
    assert anon.n == 1


# -- flight recorder ---------------------------------------------------------


def test_recorder_ring_overflow_keeps_newest():
    from dllama_tpu.obs.recorder import FlightRecorder

    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("step_dispatch", i=i)
    evs = rec.events()
    assert len(evs) == 4
    assert [e["i"] for e in evs] == [6, 7, 8, 9]  # oldest fell off
    assert [e["seq"] for e in evs] == [7, 8, 9, 10]  # lifetime index survives
    assert rec.total_recorded == 10
    d = rec.dump()
    assert d["n_events"] == 4 and d["total_recorded"] == 10
    assert d["dropped"] == 6 and d["capacity"] == 4
    assert json.loads(rec.dump_json())["n_events"] == 4
    assert rec.events(kind="nope") == []
    rec.clear()
    assert rec.events() == []
    assert rec.total_recorded == 10  # clear drops events, not the ledger


def test_recorder_thread_safety():
    from dllama_tpu.obs.recorder import FlightRecorder

    rec = FlightRecorder(capacity=128)

    def work(tid):
        for i in range(500):
            rec.record("e", tid=tid, i=i)

    threads = [threading.Thread(target=work, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert rec.total_recorded == 4000
    evs = rec.events()
    assert len(evs) == 128
    seqs = [e["seq"] for e in evs]
    assert len(set(seqs)) == 128 and max(seqs) == 4000
    assert seqs == sorted(seqs)  # ring preserves recording order


def test_recorder_disabled_is_noop():
    from dllama_tpu.obs.recorder import FlightRecorder

    rec = FlightRecorder(capacity=4, enabled=False)
    rec.record("e")
    assert rec.events() == [] and rec.total_recorded == 0
    rec.enable()
    rec.record("e")
    assert rec.total_recorded == 1
    rec.disable()
    rec.record("e")
    assert rec.total_recorded == 1


def test_recorder_postmortem_dump(tmp_path):
    from dllama_tpu.obs.recorder import FlightRecorder

    rec = FlightRecorder(capacity=16, postmortem_dir=str(tmp_path / "pm"))
    rec.record("step_dispatch", step="decode_block", pos=7)
    path = rec.postmortem("engine-step", RuntimeError("kaboom"))
    assert path is not None
    with open(path) as f:
        payload = json.load(f)
    assert payload["reason"] == "engine-step"
    assert payload["error"] == "kaboom"
    assert payload["error_type"] == "RuntimeError"
    kinds = [e["kind"] for e in payload["events"]]
    assert kinds == ["step_dispatch", "postmortem"]  # ring + the marker
    # a second postmortem gets a distinct file
    path2 = rec.postmortem("scheduler-loop", "plain string error")
    assert path2 is not None and path2 != path
    with open(path2) as f:
        p2 = json.load(f)
    assert p2["error"] == "plain string error" and p2["error_type"] is None


def test_recorder_postmortem_never_raises(tmp_path):
    from dllama_tpu.obs.recorder import FlightRecorder

    # no dir configured -> None, events still recorded
    rec = FlightRecorder(capacity=4)
    assert rec.postmortem("x", RuntimeError("e")) is None
    assert rec.events(kind="postmortem")
    # dir path blocked by a plain file -> swallowed, None returned
    blocker = tmp_path / "blocked"
    blocker.write_text("not a directory")
    rec.postmortem_dir = str(blocker)
    assert rec.postmortem("x", RuntimeError("e")) is None


def test_get_recorder_is_process_singleton():
    from dllama_tpu.obs.recorder import get_recorder

    assert get_recorder() is get_recorder()


# -- cost analysis + roofline ------------------------------------------------


class _FakeCompiled:
    def __init__(self, ca):
        self._ca = ca

    def cost_analysis(self):
        if isinstance(self._ca, Exception):
            raise self._ca
        return self._ca


def test_extract_cost_shapes():
    from dllama_tpu.obs.cost import extract_cost

    assert extract_cost(object()) is None  # lazily jitted fn: no surface
    assert extract_cost(_FakeCompiled(RuntimeError("no"))) is None
    assert extract_cost(_FakeCompiled(None)) is None
    assert extract_cost(_FakeCompiled([])) is None
    assert extract_cost(_FakeCompiled([{}])) is None
    # jax has shipped both one-dict-per-module lists and bare dicts
    got = extract_cost(
        _FakeCompiled([{"flops": 10.0, "bytes accessed": 20.0}])
    )
    assert got == {"flops": 10.0, "bytes_accessed": 20.0}
    got = extract_cost(_FakeCompiled({"flops": 3.0}))
    assert got == {"flops": 3.0, "bytes_accessed": 0.0}


def test_extract_cost_real_aot_executable():
    """The integration the /v1/debug/compile endpoint rides on: a real
    AOT-compiled executable reports non-empty cost analysis on CPU."""
    import jax
    import jax.numpy as jnp

    from dllama_tpu.obs.cost import extract_cost

    x = jnp.ones((8, 8), jnp.float32)
    compiled = jax.jit(lambda a, b: a @ b).lower(x, x).compile()
    cost = extract_cost(compiled)
    assert cost is not None and cost["flops"] > 0


def test_roofline_fraction():
    from dllama_tpu.obs.cost import roofline_fraction

    assert roofline_fraction(1e9, 0.002, 819e9) == pytest.approx(
        (1e9 / 0.002) / 819e9
    )
    assert roofline_fraction(1e9, 0.002, None) is None
    assert roofline_fraction(1e9, 0.0, 819e9) is None
    assert roofline_fraction(0.0, 0.002, 819e9) is None


def test_weight_bytes_per_token_formats():
    from types import SimpleNamespace

    from dllama_tpu.obs.cost import weight_bytes_per_token

    h = SimpleNamespace(dim=4, q_dim=4, kv_dim=2, ff_dim=8, n_layers=1,
                        vocab_size=10, n_experts=0, n_active_experts=0)
    att = 4 * 4 + 2 * 4 * 2 + 4 * 4     # 48
    ffn = 3 * 4 * 8                      # 96
    base = att + ffn + 4 * 10            # + embed/cls read
    assert weight_bytes_per_token(h, "q40") == int(base * 1.125)
    assert weight_bytes_per_token(h, "bf16") == base * 2
    assert weight_bytes_per_token(h, "q40i4") == int(base * 0.5625)
    assert weight_bytes_per_token(h, "q40i8", i8_group=64) == int(
        base * (1 + 4 / 64)
    )


def test_roofline_report_degrades_without_tpu():
    """On the CPU test backend the HBM peak is unknown: every derived
    figure is an explicit None, never a made-up fraction."""
    from types import SimpleNamespace

    from dllama_tpu.obs.cost import (
        hbm_peak_bytes_per_s,
        print_roofline_report,
        roofline_report,
    )

    assert hbm_peak_bytes_per_s() is None
    h = SimpleNamespace(dim=64, q_dim=64, kv_dim=32, ff_dim=160, n_layers=2,
                        vocab_size=288, n_experts=0, n_active_experts=0)
    rep = roofline_report(h, "q40", tp=2)
    assert rep["weight_bytes_per_token_per_chip"] > 0
    assert rep["hbm_peak_bytes_per_s"] is None
    assert rep["min_ms_per_token"] is None
    assert rep["max_tok_s_per_chip"] is None
    # tp*pp shards the weight reads
    assert rep["weight_bytes_per_token_per_chip"] == pytest.approx(
        roofline_report(h, "q40")["weight_bytes_per_token_per_chip"] // 2,
        abs=1,
    )
    assert print_roofline_report(h, "q40", tp=2) == rep  # prints, returns same


# -- device memory telemetry -------------------------------------------------


def test_device_memory_stats_shape():
    import jax

    from dllama_tpu.obs.device import device_memory_stats

    stats = device_memory_stats()
    assert len(stats) == len(jax.devices())
    for s in stats:
        assert set(s) >= {"device", "platform", "available"}
        if s["available"]:
            assert s["bytes_in_use"] >= 0 and s["bytes_limit"] >= 0
        else:
            assert "bytes_in_use" not in s  # no fabricated zeros


def test_sample_device_memory_registers_gauges():
    from dllama_tpu.obs.device import sample_device_memory

    reg = MetricsRegistry()
    stats = sample_device_memory(reg)
    text = reg.render()
    for fam in ("dllama_device_bytes_in_use",
                "dllama_device_peak_bytes_in_use",
                "dllama_device_bytes_limit"):
        assert f"# TYPE {fam} gauge" in text
    for s in stats:
        if s["available"]:  # TPU run: the gauge really carries the sample
            assert f'dllama_device_bytes_in_use{{device="{s["device"]}"}}' \
                in text


def test_compare_with_analytic_divergence(caplog):
    import logging

    from dllama_tpu.obs.device import compare_with_analytic

    ok = [{"device": "d0", "platform": "tpu", "available": True,
           "bytes_in_use": 105, "peak_bytes_in_use": 110, "bytes_limit": 200}]
    with caplog.at_level(logging.WARNING, logger="dllama_tpu.obs.device"):
        cmp_ok = compare_with_analytic(100, stats=ok)
    assert cmp_ok["available"] is True
    assert cmp_ok["max_divergence_fraction"] == pytest.approx(0.05)
    assert not caplog.records  # within tolerance: silent

    bad = [dict(ok[0], bytes_in_use=130)]
    with caplog.at_level(logging.WARNING, logger="dllama_tpu.obs.device"):
        cmp_bad = compare_with_analytic(100, stats=bad)
    assert cmp_bad["max_divergence_fraction"] == pytest.approx(0.30)
    assert any("diverges" in r.message for r in caplog.records)


def test_compare_with_analytic_unavailable():
    from dllama_tpu.obs.device import compare_with_analytic

    none_avail = [{"device": "cpu:0", "platform": "cpu", "available": False}]
    cmp_ = compare_with_analytic(100, stats=none_avail)
    assert cmp_["available"] is False
    assert cmp_["max_divergence_fraction"] is None and cmp_["per_chip"] == []
    assert compare_with_analytic(0, stats=[])["available"] is False


# -- telemetry hardening + consistency ---------------------------------------


def test_profile_survives_start_trace_failure(monkeypatch, caplog):
    import logging

    import jax

    from dllama_tpu.utils import telemetry

    def bad_start(d):
        raise RuntimeError("profiler already active")

    monkeypatch.setattr(jax.profiler, "start_trace", bad_start)
    ran = False
    with caplog.at_level(logging.WARNING, logger="dllama_tpu.utils.telemetry"):
        with telemetry.profile("/tmp/nowhere"):
            ran = True  # the profiled body still runs
    assert ran
    assert any("start_trace" in r.message for r in caplog.records)


def test_profile_survives_stop_trace_failure(monkeypatch, caplog):
    import logging

    import jax

    from dllama_tpu.utils import telemetry

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)

    def bad_stop():
        raise RuntimeError("trace collection died")

    monkeypatch.setattr(jax.profiler, "stop_trace", bad_stop)
    with caplog.at_level(logging.WARNING, logger="dllama_tpu.utils.telemetry"):
        with telemetry.profile("/tmp/nowhere"):
            pass
    assert any("stop_trace" in r.message for r in caplog.records)


def test_profile_noop_without_log_dir(monkeypatch):
    import jax

    from dllama_tpu.utils import telemetry

    def explode(*a):
        raise AssertionError("profiler must not be touched")

    monkeypatch.setattr(jax.profiler, "start_trace", explode)
    with telemetry.profile(None):
        pass
    with telemetry.profile(""):
        pass


def test_replicated_keys_match_param_spec_tree():
    """Pin telemetry's replication list to the sharding layout it models:
    the keys memory_report treats as whole-on-every-chip must be exactly
    the P() leaves of parallel/sharding.param_spec_tree across all
    arches. A sharding change that replicates or splits a new leaf must
    touch both files (this test is the tripwire)."""
    from types import SimpleNamespace

    from jax.sharding import PartitionSpec as P

    from dllama_tpu.formats.model_file import LlmArch
    from dllama_tpu.parallel.sharding import param_spec_tree
    from dllama_tpu.utils.telemetry import _REPLICATED_KEYS

    replicated = set()
    for arch in (LlmArch.LLAMA, LlmArch.QWEN3, LlmArch.QWEN3_MOE):
        spec = param_spec_tree(SimpleNamespace(arch=arch))
        layers = spec.pop("layers")
        for scope in (spec, layers):
            for key, leaf_spec in scope.items():
                if leaf_spec == P():
                    replicated.add(key)
    assert replicated == _REPLICATED_KEYS


# -- histogram percentile (watchdog stall thresholds ride on this) -----------


def test_histogram_percentile_interpolation():
    reg = MetricsRegistry()
    h = reg.histogram("t_pct_seconds", "pct", buckets=(1.0, 2.0, 4.0))
    assert h.percentile(0.5) is None  # no observations yet
    for v in (0.5, 0.5, 0.5, 0.5, 1.5, 1.5, 1.5, 1.5, 3.0, 3.0):
        h.observe(v)
    # 10 samples: 4 in (0,1], 4 in (1,2], 2 in (2,4]; linear interpolation
    # within the landing bucket, first bucket's lower edge is 0.0
    assert h.percentile(0.0) == 0.0
    assert h.percentile(0.4) == 1.0  # exactly exhausts the first bucket
    assert h.percentile(0.5) == pytest.approx(1.25)
    assert h.percentile(0.8) == pytest.approx(2.0)
    assert h.percentile(0.9) == pytest.approx(3.0)
    assert h.percentile(1.0) == 4.0
    with pytest.raises(ValueError):
        h.percentile(1.5)
    with pytest.raises(ValueError):
        h.percentile(-0.1)


def test_histogram_percentile_edge_buckets():
    reg = MetricsRegistry()
    h = reg.histogram("t_pct_edge_seconds", "pct", buckets=(1.0, 2.0, 4.0))
    h.observe(1.5)
    h.observe(1.5)
    # target 0 lands in the empty first bucket -> its upper edge
    assert h.percentile(0.0) == 1.0
    assert h.percentile(1.0) == 2.0
    # overflow-only data clamps to the largest finite edge (the +Inf
    # bucket has no finite upper bound to interpolate toward)
    h2 = reg.histogram("t_pct_inf_seconds", "pct", buckets=(1.0, 2.0, 4.0))
    h2.observe(100.0)
    assert h2.percentile(0.5) == 4.0


def test_histogram_percentile_labeled_child():
    reg = MetricsRegistry()
    fam = reg.histogram(
        "t_pct_lbl_seconds", "pct", labelnames=("kind",), buckets=(1.0, 2.0)
    )
    fam.labels(kind="decode").observe(0.5)
    # one sample in (0,1]: p100 interpolates to the bucket's upper edge
    assert fam.labels(kind="decode").percentile(1.0) == 1.0
    assert fam.labels(kind="decode").percentile(0.5) == pytest.approx(0.5)
    assert fam.labels(kind="other").percentile(0.5) is None


# -- tracer serialization fallback + sink-error event ------------------------


def test_tracer_sink_survives_nonserializable_attrs(tmp_path):
    sink = str(tmp_path / "trace.jsonl")
    tr = Tracer(capacity=4, sink_path=sink)
    tr.record({"request_id": "r1", "err": ValueError("boom")})
    tr.close()
    (rec,) = read_jsonl(sink)
    assert rec["request_id"] == "r1"
    assert rec["err"] == repr(ValueError("boom"))  # degraded, not dropped


def test_tracer_export_survives_nonserializable_attrs(tmp_path):
    tr = Tracer(capacity=4)
    tr.record({"request_id": "r1", "obj": object()})
    out = str(tmp_path / "export.jsonl")
    assert tr.export(out) == 1
    (rec,) = read_jsonl(out)
    assert rec["obj"].startswith("<object object")


def test_dumps_safe_circular_structure():
    from dllama_tpu.obs.trace import _dumps_safe

    d = {"request_id": "r1"}
    d["self"] = d  # json.dumps raises ValueError even with default=repr
    rec = json.loads(_dumps_safe(d))
    assert "_unserializable" in rec


def test_tracer_sink_write_error_records_event(tmp_path):
    from dllama_tpu.obs.recorder import get_recorder

    sink = str(tmp_path / "trace.jsonl")
    tr = Tracer(capacity=4, sink_path=sink)
    tr._sink.close()  # simulate the fd dying under the tracer
    before = len(get_recorder().events("obs_sink_error"))
    tr.record({"request_id": "r1"})
    evs = get_recorder().events("obs_sink_error")
    assert len(evs) == before + 1
    assert evs[-1]["what"] == "trace_jsonl"
    assert evs[-1]["path"] == sink
    assert evs[-1]["error_type"] == "ValueError"
    # the sink is dropped, the ring keeps serving, no second event
    assert tr._sink is None
    tr.record({"request_id": "r2"})
    assert len(get_recorder().events("obs_sink_error")) == before + 1
    assert [r["request_id"] for r in tr.records()] == ["r1", "r2"]
