"""Grouped-int8 matmul (ops/int8_matmul.py): the MXU-native restatement
of the reference's Q80-activation x Q40-weight integer dot
(src/nn/nn-cpu-ops.cpp:231-449). Pins (a) the requantization error stays
in the Q40 noise floor, (b) the Pallas kernel (interpret mode) matches
the exact-integer reference path, (c) shape/validation edges."""

import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.formats.quants import q40_to_planar, quantize_q40
from dllama_tpu.ops import quant_matmul as qm
from dllama_tpu.ops.int8_matmul import (

    Int8Weight,
    i8matmul,
    i8matmul_2d,
    i8matmul_ref,
    quantize_acts,
    requantize_q40,
)

# sub-minute CPU-only surface (codecs, tokenizer, native loader,
# interpret-mode kernel parity): the first CI lane runs `pytest -m fast`
pytestmark = pytest.mark.fast


def _q40(rng, k, n, scale=0.1):
    w = (rng.standard_normal((n, k)) * scale).astype(np.float32)
    qv, dv = q40_to_planar(quantize_q40(w), n * k)
    return qm.from_planar(qv.reshape(n, k), dv.reshape(n, k // 32)), w


def test_requantize_error_within_q40_noise():
    """int8-per-512 requantization of a Q40 tensor must add error small
    relative to what Q40 quantization itself already carries."""
    rng = np.random.default_rng(7)
    k, n = 1024, 256
    w, dense_true = _q40(rng, k, n)
    dense_q40 = np.asarray(qm.dequant(w, jnp.float32))  # [k, n]
    w8 = requantize_q40(w, group=512)
    assert w8.group == 512
    dense_i8 = np.asarray(w8.q, np.float32) * np.repeat(
        np.asarray(w8.s), 512, axis=0
    )
    q40_err = np.abs(dense_q40 - dense_true.T).max()
    i8_err = np.abs(dense_i8 - dense_q40).max()
    assert i8_err < q40_err, (i8_err, q40_err)


def test_i8matmul_ref_close_to_f32():
    rng = np.random.default_rng(11)
    k, n = 2048, 512
    w, dense_true = _q40(rng, k, n)
    x = jnp.asarray(rng.standard_normal((3, k)).astype(np.float32))
    w8 = requantize_q40(w, group=256)
    got = np.asarray(i8matmul_ref(x, w8))
    want = np.asarray(qm.qmatmul_ref(x, w))
    scale = np.abs(want).max()
    err = np.abs(got - want).max()
    assert err / scale < 2e-2, (err, scale)


@pytest.mark.parametrize("group,block_k", [(256, 1024), (512, 512), (1024, 2048)])
def test_kernel_matches_ref(group, block_k):
    """Pallas kernel in interpret mode == exact-integer reference path
    (same int math; only fp summation order differs)."""
    rng = np.random.default_rng(3)
    m, k, n = 4, 2048, 512
    w, _ = _q40(rng, k, n)
    w8 = requantize_q40(w, group=group)
    x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32))
    xq, sx = quantize_acts(x, group)
    got = np.asarray(
        i8matmul_2d(xq, sx, w8.q, w8.s, block_n=256, block_k=block_k,
                    interpret=True)
    )
    want = np.asarray(i8matmul_ref(x, w8))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_kernel_awkward_k_block_alignment():
    """k=11264 (22*512), group=512, block_k=4096: naive group-rounding of
    the preferred block gives 2560, which does NOT divide k — the block
    search must fall back to a group multiple that does (ADVICE r4)."""
    rng = np.random.default_rng(11)
    m, k, n, group = 2, 11264, 256, 512
    w, _ = _q40(rng, k, n)
    w8 = requantize_q40(w, group=group)
    x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32))
    xq, sx = quantize_acts(x, group)
    got = np.asarray(
        i8matmul_2d(xq, sx, w8.q, w8.s, block_n=256, block_k=4096,
                    interpret=True)
    )
    want = np.asarray(i8matmul_ref(x, w8))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_i8matmul_leading_dims():
    rng = np.random.default_rng(5)
    k, n = 512, 256
    w, _ = _q40(rng, k, n)
    w8 = requantize_q40(w, group=256)
    x = jnp.asarray(rng.standard_normal((2, 3, k)).astype(np.float32))
    out = i8matmul(x, w8)  # off-TPU: ref path
    assert out.shape == (2, 3, n)
    flat = i8matmul_ref(x.reshape(6, k), w8).reshape(2, 3, n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(flat), rtol=1e-6)


def test_requantize_stacked_layers():
    """Stacked [L, k, n] tensors (the lax.scan layout) requantize
    layerwise-identically to per-layer calls."""
    rng = np.random.default_rng(9)
    k, n = 256, 128
    w0, _ = _q40(rng, k, n)
    w1, _ = _q40(rng, k, n)
    stacked = qm.QuantWeight(
        jnp.stack([w0.q, w1.q]), jnp.stack([w0.d, w1.d])
    )
    w8s = requantize_q40(stacked, group=128)
    w80 = requantize_q40(w0, group=128)
    np.testing.assert_array_equal(np.asarray(w8s.q[0]), np.asarray(w80.q))
    np.testing.assert_allclose(np.asarray(w8s.s[0]), np.asarray(w80.s))


def test_group_divisibility_validation():
    rng = np.random.default_rng(1)
    w, _ = _q40(rng, 256, 128)
    with pytest.raises(ValueError):
        requantize_q40(w, group=192)
    with pytest.raises(ValueError):
        quantize_acts(jnp.ones((2, 256)), 192)


def test_zero_columns_safe():
    """All-zero groups must not divide by zero (scale floors to 1)."""
    q = jnp.zeros((256, 128), jnp.int8)
    d = jnp.zeros((8, 128), jnp.float32)
    w8 = requantize_q40(qm.QuantWeight(q, d), group=128)
    out = i8matmul_ref(jnp.ones((1, 256)), w8)
    np.testing.assert_array_equal(np.asarray(out), 0.0)


# -- engine integration (weight_format="q40i8") ---------------------------

CFG_I8 = dict(dim=64, hidden_dim=256, n_layers=2, n_heads=8, n_kv_heads=4,
              head_dim=16, vocab_size=288, seq_len=64)


def _engine(tmp_path, **kw):
    from dllama_tpu.formats import FloatType
    from dllama_tpu.runtime.engine import InferenceEngine

    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    from helpers import make_tiny_model

    mp = str(tmp_path / "m8.m")
    make_tiny_model(mp, weight_type=FloatType.Q40, seed=13, cfg=CFG_I8)
    return InferenceEngine(mp, dtype=jnp.float32, temperature=0.0, **kw)


def test_engine_q40i8_params_converted(tmp_path):
    """q40i8 load produces Int8Weight leaves (fused wrappers included)
    and a picked group recorded on the engine."""
    from dllama_tpu.ops.quant_matmul import FusedQuantWeight

    e = _engine(tmp_path, tp=1, weight_format="q40i8")
    assert e.i8_group >= 32
    lp = e.params["layers"]
    assert isinstance(lp["wqkv"], FusedQuantWeight)
    assert isinstance(lp["wqkv"].weight, Int8Weight)
    assert isinstance(lp["w2"], Int8Weight)
    assert isinstance(e.params["wcls"], Int8Weight)


def test_engine_q40i8_tp_token_parity(tmp_path):
    """q40i8 greedy decode: tp=2 must reproduce the tp=1 token stream
    (same int8 params, collectives change only the summation layout)."""
    e1 = _engine(tmp_path, tp=1, weight_format="q40i8")
    out1, _, _ = e1.generate([5, 6, 7], max_steps=12)
    del e1
    e2 = _engine(tmp_path, tp=2, weight_format="q40i8")
    out2, _, _ = e2.generate([5, 6, 7], max_steps=12)
    assert out1 == out2


def test_engine_q40i8_perplexity_close_to_q40(tmp_path):
    """Requantization must stay in the Q40 noise floor end-to-end: the
    teacher-forced NLL of the int8 engine tracks the q40 engine's."""
    toks = [(i * 11) % 250 + 1 for i in range(40)]
    eq = _engine(tmp_path, tp=1, weight_format="q40")
    nll_q, _, _ = eq.perplexity(toks)
    del eq
    e8 = _engine(tmp_path, tp=1, weight_format="q40i8")
    nll_8, _, _ = e8.perplexity(toks)
    assert abs(nll_8 - nll_q) / abs(nll_q) < 0.02, (nll_8, nll_q)


def test_engine_q40i8_moe_keeps_expert_q40(tmp_path):
    """MoE checkpoints: experts stay Q40 (the ragged kernels' format);
    attention/wcls convert; the engine still generates."""
    from dllama_tpu.formats import FloatType
    from dllama_tpu.formats.model_file import LlmArch
    from dllama_tpu.ops.quant_matmul import QuantWeight
    from dllama_tpu.runtime.engine import InferenceEngine

    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    from helpers import make_tiny_model

    mp = str(tmp_path / "moe8.m")
    make_tiny_model(mp, arch=LlmArch.QWEN3_MOE, weight_type=FloatType.Q40,
                    seed=3)
    e = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0,
                        weight_format="q40i8")
    lp = e.params["layers"]
    assert isinstance(lp["w1"], QuantWeight)  # experts untouched
    assert isinstance(lp["wqkv"].weight, Int8Weight)
    out, _, _ = e.generate([1, 2, 3], max_steps=8)
    assert len(out) == 6  # max_steps - (prompt_len - 1)


def test_engine_q40i8_pp_and_sp_parity(tmp_path):
    """q40i8 composes with pipeline stages (Int8Weight leaves ride the
    per-name pp x tp specs — q and s are both rank-3, so the same
    PartitionSpec applies) and with sequence parallelism; token streams
    match the q40i8 single-device run."""
    e1 = _engine(tmp_path, tp=1, weight_format="q40i8")
    expected, _, _ = e1.generate([5, 6, 7], max_steps=12)
    del e1
    for kw in (dict(pp=2), dict(sp=2), dict(pp=2, tp=2)):
        e = _engine(tmp_path, weight_format="q40i8", **kw)
        got, _, _ = e.generate([5, 6, 7], max_steps=12)
        del e
        assert got == expected, (kw, got, expected)
