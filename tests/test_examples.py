"""The shipped example scripts must keep working (VERDICT r1 weak #8: the
reference's CI runs its test binaries; here the examples are the
end-to-end CLI path, so they run on a tiny model in CI too)."""

import os
import subprocess

import pytest

from helpers import REPO_ROOT, make_tiny_model, make_tiny_tokenizer

# heavyweight end-to-end surface: run with the full suite / CI;
# deselect via -m 'not slow' for the fast local loop
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tiny_pair(tmp_path_factory):
    d = tmp_path_factory.mktemp("examples")
    # pad the vocab so tp=4 divides it (validate_tp mirrors the
    # reference's shardability constraints)
    tok = make_tiny_tokenizer(str(d / "tok.t"), pad_to=288)
    # seq_len must cover the Macbeth prompt (~79 byte-level tokens) plus
    # decode room: --steps is an absolute position cap, so steps beyond
    # the prompt length are what actually generate
    make_tiny_model(
        str(d / "m.m"),
        cfg=dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
                 head_dim=16, vocab_size=len(tok.vocab), seq_len=128),
    )
    return str(d / "m.m"), str(d / "tok.t")


def _env(**extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(extra)
    return env


def test_macbeth_determinism(tiny_pair):
    """Greedy long-generation twice -> byte-identical (the reference's
    examples/macbeth.sh check, on the tiny model)."""
    mp, tp = tiny_pair
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "examples", "macbeth.sh"),
         mp, tp, "120"],
        capture_output=True, text=True, timeout=600, env=_env(),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "deterministic" in r.stdout, r.stdout


def test_n_chips_cli(tiny_pair):
    """examples/n-chips.sh runs the real CLI over a 4-virtual-chip mesh."""
    mp, tp = tiny_pair
    r = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "examples", "n-chips.sh"),
         "4", mp, tp],
        capture_output=True, text=True, timeout=600,
        env=_env(XLA_FLAGS="--xla_force_host_platform_device_count=4"),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Tp: 4" in r.stdout, r.stdout


def test_chat_context_exhaustion_stops_explicitly(tiny_pair):
    """When the context window fills, the chat REPL must print an explicit
    stop and exit instead of silently generating nothing forever
    (reference behavior: src/dllama.cpp:242-253; VERDICT r2 weak #7)."""
    mp, tp = tiny_pair
    # seq_len 128: a few user turns exhaust it (each turn re-encodes the
    # chat template around the message and then decodes until EOS/stop)
    msgs = "\n".join(["tell me more about it please"] * 12) + "\n"
    r = subprocess.run(
        ["python", "-m", "dllama_tpu", "chat", "--model", mp,
         "--tokenizer", tp, "--temperature", "0.0", "--max-seq-len", "128",
         "--chat-template", "llama3"],
        input=msgs, capture_output=True, text=True, timeout=900,
        env=_env(PYTHONPATH=REPO_ROOT + os.pathsep
                 + os.environ.get("PYTHONPATH", "")),
        cwd=REPO_ROOT,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Context window full" in r.stdout, r.stdout[-2000:]
