"""Pool-native paged decode (PR 16): byte-parity + oversubscription.

The slab path (tests/test_kv_pool.py) moves published prefixes between
the lane slab and the pool with copy programs; pool-native mode makes
the pool the *only* KV storage — lanes decode through a per-lane page
table — so adoption is refcount bookkeeping and the only device copy
left is the COW fork of a mid-page boundary. These tests pin the two
invariants that make that safe to ship:

* **byte parity** — seeded streams decoded through the page table are
  token-identical to the slab engine (f32 and int8 pools, fresh and
  adopted prefixes, spec-on and spec-off);
* **zero-copy adoption** — a full-page adopt moves no bytes
  (`dllama_kv_copy_bytes_total` unchanged), a mid-page adopt forks
  exactly one page.

The server-level test drives oversubscription (`--max-streams` 2x the
lane count) and checks park -> resume returns byte-identical output.
"""

import json
import re
import threading
import urllib.request

import jax.numpy as jnp
import pytest

from dllama_tpu.formats import FloatType
from dllama_tpu.kv.manager import PagedKVManager
from dllama_tpu.runtime.api_server import serve
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.tokenizer import Tokenizer

from helpers import make_tiny_model, make_tiny_tokenizer

CFG = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=4, n_kv_heads=2,
           head_dim=16, vocab_size=256, seq_len=64)


@pytest.fixture(scope="module")
def tiny_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("kvnative")
    mp = str(d / "m.m")
    make_tiny_model(mp, cfg=CFG)
    return mp


def _stream(e, lane, token, pos, steps, seed):
    """Seeded single-lane decode stream (other lane parked): per-lane
    (seed, position) keys make it depend on nothing else."""
    toks, t, p = [], token, pos
    active = [i == lane for i in range(e.batch_size)]
    while len(toks) < steps:
        n = min(4, steps - len(toks))
        rows = e.decode_lanes(
            [t if i == lane else 0 for i in range(e.batch_size)],
            [p if i == lane else 0 for i in range(e.batch_size)],
            n, active,
            [0.8] * e.batch_size, [0.9] * e.batch_size,
            seeds=[seed if i == lane else None for i in range(e.batch_size)],
        )
        toks.extend(r[lane] for r in rows)
        t, p = toks[-1], p + n
    return toks


# -- engine level ------------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", [
    pytest.param(None, marks=pytest.mark.fast),
    "int8",
])
def test_native_decode_parity(tiny_model, kv_dtype):
    """Decoding through the page table (lane_block_paged) is
    token-identical to the slab engine, f32 and QuantKV int8 pools."""
    kw = {"kv_dtype": kv_dtype} if kv_dtype else {}
    prompt = [2 + (i * 7) % 250 for i in range(23)]

    e = InferenceEngine(
        tiny_model, tp=1, dtype=jnp.float32, temperature=0.8,
        batch_size=2, **kw,
    )
    e.prefill_lane(1, prompt, pos0=0)
    expected = _stream(e, 1, prompt[-1], len(prompt) - 1, 10, seed=42)

    e2 = InferenceEngine(
        tiny_model, tp=1, dtype=jnp.float32, temperature=0.8,
        batch_size=2, **kw,
    )
    e2.init_kv_pool(4, native=True)
    nb = e2._kv_n_blocks
    e2.adopt_pages(1, list(range(1, nb + 1)))  # page 0 is the null page
    e2.prefill_lane(1, prompt, pos0=0)
    got = _stream(e2, 1, prompt[-1], len(prompt) - 1, 10, seed=42)
    assert got == expected


@pytest.mark.fast
def test_manager_native_zero_copy_and_cow(tiny_model):
    """Manager-level native flow: a full-page adopt moves ZERO device
    bytes (page-table writes + refcounts only), shared pages serve two
    lanes byte-identically, and a mid-page adopt forks exactly the
    boundary page (COW) before diverging."""
    prompt = [2 + (i * 7) % 250 for i in range(23)]

    e_ref = InferenceEngine(
        tiny_model, tp=1, dtype=jnp.float32, temperature=0.8, batch_size=2,
    )
    e_ref.prefill_lane(1, prompt, pos0=0)
    expected = _stream(e_ref, 1, prompt[-1], len(prompt) - 1, 10, seed=42)

    e = InferenceEngine(
        tiny_model, tp=1, dtype=jnp.float32, temperature=0.8, batch_size=2,
    )
    kv = PagedKVManager(e, page_size=4, native=True)
    m, pages = kv.match(0, prompt)
    assert (m, pages) == (0, [])
    kv.adopt(0, pages)  # native: allocates the lane's private page list
    e.prefill_lane(0, prompt, pos0=0)
    first = _stream(e, 0, prompt[-1], len(prompt) - 1, 10, seed=42)
    assert first == expected
    history = prompt + first
    assert kv.publish(0, history[:20]) == 5  # 5 full pages, page-aligned
    kv.release_lane(0)
    kv.check()

    # full-page adopt into the OTHER lane: zero copy bytes
    bytes0 = e._m_kv_copy_bytes.value
    m, pages = kv.match(1, prompt)
    assert m == 20
    kv.adopt(1, pages)
    assert e._m_kv_copy_bytes.value == bytes0, (
        "full-page adopt must copy zero bytes"
    )
    fills, cur = prompt[:-1], m
    while cur < len(fills):
        cur += e.prefill_lane_chunk(1, fills[cur:], cur, budget=8)
    got = _stream(e, 1, prompt[-1], len(prompt) - 1, 10, seed=42)
    assert got == expected
    # lane 1 publishes one more page over the 5 shared slots (dedup)
    h1 = prompt + got
    assert kv.publish(1, h1[:24]) == 1
    kv.release_lane(1)
    kv.check()

    # mid-page boundary: share 22 of the stored 24 tokens, then diverge
    p2 = prompt[:22] + [199, 198, 197]
    e_ref.reset()
    e_ref.prefill_lane(0, p2, pos0=0)
    exp2 = _stream(e_ref, 0, p2[-1], len(p2) - 1, 8, seed=9)

    m, pages = kv.match(0, p2)
    assert m == 22 and m % 4 != 0  # boundary falls mid-page
    kv.adopt(0, pages)
    assert e._m_kv_copy_bytes.value > bytes0, (
        "mid-page adopt must fork the boundary page"
    )
    fills, cur = p2[:-1], m
    while cur < len(fills):
        cur += e.prefill_lane_chunk(0, fills[cur:], cur, budget=8)
    got2 = _stream(e, 0, p2[-1], len(p2) - 1, 8, seed=9)
    assert got2 == exp2
    kv.release_lane(0)
    kv.check()


# -- server level: oversubscription ------------------------------------------

SRV_CFG = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
               head_dim=16, vocab_size=288, seq_len=384)
PROMPTS = [f"hello number {i} tell me a story" for i in range(4)]


@pytest.fixture(scope="module")
def native_server(tmp_path_factory):
    """2-lane pool-native server admitting up to 4 streams, with n-gram
    speculation on (greedy lanes verify drafts through the paged verify
    programs; a park resume rebuilds the lane's drafter)."""
    d = tmp_path_factory.mktemp("oversub")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=SRV_CFG)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=2,
    )
    srv = serve(
        engine, tok, host="127.0.0.1", port=0,
        kv_page_size=4, kv_native=True, max_streams=4, speculation="ngram",
    )
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", srv, (mp, tp_)
    srv.shutdown()


def _chat(url, content, max_tokens=40):
    payload = {
        "model": "m", "stream": False, "max_tokens": max_tokens,
        "temperature": 0,
        "messages": [{"role": "user", "content": content}],
    }
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=600) as r:
        data = json.loads(r.read())
    choice = data["choices"][0]
    assert choice["finish_reason"] in ("stop", "length")
    return choice["message"]["content"]


def _metric(url, name):
    with urllib.request.urlopen(url + "/metrics") as r:
        metrics = r.read().decode()
    m = re.search(rf"^{name}(?:\{{[^}}]*\}})? ([0-9.e+-]+)$", metrics, re.M)
    return float(m.group(1)) if m else None


def test_oversubscription_park_resume_parity(native_server):
    """4 concurrent greedy streams on 2 lanes: every stream completes,
    at least one got parked and resumed, and each stream's bytes match
    its uncontended (solo) run exactly."""
    url, srv, _ = native_server
    solo = [_chat(url, p) for p in PROMPTS]  # one at a time: no parking
    assert _metric(url, "dllama_stream_resumes_total") == 0

    results = [None] * len(PROMPTS)

    def run(i):
        results[i] = _chat(url, PROMPTS[i])

    threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)

    assert results == solo, "park -> resume changed stream bytes"
    assert _metric(url, "dllama_stream_resumes_total") > 0, (
        "oversubscribed run never parked a stream"
    )
    assert _metric(url, "dllama_streams_parked") == 0  # all drained
    sched = srv.state.scheduler
    assert sched._n_parked == 0 and not sched.pending
    srv.state.kv_manager.check()


def test_native_spec_off_parity(native_server, tmp_path_factory):
    """Speculative decoding through the paged verify programs is
    lossless: a spec-off pool-native server emits the identical
    bytes."""
    url, _, (mp, tp_) = native_server
    spec_on = _chat(url, "speculation parity probe", max_tokens=24)

    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=2,
    )
    srv2 = serve(
        engine, tok, host="127.0.0.1", port=0,
        kv_page_size=4, kv_native=True, max_streams=4, speculation="off",
    )
    threading.Thread(target=srv2.serve_forever, daemon=True).start()
    url2 = f"http://127.0.0.1:{srv2.server_address[1]}"
    try:
        spec_off = _chat(url2, "speculation parity probe", max_tokens=24)
    finally:
        srv2.shutdown()
    assert spec_on == spec_off
