"""Converter end-to-end tests: HF checkpoint -> convert-hf.py -> `.m` ->
dllama_tpu forward, validated against the HF transformers forward itself.

This is the strongest correctness oracle in the suite: it proves the whole
chain (tensor plan, q/k permutation, quantization, loader transposes, RoPE
convention, GQA, qk-norm, MoE routing) against an independent production
implementation.
"""

import importlib.util
import json
import sys

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from dllama_tpu.formats import FloatType, ModelReader
from dllama_tpu.models import forward, init_kv_cache, load_params
from dllama_tpu.tokenizer import Tokenizer


def _load_script(name: str):
    path = f"/root/repo/converter/{name}"
    spec = importlib.util.spec_from_file_location(name.replace("-", "_").replace(".py", ""), path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


TOKENS = [3, 17, 92, 5, 44, 120, 7]


def _convert_and_compare(tmp_path, hf_model, float_type, atol):
    src = tmp_path / "hf"
    hf_model.save_pretrained(src, safe_serialization=True)
    conv = _load_script("convert-hf.py")
    out = str(tmp_path / "model.m")
    conv.convert(str(src), float_type, out)

    reader = ModelReader(out)
    params = load_params(reader)
    h = reader.header
    cache = init_kv_cache(h, batch_size=1)
    logits, _ = forward(
        params, h, jnp.asarray([TOKENS], dtype=jnp.int32), jnp.int32(0), cache
    )
    got = np.asarray(logits)[0]

    with torch.no_grad():
        expected = (
            hf_model(torch.tensor([TOKENS])).logits[0].to(torch.float32).numpy()
        )
    np.testing.assert_allclose(got, expected, rtol=atol, atol=atol)
    return reader


def test_convert_hf_llama_matches_transformers(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=160,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        vocab_size=256,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        hidden_act="silu",
    )
    model = LlamaForCausalLM(config).eval()
    reader = _convert_and_compare(tmp_path, model, FloatType.F32, 2e-3)
    assert reader.header.arch.name == "LLAMA"


def test_convert_hf_qwen3_matches_transformers(tmp_path):
    from transformers import Qwen3Config, Qwen3ForCausalLM

    torch.manual_seed(1)
    config = Qwen3Config(
        hidden_size=64,
        intermediate_size=160,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        vocab_size=256,
        max_position_embeddings=64,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        hidden_act="silu",
    )
    model = Qwen3ForCausalLM(config).eval()
    reader = _convert_and_compare(tmp_path, model, FloatType.F32, 2e-3)
    assert reader.header.arch.name == "QWEN3"


def test_convert_hf_qwen3_moe_matches_transformers(tmp_path):
    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM

    torch.manual_seed(2)
    config = Qwen3MoeConfig(
        hidden_size=64,
        intermediate_size=160,
        moe_intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        vocab_size=256,
        max_position_embeddings=64,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        hidden_act="silu",
        num_experts=4,
        num_experts_per_tok=2,
        norm_topk_prob=True,
        decoder_sparse_step=1,
        mlp_only_layers=[],
    )
    model = Qwen3MoeForCausalLM(config).eval()
    reader = _convert_and_compare(tmp_path, model, FloatType.F32, 2e-3)
    assert reader.header.arch.name == "QWEN3_MOE"
    assert reader.header.n_experts == 4


def test_convert_hf_q40_close(tmp_path):
    """Q40 conversion end-to-end: quality should track the f32 logits."""
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(3)
    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=160,
        num_hidden_layers=1,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        vocab_size=256,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    model = LlamaForCausalLM(config).eval()
    src = tmp_path / "hf"
    model.save_pretrained(src, safe_serialization=True)
    conv = _load_script("convert-hf.py")
    out = str(tmp_path / "model.m")
    conv.convert(str(src), FloatType.Q40, out)
    reader = ModelReader(out)
    params = load_params(reader)
    cache = init_kv_cache(reader.header, batch_size=1)
    logits, _ = forward(
        params, reader.header, jnp.asarray([TOKENS], dtype=jnp.int32), jnp.int32(0), cache
    )
    with torch.no_grad():
        expected = model(torch.tensor([TOKENS])).logits[0].numpy()
    got = np.asarray(logits)[0]
    corr = np.corrcoef(got.reshape(-1), expected.reshape(-1))[0, 1]
    assert corr > 0.98  # 4-bit weights on a random tiny model


def test_convert_tokenizer_hf_parity(tmp_path, monkeypatch):
    """Byte-level BPE tokenizer conversion: encodings through the `.t` path
    must match the HF fast tokenizer on plain text."""
    from tokenizers import Tokenizer as HfTokenizer, models, pre_tokenizers, decoders, trainers
    from transformers import PreTrainedTokenizerFast

    # train a tiny byte-level BPE in-process
    tok = HfTokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=400,
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    corpus = ["hello world", "the quick brown fox", "hello there world"] * 50
    tok.train_from_iterator(corpus, trainer)
    # specials appended AFTER the regular vocab: the `.t` format assumes the
    # regular/special split sits at bos_id (same constraint as the
    # reference, src/tokenizer.cpp:138-140)
    tok.add_special_tokens(["<s>", "</s>"])
    bos_id = tok.token_to_id("<s>")
    eos_id = tok.token_to_id("</s>")
    src = tmp_path / "tok"
    src.mkdir()
    tok.save(str(src / "tokenizer.json"))
    (src / "tokenizer_config.json").write_text(json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "add_bos_token": False,
    }))
    (src / "config.json").write_text(json.dumps({
        "bos_token_id": bos_id, "eos_token_id": eos_id,
    }))

    conv = _load_script("convert-tokenizer-hf.py")
    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(sys, "argv", ["convert-tokenizer-hf.py", str(src), "test"])
    conv.main()

    mine = Tokenizer(str(tmp_path / "dllama_tokenizer_test.t"))
    hf = PreTrainedTokenizerFast(tokenizer_file=str(src / "tokenizer.json"))
    for text in ["hello world", "the quick brown fox world", "heworldllo"]:
        expected = hf.encode(text)
        got = mine.encode(text, is_start=False, add_special_tokens=False)
        assert got == expected, f"{text!r}: {got} != {expected}"
        assert mine.decode_tokens(got) == text
