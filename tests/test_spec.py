"""Model-free speculative decoding on the lane path (ISSUE 10).

Prompt-lookup speculation must be invisible in the output: greedy
streams with speculation ON are byte-identical to speculation OFF,
because the scheduler only ever emits tokens the batched verify pass
itself argmax'd. These tests pin the contract points:

* drafter mechanics — the n-gram index proposes the continuation of the
  most recent EARLIER occurrence of the current suffix, and the adaptive
  k backs off (halve + cooldown) on low acceptance;
* engine verify parity — one `verify_lanes` dispatch accepts exactly the
  prefix a step-by-step greedy decode would produce, and a rejected
  draft's rewind leaves the lane's KV able to continue byte-identically;
* scheduler parity — spec-on vs spec-off greedy SSE streams match, also
  when a temperature>0 lane joins the batch mid-stream (per-lane
  fallback shares the dispatch group);
* pool composition — a finish after rejected-draft rewinds publishes
  only valid rows, so a follow-up request reuses the prefix AND streams
  the same bytes;
* knobs — --speculation/--spec-k resolution (explicit > env > default)
  and `off` as a pure bypass (no drafters, no verify programs).
"""

import time

import jax.numpy as jnp
import pytest

from dllama_tpu.runtime.api_server import (
    ApiState,
    ChatMessage,
    InferenceParams,
    resolve_spec_knobs,
)
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.spec import (
    NgramDrafter,
    NgramIndex,
    bucket_for,
    spec_buckets,
)
from dllama_tpu.tokenizer import Tokenizer

from helpers import make_tiny_model, make_tiny_tokenizer

CFG = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
           head_dim=16, vocab_size=288, seq_len=384)

# repetitive (JSON-ish) user content: the workload class prompt-lookup
# exists for — the model's own output also cycles quickly on a tiny
# net, so drafts get accepted and rejected within a short stream
REPETITIVE = '{"a": 1, "b": 2}, {"a": 1, "b": 2}, {"a": 1, "b": 2}'


@pytest.fixture(scope="module")
def tiny_paths(tmp_path_factory):
    d = tmp_path_factory.mktemp("spec")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    make_tiny_model(mp, cfg=CFG)
    # pad the tokenizer out to the model's vocab: the mixed-lane test
    # SAMPLES (temperature>0), so any model-vocab id may be emitted
    make_tiny_tokenizer(
        tp_, chat_template="<|start_header_id|>", pad_to=CFG["vocab_size"]
    )
    return mp, tp_


def _mk_state(tiny_paths, **kw):
    mp, tp_ = tiny_paths
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=3,
    )
    state = ApiState(
        engine, tok, lane_block_size=4, admission_chunk=6, **kw
    )
    assert state.scheduler is not None
    return state


@pytest.fixture(scope="module")
def spec_state(tiny_paths):
    return _mk_state(tiny_paths, speculation="ngram", spec_k=4)


@pytest.fixture(scope="module")
def off_state(tiny_paths):
    return _mk_state(tiny_paths)  # default: speculation off


def _drain(job, timeout=300):
    deltas = []
    deadline = time.time() + timeout
    while True:
        kind, payload = job.events.get(timeout=max(0.1, deadline - time.time()))
        if kind == "delta":
            deltas.append(payload)
        elif kind == "done":
            return "".join(deltas), payload
        else:
            raise AssertionError(f"job errored: {payload}")


def _greedy(content, max_tokens=48):
    return InferenceParams(
        messages=[ChatMessage(role="user", content=content)],
        temperature=0.0, max_tokens=max_tokens, stream=True,
    )


# -- drafter unit tests -------------------------------------------------------


@pytest.mark.fast
def test_ngram_index_proposes_continuation():
    ix = NgramIndex(max_n=3)
    ix.extend([1, 2, 3, 4, 1, 2, 3])
    # suffix (1,2,3) occurred earlier at offset 0; its continuation was 4
    assert ix.lookup(4) == [4, 1, 2, 3]
    assert ix.lookup(1) == [4]
    # unseen suffix: nothing to propose
    ix2 = NgramIndex(max_n=3)
    ix2.extend([9, 8, 7])
    assert ix2.lookup(4) == []


@pytest.mark.fast
def test_ngram_index_prefers_longest_and_latest():
    ix = NgramIndex(max_n=3)
    # (5,6) appears twice with different continuations: 7 then 9; the
    # LATEST earlier occurrence wins
    ix.extend([5, 6, 7, 0, 5, 6, 9, 0, 5, 6])
    assert ix.lookup(1) == [9]
    # longest-suffix preference: a 3-gram match beats the 1-gram's entry
    ix3 = NgramIndex(max_n=3)
    ix3.extend([1, 2, 3, 7, 0, 3, 8, 0, 1, 2, 3])
    assert ix3.lookup(1) == [7]


@pytest.mark.fast
def test_drafter_update_is_incremental():
    dr = NgramDrafter(k_max=4)
    h = [5, 6, 7, 5, 6]
    dr.update(h)
    # continuation [7, 5, 6] runs out of history one short of k_max=4;
    # the cyclic extension predicts the period-3 repeat continues
    assert dr.draft() == [7, 5, 6, 7]
    # only the unseen tail is indexed on the next sync
    h += [7]
    dr.update(h)
    assert len(dr.index.tokens) == 6


@pytest.mark.fast
def test_drafter_adaptive_k_and_cooldown():
    dr = NgramDrafter(k_max=4, cooldown=2)
    assert dr.k == 4
    dr.feedback(4, 4)  # full acceptance: already at cap
    assert dr.k == 4
    dr.feedback(4, 0)  # zero acceptance: halve + pause drafting
    assert dr.k == 2
    dr.update([1, 2, 1, 2, 1])
    assert dr.draft() == []  # cooling down
    assert dr.draft() == []
    assert dr.draft() == [2, 1]  # cooldown over, k now caps the draft
    dr.feedback(2, 2)
    assert dr.k == 3  # additive regrowth


@pytest.mark.fast
def test_spec_buckets_and_bucket_for():
    assert spec_buckets(8) == (1, 2, 4, 8)
    assert spec_buckets(6) == (1, 2, 4, 6)
    assert spec_buckets(1) == (1,)
    assert spec_buckets(0) == ()
    assert bucket_for(3, (1, 2, 4, 8)) == 4
    assert bucket_for(8, (1, 2, 4, 8)) == 8


@pytest.mark.fast
def test_spec_knob_resolution(monkeypatch):
    monkeypatch.delenv("DLLAMA_SPECULATION", raising=False)
    monkeypatch.delenv("DLLAMA_SPEC_K", raising=False)
    assert resolve_spec_knobs() == ("off", 4)
    monkeypatch.setenv("DLLAMA_SPECULATION", "ngram")
    monkeypatch.setenv("DLLAMA_SPEC_K", "8")
    assert resolve_spec_knobs() == ("ngram", 8)
    # explicit beats env
    assert resolve_spec_knobs("off", 2) == ("off", 2)
    with pytest.raises(ValueError):
        resolve_spec_knobs("eagle")


@pytest.mark.fast
def test_spec_cli_flags(tmp_path):
    import argparse

    from dllama_tpu.cli import add_engine_args

    parser = argparse.ArgumentParser()
    add_engine_args(parser)
    args = parser.parse_args(
        ["--model", "m", "--speculation", "ngram", "--spec-k", "8"]
    )
    assert args.speculation == "ngram" and args.spec_k == 8
    args = parser.parse_args(["--model", "m"])
    assert args.speculation is None and args.spec_k is None


# -- engine verify parity -----------------------------------------------------


@pytest.mark.fast
def test_engine_verify_matches_stepwise_greedy(tiny_paths):
    """One verify_lanes dispatch accepts exactly the prefix a greedy
    decode emits token by token, and the rewind after a rejected draft
    leaves the lane able to continue byte-identically."""
    mp, _ = tiny_paths
    prompt = [2 + (i * 7) % 250 for i in range(17)]
    pos0, pending = len(prompt) - 1, prompt[-1]

    e = InferenceEngine(
        mp, tp=1, dtype=jnp.float32, temperature=0.0, seed=3, batch_size=2
    )
    e.prefill_lane(0, prompt[:-1], 0)
    ref = [r[0] for r in e.decode_lanes(
        [pending, 0], [pos0, 0], 10, [True, False]
    )]

    e2 = InferenceEngine(
        mp, tp=1, dtype=jnp.float32, temperature=0.0, seed=3, batch_size=2
    )
    e2.prefill_lane(0, prompt[:-1], 0)
    # perfect draft: the model's own continuation gets fully accepted
    d = ref[:4]
    grid = e2.verify_lanes([[pending, *d], [0] * 5], [pos0, 0], [True, False])
    a = 0
    while a < len(d) and grid[0][a] == d[a]:
        a += 1
    assert a == 4
    emitted = d[:a] + [grid[0][a]]
    assert emitted == ref[:5]
    # wrong draft: accepted prefix stops at the divergence, the emitted
    # run is still the greedy stream, and the lane continues from the
    # rewound position as if the rejected rows never existed
    pos1 = pos0 + len(emitted)
    bad = [(ref[5] + 1) % CFG["vocab_size"], 3, 5, 9]
    grid = e2.verify_lanes(
        [[emitted[-1], *bad], [0] * 5], [pos1, 0], [True, False]
    )
    a = 0
    while a < len(bad) and grid[0][a] == bad[a]:
        a += 1
    assert a == 0
    emitted2 = bad[:a] + [grid[0][a]]
    assert emitted2 == ref[5:6]
    pos2 = pos1 + len(emitted2)
    cont = [r[0] for r in e2.decode_lanes(
        [emitted2[-1], 0], [pos2, 0], 10 - (pos2 - pos0), [True, False]
    )]
    assert cont == ref[pos2 - pos0:]


# -- scheduler parity (the tentpole's acceptance criterion) -------------------


def test_spec_stream_parity_and_metrics(spec_state, off_state):
    """Spec-on and spec-off greedy streams are byte-identical on a
    repetitive workload, drafts actually flowed, and the dllama_spec_*
    metrics + spec_verify recorder events are live."""
    drafted0 = spec_state.m_spec_drafted.value
    on_text, on_reason = _drain(
        spec_state.scheduler.submit(_greedy(REPETITIVE))
    )
    off_text, off_reason = _drain(
        off_state.scheduler.submit(_greedy(REPETITIVE))
    )
    assert (on_text, on_reason) == (off_text, off_reason)
    assert on_reason in ("stop", "length") and len(on_text) > 0
    # speculation really ran: draft volume moved, the acceptance-length
    # histogram sampled, and the rate gauge is a valid ratio
    assert spec_state.m_spec_drafted.value > drafted0
    assert spec_state.m_spec_accept_len.count >= 1
    assert 0.0 <= spec_state.g_spec_rate.value <= 1.0
    evs = spec_state.recorder.events(kind="spec_verify")
    assert evs and all(
        0 <= e["accepted"] <= e["k"] for e in evs
    )
    # verify programs were rehearsed + dispatched under the bucketed
    # keys — no unbucketed shape may compile mid-serve
    kinds = {k[0] for k in spec_state.engine._compiled if isinstance(k, tuple)}
    assert "lane_verify" in kinds
    widths = {
        k[1] for k in spec_state.engine._compiled
        if isinstance(k, tuple) and k[0] == "lane_verify"
    }
    allowed = {1 + b for b in spec_buckets(spec_state.scheduler.spec_k)}
    assert widths <= allowed


def test_spec_mixed_lane_fallback_parity(spec_state, off_state):
    """A temperature>0 lane joining mid-stream shares the dispatch group
    but transparently takes the decode block: the greedy lane's stream
    and the seeded sampled lane's stream both match spec-off."""
    def run(state):
        g_job = state.scheduler.submit(_greedy(REPETITIVE, max_tokens=64))
        # let the greedy stream get going before the sampled lane joins
        deadline = time.time() + 300
        while g_job.n_completion < 4 and time.time() < deadline:
            time.sleep(0.02)
        assert g_job.n_completion >= 4
        s_job = state.scheduler.submit(InferenceParams(
            messages=[ChatMessage(role="user", content="tell me a story")],
            temperature=0.8, top_p=0.9, seed=11, max_tokens=24, stream=True,
        ))
        return _drain(g_job), _drain(s_job)

    assert run(spec_state) == run(off_state)


def test_spec_rewind_composes_with_kv_publish(spec_state):
    """A stream that saw rejected drafts still publishes a valid prefix:
    the identical follow-up request adopts pool pages (prefix hit) and
    streams the same bytes — garbage KV from rejected rows never lands
    in the pool (publish covers only history[:pos])."""
    prompt = REPETITIVE + " and then some more of the same pattern"
    text1, reason1 = _drain(spec_state.scheduler.submit(_greedy(prompt)))
    evs = spec_state.recorder.events(kind="spec_verify")
    assert any(e["accepted"] < e["k"] for e in evs), (
        "expected at least one rejected-draft rewind in this stream"
    )
    reused0 = spec_state.m_reused_tokens.value
    text2, reason2 = _drain(spec_state.scheduler.submit(_greedy(prompt)))
    assert (text2, reason2) == (text1, reason1)
    assert spec_state.m_reused_tokens.value > reused0


@pytest.mark.fast
def test_spec_off_is_pure_bypass(off_state):
    """speculation=off keeps the scheduler on the plain decode path: no
    drafters ever exist and no verify program is built."""
    sched = off_state.scheduler
    assert not sched.spec_on and not sched.drafters
    kinds = {k[0] for k in off_state.engine._compiled if isinstance(k, tuple)}
    assert "lane_verify" not in kinds
