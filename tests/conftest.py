"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip sharding tests run on the host platform with 8 virtual devices
(the TPU-world equivalent of the reference's `examples/n-workers.sh`
localhost-cluster harness — see SURVEY.md §4). Must be set before jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402  (after the env setup above, by design)

# A pytest plugin (jaxtyping) imports jax before this conftest runs, so the
# env vars above may be too late — force the platform via config too.
jax.config.update("jax_platforms", "cpu")

# f32 matmuls must really be f32 for oracle-equivalence tests (this JAX
# build's default matmul precision is reduced even on CPU).
jax.config.update("jax_default_matmul_precision", "highest")
