"""Independent numpy oracle for the transformer forward pass.

Deliberately written loop-style and directly from the reference kernel
semantics (src/nn/nn-cpu-ops.cpp) — NOT by calling into dllama_tpu's model
code — so tests compare two independent implementations, mirroring the
reference's SIMD-vs-scalar / GPU-vs-CPU equivalence testing (SURVEY.md §4).
Consumes file-layout tensors: matmul weights are (out, in) and y = W @ x.
"""

from __future__ import annotations

import numpy as np

from dllama_tpu.formats.model_file import LlmArch, LlmHeader, RopeType


def rmsnorm(x: np.ndarray, w: np.ndarray, eps: float) -> np.ndarray:
    inv = 1.0 / np.sqrt(np.mean(x * x, axis=-1, keepdims=True) + eps)
    return x * inv * w


def softmax(x: np.ndarray) -> np.ndarray:
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


def silu(x):
    return x / (1.0 + np.exp(-x))


def rope_rotate(vec: np.ndarray, pos: int, h: LlmHeader) -> np.ndarray:
    """Rotate one [nHeads*headDim] row in place-style; llama interleaved or
    falcon half-rotation pairing (src/nn/nn-cpu-ops.cpp:843-885)."""
    hd = h.head_dim
    half = hd // 2
    out = vec.copy().reshape(-1, hd)
    freqs = 1.0 / (h.rope_theta ** (2.0 * np.arange(half) / hd))
    if h.rope_type == RopeType.LLAMA3_1 and h.rope_scaling_factor != 1.0:
        scaled = []
        for f in freqs:
            wave_len = 2.0 * np.pi / f
            high = h.rope_scaling_orig_max_seq_len / h.rope_scaling_high_freq_factor
            low = h.rope_scaling_orig_max_seq_len / h.rope_scaling_low_freq_factor
            if wave_len < high:
                scaled.append(f)
            elif wave_len > low:
                scaled.append(f / h.rope_scaling_factor)
            else:
                smooth = (
                    h.rope_scaling_orig_max_seq_len / wave_len
                    - h.rope_scaling_low_freq_factor
                ) / (h.rope_scaling_high_freq_factor - h.rope_scaling_low_freq_factor)
                scaled.append((1 - smooth) * f / h.rope_scaling_factor + smooth * f)
        freqs = np.array(scaled)
    cos = np.cos(pos * freqs)
    sin = np.sin(pos * freqs)
    interleaved = h.rope_type in (RopeType.LLAMA, RopeType.LLAMA3_1)
    for head in range(out.shape[0]):
        row = out[head]
        if interleaved:
            for j in range(half):
                v0, v1 = row[2 * j], row[2 * j + 1]
                row[2 * j] = v0 * cos[j] - v1 * sin[j]
                row[2 * j + 1] = v0 * sin[j] + v1 * cos[j]
        else:
            for j in range(half):
                v0, v1 = row[j], row[j + half]
                row[j] = v0 * cos[j] - v1 * sin[j]
                row[j + half] = v0 * sin[j] + v1 * cos[j]
    return out.reshape(vec.shape)


def numpy_forward(
    tensors: dict[str, np.ndarray], h: LlmHeader, tokens: list[int]
) -> np.ndarray:
    """Full forward over a token list (single sequence); returns [T, V] f32."""
    hd = h.head_dim
    n_heads, n_kv = h.n_heads, h.n_kv_heads
    kv_mul = n_heads // n_kv
    is_qwen3 = h.arch in (LlmArch.QWEN3, LlmArch.QWEN3_MOE)

    x = np.stack([tensors["embed"][t].astype(np.float64) for t in tokens])
    k_cache = [np.zeros((len(tokens), n_kv, hd)) for _ in range(h.n_layers)]
    v_cache = [np.zeros((len(tokens), n_kv, hd)) for _ in range(h.n_layers)]

    logits_rows = []
    for t, _tok in enumerate(tokens):
        xt = x[t]
        for l in range(h.n_layers):
            pre = f"layers.{l}."
            y = rmsnorm(xt, tensors[pre + "att_norm"], h.norm_epsilon)
            q = tensors[pre + "q"] @ y
            k = tensors[pre + "k"] @ y
            v = tensors[pre + "v"] @ y
            if is_qwen3:
                q = rmsnorm(
                    q.reshape(n_heads, hd), tensors[pre + "q_norm"], h.norm_epsilon
                ).reshape(-1)
                k = rmsnorm(
                    k.reshape(n_kv, hd), tensors[pre + "k_norm"], h.norm_epsilon
                ).reshape(-1)
            q = rope_rotate(q, t, h)
            k = rope_rotate(k, t, h)
            k_cache[l][t] = k.reshape(n_kv, hd)
            v_cache[l][t] = v.reshape(n_kv, hd)

            z = np.zeros(n_heads * hd)
            qh = q.reshape(n_heads, hd)
            for head in range(n_heads):
                kv_head = head // kv_mul
                scores = np.array(
                    [
                        qh[head] @ k_cache[l][s, kv_head] / np.sqrt(hd)
                        for s in range(t + 1)
                    ]
                )
                att = softmax(scores)
                z[head * hd : (head + 1) * hd] = sum(
                    att[s] * v_cache[l][s, kv_head] for s in range(t + 1)
                )
            xt = xt + tensors[pre + "wo"] @ z

            y = rmsnorm(xt, tensors[pre + "ffn_norm"], h.norm_epsilon)
            if h.arch == LlmArch.QWEN3_MOE:
                gate_logits = tensors[pre + "moe_gate"] @ y
                probs = softmax(gate_logits)
                top = np.argsort(-probs)[: h.n_active_experts]
                wsum = probs[top].sum()
                f = np.zeros_like(y)
                for e in top:
                    ep = f"{pre}experts.{e}."
                    d = silu(tensors[ep + "w1"] @ y) * (tensors[ep + "w3"] @ y)
                    f += (probs[e] / wsum) * (tensors[ep + "w2"] @ d)
            else:
                d = silu(tensors[pre + "w1"] @ y) * (tensors[pre + "w3"] @ y)
                f = tensors[pre + "w2"] @ d
            xt = xt + f
        y = rmsnorm(xt, tensors["final_norm"], h.norm_epsilon)
        logits_rows.append(tensors["wcls"] @ y)
    return np.stack(logits_rows).astype(np.float32)
