"""Pallas Q40 matmul vs jnp dequant reference (cross-implementation
equivalence, the reference's nn-cpu-ops-test.cpp:257-277 pattern)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.formats.quants import quantize_q40, q40_to_planar
from dllama_tpu.ops.quant_matmul import (

    QuantWeight,
    dequant,
    from_planar,
    qmatmul,
    qmatmul_2d,
    qmatmul_ref,
)

# sub-minute CPU-only surface (codecs, tokenizer, native loader,
# interpret-mode kernel parity): the first CI lane runs `pytest -m fast`
pytestmark = pytest.mark.fast


def make_qw(n, k, seed=0):
    """QuantWeight for a logical [out=n, in=k] matmul weight."""
    rng = np.random.default_rng(seed)
    w = rng.standard_normal((n, k)).astype(np.float32) * 0.1
    raw = quantize_q40(w)
    q, d = q40_to_planar(raw, n * k)
    return from_planar(q.reshape(n, k), d.reshape(n, k // 32)), w


def test_dequant_matches_codec():
    qw, w = make_qw(64, 128)
    dense = np.asarray(dequant(qw, jnp.float32)).T  # device layout is [in, out]
    # within one Q40 block scale of the original
    scales = np.abs(w.reshape(-1, 32)).max(axis=1) / 8.0
    err = np.abs(dense.reshape(-1, 32) - w.reshape(-1, 32))
    assert (err <= scales[:, None] * 1.01 + 1e-6).all()


@pytest.mark.parametrize("m,n,k", [(1, 256, 512), (8, 512, 256), (16, 256, 1024)])
def test_pallas_kernel_matches_reference(m, n, k):
    """Interpret-mode kernel vs dequant einsum (bf16 input rounding is the
    only difference source)."""
    qw, _ = make_qw(n, k, seed=1)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32))
    expected = np.asarray(qmatmul_ref(x.astype(jnp.bfloat16).astype(jnp.float32), qw))
    got = np.asarray(qmatmul_2d(x, qw.q, qw.d, block_n=128, interpret=True))
    np.testing.assert_allclose(got, expected, rtol=2e-2, atol=2e-2)


def test_qmatmul_auto_flatten():
    qw, _ = make_qw(128, 256, seed=3)
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((2, 3, 256)).astype(np.float32))
    out = qmatmul(x, qw)
    assert out.shape == (2, 3, 128)
    expected = qmatmul_ref(x, qw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-2, atol=2e-2)


def test_quantweight_is_pytree():
    import jax

    qw, _ = make_qw(64, 64)
    stacked = jax.tree.map(lambda a: jnp.stack([a, a]), qw)
    assert isinstance(stacked, QuantWeight)
    assert stacked.q.shape == (2, 64, 64)
    leaves = jax.tree.leaves(qw)
    assert len(leaves) == 2


@pytest.mark.parametrize("m", [1, 4])
def test_moe_active_experts_kernel(m):
    """Ragged MoE kernel (per-token top-k) vs the dense jnp path
    (interpret mode)."""
    import jax
    from jax import lax

    from dllama_tpu.ops.moe_kernel import moe_active_experts

    rng = np.random.default_rng(2)
    E, D, F, K = 8, 64, 96, 3
    w1 = jnp.asarray(rng.standard_normal((E, D, F)).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.standard_normal((E, F, D)).astype(np.float32) * 0.1)
    w3 = jnp.asarray(rng.standard_normal((E, D, F)).astype(np.float32) * 0.1)
    gate = jnp.asarray(rng.standard_normal((D, E)).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((m, D)).astype(np.float32))

    probs = jax.nn.softmax(x @ gate, axis=-1)
    top_p, top_i = lax.top_k(probs, K)  # [m, K]
    weights = top_p / top_p.sum(axis=-1, keepdims=True)
    out = moe_active_experts(x, w1, w2, w3, top_i, weights, interpret=True)

    from dllama_tpu.models.transformer import _moe_ffn
    from dllama_tpu.ops.jnp_ops import silu

    dense = _moe_ffn(x[:, None], gate, w1, w2, w3, K, silu)  # [m, 1, D]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense)[:, 0], rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("m", [1, 4])
def test_moe_active_experts_q40_kernel(m):
    """Quantized ragged MoE kernel vs dequant-then-dense-kernel: the only
    difference source is where the dequant happens (in-VMEM vs host), so
    tolerances are bf16-rounding tight."""
    import jax
    from jax import lax

    from dllama_tpu.ops.moe_kernel import (
        moe_active_experts,
        moe_active_experts_q40,
    )

    rng = np.random.default_rng(7)
    E, D, F, K = 8, 64, 96, 3

    def make_experts(out_dim, in_dim, seed):
        qs, ds = [], []
        for e in range(E):
            qw, _ = make_qw(out_dim, in_dim, seed=seed * 100 + e)
            qs.append(np.asarray(qw.q))
            ds.append(np.asarray(qw.d))
        return QuantWeight(jnp.asarray(np.stack(qs)), jnp.asarray(np.stack(ds)))

    w1 = make_experts(F, D, 1)  # device layout: q [E, D, F]
    w3 = make_experts(F, D, 2)
    w2 = make_experts(D, F, 3)  # q [E, F, D]
    gate = jnp.asarray(rng.standard_normal((D, E)).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((m, D)).astype(np.float32))

    probs = jax.nn.softmax(x @ gate, axis=-1)
    top_p, top_i = lax.top_k(probs, K)
    weights = top_p / top_p.sum(axis=-1, keepdims=True)

    out = moe_active_experts_q40(
        x, w1.q, w1.d, w2.q, w2.d, w3.q, w3.d, top_i, weights, interpret=True
    )
    expected = moe_active_experts(
        x.astype(jnp.bfloat16),
        dequant(w1, jnp.bfloat16),
        dequant(w2, jnp.bfloat16),
        dequant(w3, jnp.bfloat16),
        top_i,
        weights,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-2, atol=2e-2
    )


@pytest.mark.parametrize("tp", [1, 2, 4])
def test_fused_interleave_roundtrip(tp):
    """loader._interleave_concat + transformer._split_fused restore the
    separate matmul outputs (up to XLA reduction-order f32 noise: the
    fused width changes the einsum's tiling, not its math)."""
    from dllama_tpu.models.loader import _interleave_concat
    from dllama_tpu.models.transformer import _split_fused

    rng = np.random.default_rng(7)
    k = 64
    dims = (32 * tp, 16 * tp, 16 * tp)
    ws = [rng.standard_normal((k, d)).astype(np.float32) for d in dims]
    fused = _interleave_concat(ws, tp)
    x = jnp.asarray(rng.standard_normal((2, 3, k)).astype(np.float32))
    out = jnp.einsum("btk,ko->bto", x, jnp.asarray(fused))
    parts = _split_fused(out, tp, dims)
    for part, w in zip(parts, ws):
        expect = jnp.einsum("btk,ko->bto", x, jnp.asarray(w))
        np.testing.assert_allclose(
            np.asarray(part), np.asarray(expect), rtol=1e-5, atol=1e-5
        )


def test_fused_quant_loader_matches_split(tmp_path):
    """Engine-default fusion (weight_format=q40) at the loader level: the
    fused wqkv QuantWeight dequantizes to the column-permuted concat of
    wq/wk/wv, and un-interleaving the fused matmul output reproduces the
    split results (same dequant blocks, f32-noise-level tolerance)."""
    from dllama_tpu.models.loader import _interleave_concat
    from dllama_tpu.models.transformer import _split_fused

    tp = 2
    k = 128
    dims = (64, 64, 64)
    qws = [make_qw(d, k, seed=10 + i)[0] for i, d in enumerate(dims)]
    fused = QuantWeight(
        jnp.asarray(
            _interleave_concat([np.asarray(w.q) for w in qws], tp)
        ),
        jnp.asarray(
            _interleave_concat([np.asarray(w.d) for w in qws], tp)
        ),
    )
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((1, 1, k)).astype(np.float32))
    out = qmatmul_ref(x, fused)
    parts = _split_fused(out, tp, dims)
    for part, w in zip(parts, qws):
        expect = qmatmul_ref(x, w)
        np.testing.assert_allclose(
            np.asarray(part), np.asarray(expect), rtol=0, atol=1e-5
        )


# ---------------------------------------------------------------- packed int4


def make_packed(n, k, seed=0):
    """(PackedQuantWeight, QuantWeight, dense) triple for [out=n, in=k]."""
    from dllama_tpu.ops.quant_matmul import pack_nibbles

    qw, w = make_qw(n, k, seed=seed)
    return pack_nibbles(qw), qw, w


def test_pack_nibbles_roundtrip():
    from dllama_tpu.ops.quant_matmul import unpack_nibbles

    pw, qw, _ = make_packed(64, 128)
    assert pw.qp.shape == (64, 64) == (qw.q.shape[0] // 2, qw.q.shape[1])
    assert pw.d.dtype == jnp.float16
    np.testing.assert_array_equal(
        np.asarray(unpack_nibbles(pw.qp)), np.asarray(qw.q, dtype=np.int32)
    )


def test_host_pack_matches_device_pack():
    """formats.pack_q40_device (numpy, loader path) produces the exact
    bytes of ops.pack_nibbles (jnp, requantize path)."""
    from dllama_tpu.formats.quants import pack_q40_device

    pw, qw, _ = make_packed(128, 256, seed=5)
    qp_np, d_np = pack_q40_device(np.asarray(qw.q), np.asarray(qw.d))
    np.testing.assert_array_equal(qp_np, np.asarray(pw.qp))
    np.testing.assert_array_equal(d_np, np.asarray(pw.d))


def test_dequant_packed_matches_dequant():
    """f16 scales are wire-exact (Q40 stores fp16 scales), so the packed
    dequant is bit-identical to the int8 dequant."""
    from dllama_tpu.ops.quant_matmul import dequant_packed

    pw, qw, _ = make_packed(64, 128, seed=2)
    np.testing.assert_array_equal(
        np.asarray(dequant_packed(pw, jnp.float32)),
        np.asarray(dequant(qw, jnp.float32)),
    )


@pytest.mark.parametrize("m,n,k", [(1, 256, 512), (8, 512, 256), (16, 256, 1024)])
def test_packed_kernel_matches_reference(m, n, k):
    """Interpret-mode int4 kernel vs the dequant einsum AND vs the int8
    kernel on the unpacked twin (in-kernel nibble unpack is exact, so the
    two kernels agree bit-for-bit)."""
    from dllama_tpu.ops.quant_matmul import qmatmul_i4_2d

    pw, qw, _ = make_packed(n, k, seed=1)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32))
    expected = np.asarray(qmatmul_ref(x.astype(jnp.bfloat16).astype(jnp.float32), qw))
    got = np.asarray(
        qmatmul_i4_2d(x, pw.qp, pw.d, block_n=128, interpret=True)
    )
    np.testing.assert_allclose(got, expected, rtol=2e-2, atol=2e-2)
    int8 = np.asarray(qmatmul_2d(x, qw.q, qw.d, block_n=128, interpret=True))
    np.testing.assert_array_equal(got, int8)


def test_packed_bytes_per_weight():
    """The device residency win the format exists for: ≤ 0.60 B/weight
    including scales (0.5 packed nibbles + 2/32 f16 scale = 0.5625)."""
    pw, qw, _ = make_packed(256, 512)
    n_weights = 256 * 512
    packed_bytes = pw.qp.nbytes + pw.d.nbytes
    assert packed_bytes / n_weights <= 0.60
    assert pw.qp.nbytes * 2 == qw.q.nbytes  # exactly half the value bytes


def test_packed_qmatmul_dispatch():
    """qmatmul auto-dispatches on the weight class (ref path off-TPU)."""
    from dllama_tpu.ops.quant_matmul import PackedQuantWeight

    pw, qw, _ = make_packed(128, 256, seed=3)
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((2, 3, 256)).astype(np.float32))
    out = qmatmul(x, pw)
    assert out.shape == (2, 3, 128)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(qmatmul_ref(x, qw)), rtol=2e-2, atol=2e-2
    )


def test_packedquantweight_is_pytree():
    import jax

    from dllama_tpu.ops.quant_matmul import PackedQuantWeight

    pw, _, _ = make_packed(64, 64)
    stacked = jax.tree.map(lambda a: jnp.stack([a, a]), pw)
    assert isinstance(stacked, PackedQuantWeight)
    assert stacked.qp.shape == (2, 32, 64)
    assert len(jax.tree.leaves(pw)) == 2


def test_fused_packed_matches_split():
    """Interleave (out-axis permutation) commutes with packing (in-axis
    halving): packing the fused int8 weight equals fusing then packing,
    and the fused packed ref output un-interleaves to the split results."""
    from dllama_tpu.models.loader import _interleave_concat
    from dllama_tpu.models.transformer import _split_fused
    from dllama_tpu.ops.quant_matmul import pack_nibbles

    tp = 2
    k = 128
    dims = (64, 64, 64)
    qws = [make_qw(d, k, seed=20 + i)[0] for i, d in enumerate(dims)]
    fused = QuantWeight(
        jnp.asarray(_interleave_concat([np.asarray(w.q) for w in qws], tp)),
        jnp.asarray(_interleave_concat([np.asarray(w.d) for w in qws], tp)),
    )
    pfused = pack_nibbles(fused)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((1, 1, k)).astype(np.float32))
    out = qmatmul_ref(x, pfused)
    parts = _split_fused(out, tp, dims)
    for part, w in zip(parts, qws):
        expect = qmatmul_ref(x, pack_nibbles(w))
        np.testing.assert_allclose(
            np.asarray(part), np.asarray(expect), rtol=0, atol=1e-5
        )
