"""Predictive SLO-aware admission control (ISSUE 20).

The LoadPredictor units are fake-clock / fake-engine pure tests (the
fast lockwatch subset); the scheduler-level tests drive a real tiny
engine through the ApiState directly (EDF ordering, infeasible-reject,
byte-identity predictive on vs off); the server-level test forces a
deterministic preemption and asserts the parked victim resumes
byte-identically through the PR 16 park/resume contract.
"""

import json
import math
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import pytest

from dllama_tpu.runtime.admission import (
    _CORR_MAX,
    LoadPredictor,
    OccupancySnapshot,
    Prediction,
    effective_deadline_ms,
    resolve_admission_knobs,
    resolve_deadline_knobs,
)
from dllama_tpu.runtime.api_server import (
    ApiState,
    ChatMessage,
    InferenceParams,
    serve,
)
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.tokenizer import Tokenizer

from helpers import make_tiny_model, make_tiny_tokenizer

CFG = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
           head_dim=16, vocab_size=288, seq_len=384)


# -- LoadPredictor units (no engine: cold-floor physics) ----------------------


@pytest.mark.fast
def test_predict_occupancy_sensitivity():
    """More load => higher forecast, on every axis the snapshot carries:
    queue depth raises TTFT (each queued request adds drain time), busy
    lanes raise TTFT (decode interleave per chunk), parked streams
    stretch TPOT by the oversubscription factor."""
    pred = LoadPredictor(object(), clock=lambda: 0.0)

    by_queue = [
        pred.predict(100, OccupancySnapshot(4, 4, queue_depth=q))
        for q in (0, 2, 6)
    ]
    assert by_queue[0].ttft_ms < by_queue[1].ttft_ms < by_queue[2].ttft_ms
    assert (
        by_queue[0].queue_wait_ms
        < by_queue[1].queue_wait_ms
        < by_queue[2].queue_wait_ms
    )

    idle = pred.predict(100, OccupancySnapshot(4, 0))
    busy = pred.predict(100, OccupancySnapshot(4, 4))
    assert busy.ttft_ms > idle.ttft_ms

    over = pred.predict(100, OccupancySnapshot(4, 4, parked=4))
    assert over.tpot_ms == pytest.approx(2.0 * idle.tpot_ms)  # 8 streams / 4 lanes

    for p in (*by_queue, idle, busy, over):
        assert math.isfinite(p.ttft_ms) and math.isfinite(p.tpot_ms)
        assert p.ttft_ms > 0 and p.tpot_ms > 0


@pytest.mark.fast
def test_predict_prefix_match_sensitivity():
    """A radix-tree match is prefill the engine skips: matched tokens
    shrink the chunk count and the TTFT, floored at one chunk (admission
    always replays the last matched token for the first logits)."""
    pred = LoadPredictor(object())
    occ = OccupancySnapshot(4, 2, admission_chunk=32)
    full = pred.predict(256, occ)
    half = pred.predict(256, occ, matched_tokens=128)
    whole = pred.predict(256, occ, matched_tokens=256)
    assert full.prefill_chunks == 8
    assert half.prefill_chunks == 4
    assert whole.prefill_chunks == 1
    assert full.ttft_ms > half.ttft_ms > whole.ttft_ms


@pytest.mark.fast
def test_queue_drain_and_retry_after_monotonic_in_queue_depth():
    """The satellite contract: every shed Retry-After is derived from the
    predicted queue-drain time, monotonic in queue depth and capped at
    the max-wait knob."""
    pred = LoadPredictor(object())
    drains = [
        pred.queue_drain_seconds(OccupancySnapshot(2, 2, queue_depth=q))
        for q in range(6)
    ]
    assert all(b > a for a, b in zip(drains, drains[1:])), drains

    ras = [
        pred.retry_after_s(
            OccupancySnapshot(2, 2, queue_depth=q), max_wait_ms=30_000
        )
        for q in (0, 10, 50)
    ]
    assert ras[0] >= 1
    assert ras[0] < ras[1] < ras[2], ras
    # the cap: an absurd backlog still advertises at most max_wait
    assert pred.retry_after_s(
        OccupancySnapshot(2, 2, queue_depth=10_000), max_wait_ms=4_000
    ) == 4


@pytest.mark.fast
def test_ewma_self_calibration_converges():
    """Closed loop: predictions fold their own observed error back in,
    so a consistently-slow reality converges the forecast onto itself;
    a single wild observation is clamped, never a 10x swing."""
    pred = LoadPredictor(object())
    occ = OccupancySnapshot(2, 1)
    true_ms = 300.0
    # reality is consistently 2x the uncorrected tpot forecast: the
    # closed loop must converge the correction onto that fixed truth
    true_tpot_ms = 2.0 * pred.predict(64, occ).tpot_ms
    for _ in range(40):
        p = pred.predict(64, occ)
        pred.observe_ttft(p.ttft_ms, true_ms)
        pred.observe_tpot(p.tpot_ms, true_tpot_ms)
    final = pred.predict(64, occ)
    assert final.ttft_ms == pytest.approx(true_ms, rel=0.10)
    assert final.tpot_ms == pytest.approx(true_tpot_ms, rel=0.10)
    snap = pred.snapshot()
    assert snap["n_observations"] == 40
    assert snap["tpot_correction"] == pytest.approx(2.0, rel=0.10)

    # clamp: absurd ratios saturate at the correction ceiling
    wild = LoadPredictor(object(), alpha=0.9)
    for _ in range(50):
        wild.observe_ttft(1.0, 1e9)
    assert wild.snapshot()["ttft_correction"] <= _CORR_MAX
    # degenerate observations are ignored entirely
    n0 = wild.snapshot()["n_observations"]
    wild.observe_ttft(0.0, 100.0)
    wild.observe_ttft(100.0, -1.0)
    assert wild.snapshot()["n_observations"] == n0


@pytest.mark.fast
def test_step_seconds_prefers_measured_over_floor():
    """Cost resolution order: measured step p50 (once enough samples
    exist) > analytic cost model > cold floor."""

    class _Child:
        def __init__(self, count, p50):
            self.count, self._p50 = count, p50

        def percentile(self, q):
            return self._p50

    class _Hist:
        def __init__(self, children):
            self._children = children

        def labels(self, kind):
            return self._children[kind]

    class _Engine:
        def __init__(self, count):
            self._m_step = _Hist({
                "prefill_lane_chunk": _Child(count, 0.007),
                "decode_lanes": _Child(count, 0.003),
            })

    warm = LoadPredictor(_Engine(count=50))
    assert warm.step_seconds("prefill_lane_chunk", 0.05) == 0.007
    assert warm.step_seconds("decode_lanes", 0.02) == 0.003

    # below MIN_STEP_SAMPLES (and no cost_report): the cold floor
    cold = LoadPredictor(_Engine(count=2))
    assert cold.step_seconds("prefill_lane_chunk", 0.05) == 0.05
    assert cold.step_seconds("decode_lanes", 0.02) == 0.02


@pytest.mark.fast
def test_effective_deadline_edf_key():
    """Deterministic EDF keys: hints win (tightest hint), the unhinted
    priority ladder becomes deadline offsets preserving strict
    high < normal < low ordering — the PR 12 contract."""
    now = 1_000_000.0
    assert effective_deadline_ms(now, deadline_ms=5000.0) == now + 5000.0
    assert effective_deadline_ms(
        now, deadline_ms=5000.0, ttft_budget_ms=800.0
    ) == now + 800.0

    hi = effective_deadline_ms(now, "high")
    no = effective_deadline_ms(now, "normal")
    lo = effective_deadline_ms(now, "low")
    assert hi < no < lo
    assert no == now + 600_000.0
    assert no - hi == 60_000.0 and lo - no == 60_000.0
    # unknown priority degrades to normal
    assert effective_deadline_ms(now, "vip") == no
    # a hinted low-priority request still beats an unhinted high one:
    # explicit budgets always dominate the synthetic ladder
    assert effective_deadline_ms(now, "low", deadline_ms=1000.0) < hi
    # determinism: same inputs, same key
    assert effective_deadline_ms(now, "low", deadline_ms=1000.0) == (
        effective_deadline_ms(now, "low", deadline_ms=1000.0)
    )


# -- knobs: env + CLI ---------------------------------------------------------


@pytest.mark.fast
def test_admission_knob_resolution(monkeypatch):
    for name in (
        "DLLAMA_ADMISSION_PREDICT", "DLLAMA_ADMISSION_MAX_WAIT_MS",
        "DLLAMA_DEADLINE_DEFAULT_MS", "DLLAMA_DEADLINE_PRIORITY_STEP_MS",
    ):
        monkeypatch.delenv(name, raising=False)
    assert resolve_admission_knobs(None, None) == (False, 30_000)
    assert resolve_deadline_knobs(None, None) == (600_000, 60_000)

    monkeypatch.setenv("DLLAMA_ADMISSION_PREDICT", "1")
    monkeypatch.setenv("DLLAMA_ADMISSION_MAX_WAIT_MS", "9000")
    monkeypatch.setenv("DLLAMA_DEADLINE_DEFAULT_MS", "120000")
    monkeypatch.setenv("DLLAMA_DEADLINE_PRIORITY_STEP_MS", "5000")
    assert resolve_admission_knobs(None, None) == (True, 9000)
    assert resolve_deadline_knobs(None, None) == (120_000, 5000)
    # explicit flags beat the env
    assert resolve_admission_knobs(False, 1000) == (False, 1000)
    assert resolve_deadline_knobs(60_000, 100) == (60_000, 100)
    monkeypatch.setenv("DLLAMA_ADMISSION_PREDICT", "off")
    assert resolve_admission_knobs(None, None)[0] is False


@pytest.mark.fast
def test_admission_cli_flags():
    import argparse

    from dllama_tpu.cli import add_engine_args

    parser = argparse.ArgumentParser()
    add_engine_args(parser)
    args = parser.parse_args([
        "--admission-predict",
        "--admission-max-wait-ms", "5000",
        "--deadline-default-ms", "100000",
        "--deadline-priority-step-ms", "1000",
    ])
    assert args.admission_predict is True
    assert args.admission_max_wait_ms == 5000
    assert args.deadline_default_ms == 100_000
    assert args.deadline_priority_step_ms == 1000
    # absent flags stay None so env/default resolution applies
    blank = parser.parse_args([])
    assert blank.admission_predict is None
    assert blank.admission_max_wait_ms is None


# -- router: Retry-After propagation + shed backoff ---------------------------


@pytest.mark.fast
def test_router_retry_after_parse():
    from dllama_tpu.fleet.router import _retry_after_s

    assert _retry_after_s("3") == 3
    assert _retry_after_s(5) == 5
    assert _retry_after_s("2.7") == 2
    assert _retry_after_s(None) == 2
    assert _retry_after_s("abc") == 2
    assert _retry_after_s("0") == 2
    assert _retry_after_s(None, default=7) == 7


@pytest.mark.fast
def test_router_shed_backoff_ordering(tmp_path):
    """A replica that shed with Retry-After is demoted to the spill
    tail (soonest-free first) until its self-predicted busy window
    expires; nothing is ever dropped, and the all-shed 503 quotes the
    smallest non-expired wait."""
    from dllama_tpu.fleet.replicas import ReplicaRegistry
    from dllama_tpu.fleet.router import RouterState

    tp_ = str(tmp_path / "t.t")
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    reg = ReplicaRegistry(
        {"a": "http://a", "b": "http://b", "c": "http://c"},
        fetch=lambda url: {"status": "ok"},
    )
    state = RouterState(reg, Tokenizer(tp_))

    assert state.min_shed_wait_s() is None
    assert state.order_by_backoff(["a", "b", "c"]) == ["a", "b", "c"]

    state.note_shed("a", "30")
    state.note_shed("b", 10)
    # free replica keeps affinity order; busy ones spill soonest-free
    assert state.order_by_backoff(["a", "b", "c"]) == ["c", "b", "a"]
    assert state.shed_wait_s("c") == 0.0
    assert 0.0 < state.shed_wait_s("b") <= 10.0
    assert state.shed_wait_s("b") < state.shed_wait_s("a")
    # the honest all-shed Retry-After: ceil of the smallest live wait
    assert 1 <= state.min_shed_wait_s() <= 10


# -- scheduler level: EDF, infeasible-reject, byte-identity -------------------


@pytest.fixture(scope="module")
def tiny_paths(tmp_path_factory):
    d = tmp_path_factory.mktemp("predadm")
    mp, tp_ = str(d / "m.m"), str(d / "t.t")
    make_tiny_model(mp, cfg=CFG)
    make_tiny_tokenizer(tp_, chat_template="<|start_header_id|>")
    return mp, tp_


@pytest.fixture(scope="module")
def pred_state(tiny_paths):
    """A predictive-mode scheduler ApiState driven directly (no HTTP)."""
    mp, tp_ = tiny_paths
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=2,
    )
    state = ApiState(
        engine, tok, lane_block_size=4, admission_chunk=6,
        admission_predict=True,
    )
    assert state.scheduler is not None and state.predictor is not None
    return state


def _params(content, max_tokens=3, **kw):
    return InferenceParams(
        messages=[ChatMessage("user", content)], max_tokens=max_tokens,
        temperature=0.0, **kw,
    )


def _drain(job, timeout=300):
    deltas = []
    deadline = time.time() + timeout
    while True:
        kind, payload = job.events.get(
            timeout=max(0.1, deadline - time.time())
        )
        if kind == "delta":
            deltas.append(payload)
        elif kind == "done":
            return "".join(deltas), payload
        else:
            raise AssertionError(f"job errored: {payload}")


def _wait_lanes(state, n, timeout=300):
    sched = state.scheduler
    deadline = time.time() + timeout
    while time.time() < deadline:
        with sched.cv:
            active = sum(1 for ls in sched.lanes if ls is not None)
        if active >= n:
            return
        time.sleep(0.02)
    raise AssertionError(f"{n} lanes never became active")


def test_edf_ordering_deterministic(pred_state):
    """Three requests queued while every lane is busy admit in EDF
    order — tightest deadline first, unhinted synthetic deadlines last —
    regardless of submit order."""
    state = pred_state
    sched, rec = state.scheduler, state.recorder

    blockers = [
        sched.submit(_params(f"edf blocker {i}", max_tokens=220))
        for i in range(2)
    ]
    _wait_lanes(state, 2)
    base = rec.total_recorded

    # submit in REVERSE deadline order; distinct prompt lengths map the
    # admit events back to jobs (the admit record carries n_prompt)
    late = sched.submit(_params("e " * 30, priority="high"))  # unhinted
    mid = sched.submit(_params("dd " * 18, deadline_ms=150_000.0))
    tight = sched.submit(_params("c " * 6, deadline_ms=50_000.0))
    assert tight.edf_deadline_ms < mid.edf_deadline_ms < late.edf_deadline_ms

    for b in blockers:
        b.cancelled = True
        _drain(b)
    order = []
    for job in (tight, mid, late):
        _drain(job)
    n_by_job = {
        tight.n_prompt_tokens: "tight",
        mid.n_prompt_tokens: "mid",
        late.n_prompt_tokens: "late",
    }
    assert len(n_by_job) == 3, "prompts must tokenize to distinct lengths"
    for ev in rec.events():
        if ev["seq"] > base and ev["kind"] == "admit":
            if ev["n_prompt"] in n_by_job:
                order.append(n_by_job[ev["n_prompt"]])
    assert order == ["tight", "mid", "late"], order


def test_infeasible_rejected_before_admission(pred_state):
    """A hinted request whose budget cannot be met is refused by the
    pre-queue gate: structured reason, derived Retry-After, rejection
    counter bumped, and the scheduler queue never sees it."""
    state = pred_state
    sched = state.scheduler
    before = dict(state.m_admission_rejected.child_values())
    q_before = len(sched.pending)

    decision = state.admission_decision(
        "normal", _params("budget doom", ttft_budget_ms=0.0001)
    )
    assert decision is not None
    reason, retry_after = decision
    assert reason == "infeasible"
    assert isinstance(retry_after, int) and retry_after >= 1
    after = state.m_admission_rejected.child_values()
    assert after[("infeasible",)] == before.get(("infeasible",), 0) + 1
    assert len(sched.pending) == q_before  # never queued

    # unhinted requests are NEVER infeasible-rejected (PR 12 ladder)
    assert state.admission_decision("normal", _params("no hints")) is None
    # predictive off: the gate is exactly the reactive ladder
    state.admission_predict = False
    try:
        assert state.admission_decision(
            "normal", _params("budget doom", ttft_budget_ms=0.0001)
        ) is None
    finally:
        state.admission_predict = True


def test_state_retry_after_monotonic_in_queue_depth(pred_state):
    """predicted_retry_after() derives from live occupancy: parking
    opaque sentinels in the pending queue (no cv notify — the idle
    scheduler never observes them) must never DECREASE the advertised
    wait."""
    state = pred_state
    sched = state.scheduler
    ras = []
    sentinels = []
    try:
        for extra in (0, 200, 2000):
            with sched.cv:
                while len(sentinels) < extra:
                    s = object()
                    sentinels.append(s)
                    sched.pending.append(s)
            ras.append(state.predicted_retry_after())
    finally:
        with sched.cv:
            for s in sentinels:
                sched.pending.remove(s)
    assert all(r >= 1 for r in ras)
    assert ras == sorted(ras), ras
    assert ras[-1] <= max(1, state.admission_max_wait_ms // 1000)


def test_greedy_bytes_identical_predictive_on_off(pred_state):
    """The acceptance invariant: prediction only gates and orders work.
    The same greedy request produces byte-identical output with the
    controller on, off, and with deadline hints attached."""
    state = pred_state
    sched = state.scheduler

    text_on, reason = _drain(
        sched.submit(_params("determinism probe", max_tokens=16))
    )
    assert reason in ("stop", "length")
    state.admission_predict = False
    try:
        text_off, _ = _drain(
            sched.submit(_params("determinism probe", max_tokens=16))
        )
    finally:
        state.admission_predict = True
    text_hinted, _ = _drain(
        sched.submit(_params(
            "determinism probe", max_tokens=16, deadline_ms=90_000.0,
        ))
    )
    assert text_on == text_off == text_hinted


def test_prediction_error_is_tracked(pred_state):
    """Admission records a forecast; finish scores it: the error ring
    feeds /v1/debug/admission and the predict-error histogram has
    samples with finite values."""
    state = pred_state
    _drain(state.scheduler.submit(_params("score me", max_tokens=8)))
    stats = state.predict_error_stats()
    assert stats["n"] >= 1
    assert stats["p50_ms"] is not None and math.isfinite(stats["p50_ms"])
    assert stats["p95_ms"] is not None and math.isfinite(stats["p95_ms"])
    snap = state.predictor.snapshot()
    assert snap["n_observations"] >= 1
    ttft_child = state.m_predict_error.labels(signal="ttft")
    assert ttft_child.count >= 1


# -- server level: deterministic preemption + park/resume byte parity ---------

LOW_PROMPTS = [
    "tell me a long winding story about lane zero",
    "tell me a long winding story about lane one",
]
HIGH_PROMPT = "urgent deadline question"


@pytest.fixture(scope="module")
def preempt_server(tiny_paths):
    """2-lane pool-native predictive server; max_streams == lanes keeps
    PR 16 oversubscription parking OUT of the picture, so the only park
    path left is deadline preemption."""
    mp, tp_ = tiny_paths
    tok = Tokenizer(tp_)
    engine = InferenceEngine(
        mp, tokenizer=tok, tp=1, dtype=jnp.float32, temperature=0.0, seed=3,
        batch_size=2,
    )
    srv = serve(
        engine, tok, host="127.0.0.1", port=0,
        lane_block_size=4, kv_page_size=4, kv_native=True, max_streams=2,
        admission_predict=True,
    )
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv
    srv.shutdown()


def _url(srv):
    return f"http://127.0.0.1:{srv.server_address[1]}"


def _chat(srv, content, max_tokens=40, priority=None, deadline_ms=None,
          ttft_budget_ms=None, headers=None):
    payload = {
        "model": "m", "stream": False, "max_tokens": max_tokens,
        "temperature": 0,
        "messages": [{"role": "user", "content": content}],
    }
    if priority is not None:
        payload["priority"] = priority
    if deadline_ms is not None:
        payload["deadline_ms"] = deadline_ms
    if ttft_budget_ms is not None:
        payload["ttft_budget_ms"] = ttft_budget_ms
    hdrs = {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(
        _url(srv) + "/v1/chat/completions",
        data=json.dumps(payload).encode(), headers=hdrs, method="POST",
    )
    with urllib.request.urlopen(req, timeout=600) as r:
        data = json.loads(r.read())
    choice = data["choices"][0]
    assert choice["finish_reason"] in ("stop", "length")
    return choice["message"]["content"]


def _get_json(srv, path):
    with urllib.request.urlopen(_url(srv) + path, timeout=30) as r:
        return json.loads(r.read())


def test_debug_admission_endpoint(preempt_server):
    snap = _get_json(preempt_server, "/v1/debug/admission")
    assert snap["predictive"] is True
    assert snap["max_wait_ms"] >= 1
    assert snap["retry_after_s"] >= 1
    assert set(snap["occupancy"]) >= {
        "lanes_total", "active_lanes", "queue_depth", "oversubscription",
    }
    assert set(snap["predictor"]) >= {
        "ttft_correction", "tpot_correction", "prefill_chunk_s",
    }
    assert snap["prediction_error"]["n"] >= 0


def test_deadline_header_infeasible_reject(preempt_server):
    """The fleet router forwards x-dllama-deadline-ms; a relayed budget
    that cannot be met is shed as infeasible with a derived
    Retry-After — no body hint needed."""
    state = preempt_server.state
    before = dict(state.m_admission_rejected.child_values())
    with pytest.raises(urllib.error.HTTPError) as exc:
        _chat(
            preempt_server, "relayed doomed budget",
            headers={"x-dllama-deadline-ms": "0.0001"},
        )
    e = exc.value
    assert e.code == 429
    err = json.loads(e.read())["error"]
    assert "infeasible" in err["message"]
    assert err["retryable"] is True
    assert int(e.headers["Retry-After"]) >= 1
    after = state.m_admission_rejected.child_values()
    assert after[("infeasible",)] == before.get(("infeasible",), 0) + 1


def test_preemption_parks_victim_byte_identical(preempt_server, monkeypatch):
    """The seeded preemption test: two low-priority greedy streams hold
    both lanes past the no-thrash progress floor; a deadline-hinted
    high-priority request arrives; the forecast (made deterministic)
    says it blows its budget waiting but meets it on a freed lane — so
    the scheduler parks one low stream through the PR 16 contract. All
    three streams complete byte-identical to their uncontended solo
    runs: the victim was paused, never restarted."""
    srv = preempt_server
    state = srv.state
    sched = state.scheduler

    solo_low = [_chat(srv, p, max_tokens=48) for p in LOW_PROMPTS]
    solo_high = _chat(srv, HIGH_PROMPT, max_tokens=8)
    base_resumes = state.m_stream_resumes.value
    base_events = state.recorder.total_recorded

    def fake_predict(n_tok, occ, matched_tokens=0):
        # deterministic forecast: infeasible while both lanes are busy
        # and the request waits in queue, trivially feasible otherwise
        # (the freed-lane forecast zeroes queue_depth and drops a lane)
        busy = occ.active_lanes >= 2 and occ.queue_depth > 0
        return Prediction(
            ttft_ms=1e9 if busy else 1.0, tpot_ms=1.0,
            queue_wait_ms=0.0, prefill_chunks=1,
        )

    monkeypatch.setattr(state.predictor, "predict", fake_predict)

    results = [None, None]

    def run_low(i):
        results[i] = _chat(srv, LOW_PROMPTS[i], max_tokens=48, priority="low")

    threads = [
        threading.Thread(target=run_low, args=(i,)) for i in range(2)
    ]
    for t in threads:
        t.start()
    # wait until both lanes are decoding with more than one block of
    # progress (the preemption victim floor)
    deadline = time.time() + 300
    while time.time() < deadline:
        with sched.cv:
            active = [
                i for i, ls in enumerate(sched.lanes) if ls is not None
            ]
            ready = (
                len(active) == 2
                and all(
                    sched._progress[i] > sched.block_size for i in active
                )
            )
        if ready:
            break
        time.sleep(0.01)
    else:
        raise AssertionError("low streams never filled both lanes")

    high = _chat(
        srv, HIGH_PROMPT, max_tokens=8, priority="high",
        deadline_ms=600_000.0,
    )
    for t in threads:
        t.join(timeout=600)

    assert high == solo_high
    assert results == solo_low, "preempted stream diverged after resume"

    pre = {
        k: v for k, v in state.m_preemptions.child_values().items()
    }
    assert sum(pre.values()) >= 1, "no preemption fired"
    assert pre.get(("priority",), 0) >= 1
    assert state.m_stream_resumes.value > base_resumes
    kinds = [
        e["kind"] for e in state.recorder.events()
        if e["seq"] > base_events
    ]
    assert "stream_preempt" in kinds
    assert "stream_park" in kinds and "stream_resume" in kinds

    # fully drained: no parked streams, no queue, pool invariant holds
    deadline = time.time() + 60
    while time.time() < deadline and (
        any(sched.lanes) or sched.admitting or sched.pending
    ):
        time.sleep(0.02)
    assert sched._n_parked == 0 and not sched.pending
    state.kv_manager.check()
