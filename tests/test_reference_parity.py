"""End-to-end parity against the actual reference C++ implementation.

Builds b4rtaz/distributed-llama's `dllama` binary from the read-only mount
(out-of-tree, cached under /tmp/refbuild), generates a tiny f32 model +
tokenizer with OUR writers, runs greedy inference on BOTH implementations,
and requires byte-identical per-token output.

This is the strongest possible cross-implementation check (SURVEY.md §4):
it covers the `.m`/`.t` wire formats, BPE encoding, the full transformer
numerics (prefill + decode argmax stream), and the streaming UTF-8 display
semantics in one shot. Skipped when the reference mount or a toolchain is
unavailable.

Reference quirk discovered while building this test: `dllama inference`
seeds the decode loop with `inputTokens[pos + 1]` (dllama.cpp:54) — one
slot PAST the prompt, which holds a stale intermediate of the in-place BPE
merge loop rather than the last prompt token. (For some prompts the stale
slot happens to contain the right token, which is why the bug is invisible
in casual use.) Our framework feeds the last prompt token (the correct
semantics, matching HF transformers); the comparison below replays the
reference's stale-seed behavior via `reference_decode_seed` so the
numerics can still be compared token-for-token.
"""

import os
import re
import shutil
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.formats import FloatType
from dllama_tpu.formats.model_file import LlmArch
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.tokenizer import Tokenizer

from helpers import REPO_ROOT, make_tiny_model, make_tiny_tokenizer

# heavyweight end-to-end surface: run with the full suite / CI;
# deselect via -m 'not slow' for the fast local loop
pytestmark = pytest.mark.slow

REFERENCE = "/root/reference"
BUILD_DIR = "/tmp/refbuild"  # session cache; the mount is immutable


@pytest.fixture(scope="module")
def dllama_binary():
    if not os.path.isdir(os.path.join(REFERENCE, "src")):
        pytest.skip("reference source not mounted")
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no C++ toolchain")
    binary = os.path.join(BUILD_DIR, "dllama")
    if not os.path.isfile(binary):
        if not os.path.isdir(BUILD_DIR):
            shutil.copytree(REFERENCE, BUILD_DIR)
        r = subprocess.run(
            ["make", "dllama"], cwd=BUILD_DIR, capture_output=True, timeout=600
        )
        if r.returncode != 0 or not os.path.isfile(binary):
            pytest.skip(f"reference build failed: {r.stderr[-500:]}")
    return binary


def reference_decode_seed(tok: Tokenizer, prompt: str) -> int:
    """The token the reference actually feeds at the first decode step:
    simulate its encode buffer (greedy byte accumulation, then in-place
    best-score pair merging with left shifts, tokenizer.cpp:311-390) and
    return the stale slot at index nTokens (dllama.cpp:54)."""
    buf: list[int] = []
    if tok.add_bos and tok.bos_id >= 0:
        buf.append(tok.bos_id)
    raw = prompt.encode("utf-8")
    acc = bytearray()
    i = 0
    while i < len(raw):
        sid = tok.find_special_token_start_with(raw, i)
        if sid >= 0 and not acc:
            buf.append(sid)
            i += len(tok.vocab[sid])
            continue
        acc.append(raw[i])
        i += 1
        tid = tok.find_regular_token(bytes(acc))
        if tid != -1:
            buf.append(tid)
            acc.clear()
    n = len(buf)
    while True:
        best_score, best_id, best_idx = -1e10, -1, -1
        for j in range(n - 1):
            mid = tok.find_regular_token(tok.vocab[buf[j]] + tok.vocab[buf[j + 1]])
            if mid != -1 and tok.scores[mid] > best_score:
                best_score, best_id, best_idx = tok.scores[mid], mid, j
        if best_idx == -1:
            break
        buf[best_idx] = best_id
        for j in range(best_idx + 1, n - 1):
            buf[j] = buf[j + 1]
        n -= 1
    # buf[n] is the stale slot (zero-initialized if never written)
    return buf[n] if n < len(buf) else 0


def reference_render(tok: Tokenizer, ids: list[int]) -> str:
    """The reference's per-token display (Tokenizer::decode + detokUtf8,
    src/tokenizer.cpp:224-309 + dllama.cpp:88-95): '~' for null pieces,
    partial UTF-8 held across tokens, invalid bytes kept in the buffer and
    materialized as one U+FFFD only once valid text follows (consecutive
    invalid bytes collapse — the recovery resets the output cursor to the
    last checkpoint). BOS renders null; EOS flushes the raw pending buffer;
    the C scan stops at a NUL byte."""
    out = []
    pending = b""
    for t in ids:
        if t == tok.bos_id:
            out.append(None)
            continue
        if tok.is_eos(t):
            out.append(pending.decode("utf-8", "replace") if pending else None)
            continue
        buf = pending + tok.vocab[t]
        res = b""
        checkpoint = 0
        checkpoint_src = 0
        src = 0
        expect = 0
        while src < len(buf) and buf[src] != 0:  # C scan stops at NUL
            c = buf[src]
            recovery = False
            if expect:
                if (c & 0xC0) == 0x80:
                    res += bytes([c])
                    src += 1
                    expect -= 1
                else:
                    recovery = True
            elif c <= 0x7F:
                res += bytes([c])
                src += 1
            elif 0xC0 <= c <= 0xF7:
                res += bytes([c])
                src += 1
                expect = 1 if c <= 0xDF else (2 if c <= 0xEF else 3)
            else:
                recovery = True
            if not recovery:
                if not expect:
                    checkpoint = len(res)
                    checkpoint_src = src
            else:
                if expect:
                    expect = 0
                else:
                    src += 1
                res = res[:checkpoint] + b"\xef\xbf\xbd"
                # checkpoint intentionally NOT advanced — the reference only
                # commits the replacement char when valid text follows
        emitted = res[:checkpoint]
        pending = buf[checkpoint_src:src]  # a scanned NUL byte vanishes
        out.append(emitted.decode("utf-8") if emitted else None)
    return "".join(p if p is not None else "~" for p in out)


# fixed-width per-token prefix printed by the reference (dllama.cpp:88-95)
_PRED_PREFIX = re.compile(
    r"Pred\s*\d+ ms Sync\s*\d+ ms \| Sent\s*\d+ kB Recv\s*\d+ kB \| "
)


def extract_reference_pieces(stdout: str) -> str:
    """Concatenated per-token text from the reference's 🔶 lines. Splitting
    on the 🔶 marker (not on newlines) keeps pieces that themselves contain
    newlines intact; each printf appends exactly one trailing newline."""
    chunks = stdout.split("🔶 ")[1:]
    pieces = []
    for chunk in chunks:
        m = _PRED_PREFIX.match(chunk)
        if not m:
            break  # end of the prediction block (summary follows)
        body = chunk[m.end():]
        # the final chunk carries the run summary after its newline
        piece = body.split("\n\nEvaluation", 1)[0]
        if piece.endswith("\n"):
            piece = piece[:-1]  # printf's own trailing newline
        pieces.append(piece)
    return "".join(pieces)


PARITY_CFG = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=8, n_kv_heads=4,
                  head_dim=16, vocab_size=288, seq_len=96)
# the MoE variant adds expert routing on top of the same attention shapes
PARITY_CFG_MOE = dict(PARITY_CFG, moe_hidden_dim=96, n_experts=4,
                      n_active_experts=2)


def make_parity_fixture(tmp_path, seed, arch=LlmArch.LLAMA):
    # NB: f32 weights — the reference can't run QWEN3_MOE at f32 sync (its
    # REPEAT_Z op has no F32 kernel), so the MoE test builds its own Q40
    # model instead of using this fixture.
    mp = str(tmp_path / "m.m")
    tp = str(tmp_path / "t.t")
    make_tiny_model(
        mp, arch=arch, weight_type=FloatType.F32, cfg=dict(PARITY_CFG), seed=seed
    )
    make_tiny_tokenizer(tp, pad_to=PARITY_CFG["vocab_size"])
    return mp, tp


def run_parity(dllama_binary, tmp_path, arch, seed, prompt, steps):
    mp, tp = make_parity_fixture(tmp_path, seed, arch)

    r = subprocess.run(
        [dllama_binary, "inference", "--model", mp, "--tokenizer", tp,
         "--prompt", prompt, "--steps", str(steps), "--temperature", "0.0",
         "--nthreads", "1", "--buffer-float-type", "f32"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-800:]
    ref_text = extract_reference_pieces(r.stdout)

    tok = Tokenizer(tp)
    prompt_tokens = tok.encode(prompt, is_start=True, add_special_tokens=True)
    engine = InferenceEngine(mp, tp=1, dtype=jnp.float32, temperature=0.0)
    engine.prefill(prompt_tokens)
    pos = len(prompt_tokens) - 1
    token = reference_decode_seed(tok, prompt)  # replay the reference quirk
    ids = []
    while pos < min(engine.header.seq_len, steps):
        token, _ = engine.decode_step(token, pos)
        pos += 1
        ids.append(token)

    ours = reference_render(tok, ids)
    assert ours == ref_text, f"\nref:  {ref_text!r}\nours: {ours!r}\nids: {ids}"


def test_greedy_stream_matches_reference(dllama_binary, tmp_path):
    run_parity(dllama_binary, tmp_path, LlmArch.LLAMA, 11, "hello world", 20)


def test_greedy_stream_matches_reference_qwen3(dllama_binary, tmp_path):
    """Same cross-binary check for the Qwen3 arch (falcon RoPE, QK-norm)."""
    run_parity(dllama_binary, tmp_path, LlmArch.QWEN3, 13, "the world", 16)


def test_greedy_stream_matches_reference_fresh(dllama_binary, tmp_path):
    """A third seed/prompt to guard against fixture-tuned coincidences."""
    run_parity(dllama_binary, tmp_path, LlmArch.LLAMA, 23, "hi there world", 18)


def test_perplexity_matches_reference(dllama_binary, tmp_path):
    """Perplexity (teacher-forced NLL) parity — the numerical-quality oracle
    (reference: dllama.cpp:132-172) compared across implementations."""
    mp, tp = make_parity_fixture(tmp_path, seed=31)
    prompt = "hello world the world hello"

    r = subprocess.run(
        [dllama_binary, "perplexity", "--model", mp, "--tokenizer", tp,
         "--prompt", prompt, "--nthreads", "1", "--buffer-float-type", "f32"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-800:]
    m = re.search(r"perplexity: ([0-9.]+)", r.stdout)
    assert m, r.stdout[-500:]
    ref_ppl = float(m.group(1))

    cli = subprocess.run(
        [sys.executable, "-m", "dllama_tpu", "perplexity", "--model", mp,
         "--tokenizer", tp, "--prompt", prompt, "--dtype", "f32", "--tp", "1"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=REPO_ROOT,
    )
    assert cli.returncode == 0, cli.stderr[-800:]
    m2 = re.search(r"perplexity: ([0-9.]+)", cli.stdout)
    assert m2, cli.stdout[-500:]
    ours_ppl = float(m2.group(1))
    assert abs(ours_ppl - ref_ppl) / ref_ppl < 2e-3, (ours_ppl, ref_ppl)


def test_perplexity_close_reference_qwen3_moe(dllama_binary, tmp_path):
    """Cross-binary check for Qwen3-MoE (gate softmax/top-k/expert SwiGLU).

    The reference cannot run MoE at f32 sync type — its REPEAT_Z op only
    has a Q80-output kernel (`Unsupported CPU op code: REPEAT_Z, quant:
    F32_F32_F32`), an undocumented gap behind the README's "q40 weights +
    q80 buffer" rule — so byte-exact greedy parity is impossible: with
    q40+q80 the reference quantizes expert-matmul activations to 8 bits,
    ours computes them dense. Perplexity with a quantization-noise
    tolerance still validates the routing + expert pipeline end-to-end."""
    mp = str(tmp_path / "m.m")
    tp = str(tmp_path / "t.t")
    make_tiny_model(mp, arch=LlmArch.QWEN3_MOE, weight_type=FloatType.Q40,
                    cfg=dict(PARITY_CFG_MOE), seed=17)
    make_tiny_tokenizer(tp, pad_to=PARITY_CFG["vocab_size"])
    prompt = "hello world the world"

    r = subprocess.run(
        [dllama_binary, "perplexity", "--model", mp, "--tokenizer", tp,
         "--prompt", prompt, "--nthreads", "1", "--buffer-float-type", "q80"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, (r.stdout[-400:], r.stderr[-400:])
    m = re.search(r"perplexity: ([0-9.]+)", r.stdout)
    assert m, r.stdout[-500:]
    ref_ppl = float(m.group(1))

    cli = subprocess.run(
        [sys.executable, "-m", "dllama_tpu", "perplexity", "--model", mp,
         "--tokenizer", tp, "--prompt", prompt, "--dtype", "f32", "--tp", "1"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=REPO_ROOT,
    )
    assert cli.returncode == 0, cli.stderr[-800:]
    m2 = re.search(r"perplexity: ([0-9.]+)", cli.stdout)
    assert m2, cli.stdout[-500:]
    ours_ppl = float(m2.group(1))
    # Q80 activation quantization in the reference's expert matmuls is the
    # only systematic difference; a few percent covers it
    assert abs(ours_ppl - ref_ppl) / ref_ppl < 0.05, (ours_ppl, ref_ppl)


# ~100M-param stress (VERDICT r4 #5): realistic depth/width/GQA — drift
# that 2-layer fixtures can't catch (accumulation depth, RoPE at real
# dims, 256-token error growth).
MID_CFG = dict(dim=768, hidden_dim=2560, n_layers=12, n_heads=12,
               n_kv_heads=4, head_dim=64, vocab_size=4096, seq_len=512)


def _mid_prompt(n_words: int = 60) -> str:
    words = ["hello", "world", "the", "hi", "there"]
    import random

    rng = random.Random(7)
    return " ".join(rng.choice(words) for _ in range(n_words))


def test_midsize_greedy_stream_256_matches_reference(dllama_binary, tmp_path):
    """256-token greedy stream on a ~100M-param f32 model vs the reference
    binary. Token-for-token equality required; a divergence is excused
    ONLY if our top-2 logit gap at that step is within f32 cross-
    implementation noise (argmax tie — both orders defensible), and the
    matched prefix must already be deep enough to have teeth."""
    from dllama_tpu.models import forward, init_kv_cache, load_params
    from dllama_tpu.formats.model_file import ModelReader

    mp = str(tmp_path / "mid.m")
    tp = str(tmp_path / "mid.t")
    make_tiny_model(mp, weight_type=FloatType.F32, cfg=dict(MID_CFG), seed=41)
    make_tiny_tokenizer(tp, pad_to=MID_CFG["vocab_size"])
    prompt = _mid_prompt(12)
    steps = 280  # ~256 decode tokens after the prompt

    r = subprocess.run(
        [dllama_binary, "inference", "--model", mp, "--tokenizer", tp,
         "--prompt", prompt, "--steps", str(steps), "--temperature", "0.0",
         "--nthreads", "1", "--buffer-float-type", "f32"],
        capture_output=True, text=True, timeout=1200,
    )
    assert r.returncode == 0, r.stderr[-800:]
    ref_text = extract_reference_pieces(r.stdout)

    tok = Tokenizer(tp)
    prompt_tokens = tok.encode(prompt, is_start=True, add_special_tokens=True)
    reader = ModelReader(mp)
    h = reader.header
    params = load_params(reader)  # f32 dense
    cache = init_kv_cache(h, 1)
    arr = jnp.asarray([prompt_tokens], jnp.int32)
    _, cache = forward(params, h, arr, jnp.int32(0), cache)
    pos = len(prompt_tokens) - 1
    token = reference_decode_seed(tok, prompt)
    ids, gaps = [], []
    while pos < min(h.seq_len, steps):
        lg, cache = forward(
            params, h, jnp.asarray([[token]], jnp.int32), jnp.int32(pos),
            cache,
        )
        row = np.asarray(lg)[0, -1].astype(np.float64)
        top2 = np.partition(row, -2)[-2:]
        gaps.append(float(top2[1] - top2[0]))
        token = int(row.argmax())
        pos += 1
        ids.append(token)

    ours = reference_render(tok, ids)
    if ours != ref_text:
        # locate the first diverging rendered piece -> step index
        ref_pieces = ref_text
        k = 0
        while k < min(len(ours), len(ref_pieces)) and ours[k] == ref_pieces[k]:
            k += 1
        # map char offset back to a conservative step index: count pieces
        # fully matched so far
        step = 0
        for i, t in enumerate(ids):
            if len(reference_render(tok, ids[: i + 1])) > k:
                step = i
                break
        assert gaps[step] < 1e-3, (
            f"diverged at step {step} with top-2 gap {gaps[step]:.2e} "
            f"(not a tie)\nref:  {ref_text[:400]!r}\nours: {ours[:400]!r}"
        )
        assert step >= 32, (
            f"diverged too early (step {step}) to count as drift-free"
        )


def test_midsize_q40_perplexity_nll_bound(dllama_binary, tmp_path):
    """Perplexity on the ~100M model with Q40 weights: the reference runs
    Q40 x Q80 integer dots, ours dequantizes to f32 — the NLL must agree
    within the activation-quantization noise bound at depth 12."""
    mp = str(tmp_path / "midq.m")
    tp = str(tmp_path / "midq.t")
    make_tiny_model(mp, weight_type=FloatType.Q40, cfg=dict(MID_CFG), seed=43)
    make_tiny_tokenizer(tp, pad_to=MID_CFG["vocab_size"])
    prompt = _mid_prompt(60)

    r = subprocess.run(
        [dllama_binary, "perplexity", "--model", mp, "--tokenizer", tp,
         "--prompt", prompt, "--nthreads", "1",
         "--buffer-float-type", "q80"],
        capture_output=True, text=True, timeout=1200,
    )
    assert r.returncode == 0, r.stderr[-800:]
    m = re.search(r"perplexity: ([0-9.]+)", r.stdout)
    assert m, r.stdout[-500:]
    ref_nll = float(np.log(float(m.group(1))))  # nats/token

    cli = subprocess.run(
        [sys.executable, "-m", "dllama_tpu", "perplexity", "--model", mp,
         "--tokenizer", tp, "--prompt", prompt, "--dtype", "f32",
         "--tp", "1", "--weight-format", "q40"],
        capture_output=True, text=True, timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd=REPO_ROOT,
    )
    assert cli.returncode == 0, cli.stderr[-800:]
    m2 = re.search(r"perplexity: ([0-9.]+)", cli.stdout)
    assert m2, cli.stdout[-500:]
    ours_nll = float(np.log(float(m2.group(1))))
    # per-token NLL delta bound: Q80 activation quantization noise at
    # depth 12 stays well under 0.02 nats on this fixture
    assert abs(ours_nll - ref_nll) < 0.02, (ours_nll, ref_nll)
