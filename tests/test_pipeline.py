"""Pipeline parallelism: forward_pp vs the single-device forward.

The reference has no pipeline strategy (SURVEY.md §2 checklist: TP only,
bounded by nNodes <= nKvHeads); these tests pin the pp stage schedule —
identical logits AND identical per-layer cache commits — on the virtual
CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.formats import FloatType, ModelReader
from dllama_tpu.models import forward, init_kv_cache, load_params
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.parallel.pipeline import forward_pp, validate_pp

from helpers import make_tiny_model

CFG4 = dict(dim=64, hidden_dim=160, n_layers=4, n_heads=4, n_kv_heads=2,
            head_dim=16, vocab_size=256, seq_len=64)
TOKENS = [3, 17, 92, 5, 44, 120, 7, 3]


def _params(tmp_path, weight_format="dense", fuse=0, cfg=None):
    path = str(tmp_path / "m.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=11, cfg=cfg or CFG4)
    r = ModelReader(path)
    p = load_params(r, weight_format=weight_format, fuse=fuse)
    return r.header, p


@pytest.mark.parametrize("pp", [2, 4])
def test_forward_pp_matches_single(tmp_path, pp):
    h, params = _params(tmp_path)
    mesh = make_mesh(pp=pp)
    tokens = jnp.asarray([TOKENS], jnp.int32)

    lg_ref, cache_ref = forward(
        params, h, tokens, jnp.int32(0), init_kv_cache(h, 1)
    )
    lg_pp, cache_pp = forward_pp(
        params, h, tokens, jnp.int32(0), init_kv_cache(h, 1), mesh
    )
    np.testing.assert_allclose(
        np.asarray(lg_pp), np.asarray(lg_ref), rtol=1e-5, atol=1e-5
    )
    for k in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(cache_pp[k]), np.asarray(cache_ref[k]),
            rtol=1e-5, atol=1e-5,
        )


def test_forward_pp_decode_chain(tmp_path):
    """Greedy prefill + 6 decode steps through forward_pp must reproduce
    the single-device token stream (cache committed per stage range)."""
    h, params = _params(tmp_path)
    mesh = make_mesh(pp=2)
    prompt = TOKENS[:4]

    def run(fwd, **kw):
        cache = init_kv_cache(h, 1)
        toks = jnp.asarray([prompt], jnp.int32)
        logits, cache = fwd(params, h, toks, jnp.int32(0), cache, **kw)
        out = [int(jnp.argmax(logits[0, -1]))]
        pos = len(prompt)
        for _ in range(6):
            logits, cache = fwd(
                params, h, jnp.asarray([[out[-1]]], jnp.int32),
                jnp.int32(pos), cache, **kw,
            )
            out.append(int(jnp.argmax(logits[0, -1])))
            pos += 1
        return out

    expected = run(forward)
    got = run(forward_pp, mesh=mesh)
    assert got == expected, (got, expected)


def test_forward_pp_q40_fused(tmp_path):
    """Quantized weights with fused wqkv/w13 run stage-local inside the pp
    shard_map (mesh=None per stage -> local qmatmul) and match dense."""
    h, pq = _params(tmp_path, weight_format="q40", fuse=1)
    mesh = make_mesh(pp=2)
    tokens = jnp.asarray([TOKENS], jnp.int32)
    lg_ref, _ = forward(pq, h, tokens, jnp.int32(0), init_kv_cache(h, 1))
    lg_pp, _ = forward_pp(pq, h, tokens, jnp.int32(0), init_kv_cache(h, 1), mesh)
    np.testing.assert_allclose(
        np.asarray(lg_pp), np.asarray(lg_ref), rtol=1e-5, atol=1e-5
    )


def test_validate_pp(tmp_path):
    h, _ = _params(tmp_path)
    validate_pp(h, 2)
    validate_pp(h, 4)  # any divisor of nLayers is legal, not just 2^n
    with pytest.raises(ValueError, match=">= 1"):
        validate_pp(h, 0)
    with pytest.raises(ValueError, match="not divisible"):
        validate_pp(h, 3)  # 4 layers / 3 stages
    with pytest.raises(ValueError, match="not divisible"):
        validate_pp(h, 8)  # 4 layers / 8 stages


def test_engine_pp_matches_single_device(tmp_path):
    """The full engine path (bucketed prefill + on-device block decode)
    over pp=2 stages must reproduce the single-device token stream, for
    dense AND fused-q40 weights."""
    from dllama_tpu.runtime.engine import InferenceEngine

    path = str(tmp_path / "m.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=11, cfg=CFG4)
    prompt = [1, 2, 3, 4, 5]
    for fmt in ("dense", "q40"):
        e1 = InferenceEngine(
            path, tp=1, dtype=jnp.float32, temperature=0.0, weight_format=fmt
        )
        expected, _, _ = e1.generate(prompt, max_steps=16)
        del e1
        epp = InferenceEngine(
            path, pp=2, dtype=jnp.float32, temperature=0.0, weight_format=fmt
        )
        assert epp.mesh.shape["pp"] == 2
        got, _, _ = epp.generate(prompt, max_steps=16)
        del epp
        assert got == expected, (fmt, got, expected)


def test_engine_pp_with_lanes(tmp_path):
    """Continuous batching over pipeline stages: per-lane prefill+decode
    with pp=2 must reproduce each prompt's single-stream tokens (parked
    writes and per-lane positions flow through the stage schedule)."""
    from dllama_tpu.runtime.engine import InferenceEngine

    path = str(tmp_path / "m.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=11, cfg=CFG4)
    prompts = [[1, 2, 3, 4], [9, 8, 7, 6, 5]]
    singles = []
    e1 = InferenceEngine(path, tp=1, dtype=jnp.float32, temperature=0.0)
    for p in prompts:
        e1.reset()
        o, _, _ = e1.generate(p, max_steps=16)
        singles.append(o)
    del e1
    epp = InferenceEngine(
        path, pp=2, dtype=jnp.float32, temperature=0.0, batch_size=2
    )
    outs = epp.generate_batch(prompts, max_steps=16)
    assert outs == singles, (outs, singles)


@pytest.mark.parametrize("n_micro", [2, 4])
def test_forward_pp_sequence_microbatch(tmp_path, n_micro):
    """Sequence-wave microbatching (GPipe over the T axis): chunk c hits
    stage s only after chunks < c committed their KV there, so logits and
    caches must match the flat forward exactly for a 32-token chunk."""
    h, params = _params(tmp_path)
    mesh = make_mesh(pp=2)
    toks = (list(range(3, 35)))
    tokens = jnp.asarray([toks], jnp.int32)

    lg_ref, cache_ref = forward(
        params, h, tokens, jnp.int32(0), init_kv_cache(h, 1)
    )
    lg_pp, cache_pp = forward_pp(
        params, h, tokens, jnp.int32(0), init_kv_cache(h, 1), mesh,
        n_micro=n_micro,
    )
    np.testing.assert_allclose(
        np.asarray(lg_pp), np.asarray(lg_ref), rtol=1e-4, atol=1e-4
    )
    for k in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(cache_pp[k]), np.asarray(cache_ref[k]),
            rtol=1e-5, atol=1e-5,
        )


def test_engine_pp_micro_prefill(tmp_path):
    """A prompt long enough to trigger the microbatched prefill bucket
    (t=32 with pp=2 -> n_micro via _pp_micro when rows allow) still
    reproduces single-device tokens through the engine."""
    from dllama_tpu.runtime.engine import InferenceEngine

    path = str(tmp_path / "m.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=11, cfg=CFG4)
    prompt = list(range(2, 36))  # 34 tokens -> 32-wide bucket in play
    e1 = InferenceEngine(path, tp=1, dtype=jnp.float32, temperature=0.0)
    expected, _, _ = e1.generate(prompt, max_steps=44)
    del e1
    epp = InferenceEngine(path, pp=2, dtype=jnp.float32, temperature=0.0)
    assert epp._pp_micro(32) == 4  # 32 rows / 4 waves of 8
    got, _, _ = epp.generate(prompt, max_steps=44)
    del epp
    assert got == expected, (got, expected)


def test_engine_pp_perplexity_matches(tmp_path):
    """Chunked teacher-forced scoring through pp stages (the score path
    runs logits_mode='all' over microbatched waves) must match the
    single-device perplexity."""
    from dllama_tpu.runtime.engine import InferenceEngine

    path = str(tmp_path / "m.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=11, cfg=CFG4)
    toks = [(i * 7) % 250 + 1 for i in range(40)]
    e1 = InferenceEngine(path, tp=1, dtype=jnp.float32, temperature=0.0)
    nll1, ppl1, n1 = e1.perplexity(toks)
    del e1
    epp = InferenceEngine(path, pp=2, dtype=jnp.float32, temperature=0.0)
    nll2, ppl2, n2 = epp.perplexity(toks)
    del epp
    assert n1 == n2
    np.testing.assert_allclose(nll2, nll1, rtol=1e-4)


CFG4_TP = dict(CFG4, hidden_dim=256)  # q40 col splits need dims % (32*tp)


def _params_tp(tmp_path, weight_format="dense", fuse=0):
    path = str(tmp_path / "mtp.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=11, cfg=CFG4_TP)
    r = ModelReader(path)
    return r.header, load_params(r, weight_format=weight_format, fuse=fuse)


def test_forward_pp_with_tp(tmp_path):
    """pp x tp: stages of tensor-parallel groups (manual psum inside the
    stage shard_map). Logits and caches must match the flat forward, for
    dense and fused-q40 weights."""
    for fmt, fuse in (("dense", 0), ("q40", 2)):
        h, params = _params_tp(tmp_path, weight_format=fmt, fuse=fuse)
        mesh = make_mesh(pp=2, tp=2)
        tokens = jnp.asarray([TOKENS], jnp.int32)
        lg_ref, cache_ref = forward(
            params, h, tokens, jnp.int32(0), init_kv_cache(h, 1)
        )
        lg_pp, cache_pp = forward_pp(
            params, h, tokens, jnp.int32(0), init_kv_cache(h, 1), mesh
        )
        np.testing.assert_allclose(
            np.asarray(lg_pp), np.asarray(lg_ref), rtol=2e-4, atol=2e-4,
            err_msg=fmt,
        )
        for k in ("k", "v"):
            np.testing.assert_allclose(
                np.asarray(cache_pp[k]), np.asarray(cache_ref[k]),
                rtol=1e-4, atol=1e-4, err_msg=fmt,
            )


def test_forward_pp_tp_wcls_stays_sharded(tmp_path):
    """Under pp x tp the vocab head must keep wcls tp-sharded and compute
    per-shard logits slices (logits_head tp_axis): the ONLY all-gather in
    the compiled program is the [B, T, V] logits gather over the tp
    groups. A replicated wcls in_spec would add a weight-sized [D, V]
    all-gather per step — GB-scale on a real 70B layout."""
    from dllama_tpu.parallel.sharding import shard_params_put

    path = str(tmp_path / "mtp.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=11, cfg=CFG4_TP)
    r = ModelReader(path)
    h = r.header
    mesh = make_mesh(pp=2, tp=2)
    params = load_params(
        r, weight_format="dense", put=shard_params_put(mesh, h)
    )
    tokens = jnp.asarray([TOKENS], jnp.int32)
    cache = init_kv_cache(h, 1)
    f = jax.jit(
        lambda p, t, c: forward_pp(p, h, t, jnp.int32(0), c, mesh)
    )
    txt = f.lower(params, tokens, cache).compile().as_text()
    gathers = [ln for ln in txt.splitlines() if "all-gather(" in ln]
    assert len(gathers) == 1, gathers
    b, t = tokens.shape
    assert f"f32[{b},{t},{h.vocab_size}]" in gathers[0], gathers[0]


def test_forward_pp_tp_sync_quant(tmp_path):
    """buffer_float_type=q80 must reach the pp x tp stage-local partial
    sums (not be silently dropped): logits stay within quantization
    tolerance of the exact run AND differ from it (the compressed
    collective actually ran)."""
    h, params = _params_tp(tmp_path)
    mesh = make_mesh(pp=2, tp=2)
    tokens = jnp.asarray([TOKENS], jnp.int32)
    lg_exact, _ = forward_pp(
        params, h, tokens, jnp.int32(0), init_kv_cache(h, 1), mesh,
        sync_quant=False,
    )
    lg_q80, _ = forward_pp(
        params, h, tokens, jnp.int32(0), init_kv_cache(h, 1), mesh,
        sync_quant=True,
    )
    exact = np.asarray(lg_exact)
    q80 = np.asarray(lg_q80)
    scale = np.abs(exact).max()
    err = np.abs(q80 - exact).max()
    assert err / scale < 2e-2, (err, scale)
    assert err > 0.0  # compression actually happened


def test_engine_pp_x_tp_matches_single_device(tmp_path):
    """Engine-level pp=2 x tp=2 (4 virtual chips): generated tokens match
    the single-device stream for fused q40."""
    from dllama_tpu.runtime.engine import InferenceEngine

    path = str(tmp_path / "m.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=11, cfg=CFG4_TP)
    prompt = list(range(2, 36))
    e1 = InferenceEngine(
        path, tp=1, dtype=jnp.float32, temperature=0.0, weight_format="q40"
    )
    expected, _, _ = e1.generate(prompt, max_steps=44)
    del e1
    epp = InferenceEngine(
        path, pp=2, tp=2, dtype=jnp.float32, temperature=0.0,
        weight_format="q40",
    )
    got, _, _ = epp.generate(prompt, max_steps=44)
    del epp
    assert got == expected, (got, expected)


def test_forward_pp_park_writes_match_select(tmp_path):
    """park_pos mode (invalid-tick writes into padding scratch rows) must
    reproduce the select-merge logits and every REAL cache row, prefill
    and decode, including the n_micro sequence-wave schedule."""
    h, params = _params(tmp_path)
    mesh = make_mesh(pp=2)
    s = h.seq_len
    pad = 8

    def run(park):
        cache = init_kv_cache(h, 1, seq_len=s + pad)
        toks = jnp.asarray([TOKENS], jnp.int32)
        logits, cache = forward_pp(
            params, h, toks, jnp.int32(0), cache, mesh,
            park_pos=park, n_micro=2,
        )
        out = [logits]
        pos = len(TOKENS)
        for _ in range(3):
            nxt = jnp.argmax(logits[0, -1])[None, None].astype(jnp.int32)
            logits, cache = forward_pp(
                params, h, nxt, jnp.int32(pos), cache, mesh, park_pos=park
            )
            out.append(logits)
            pos += 1
        return out, cache

    lg_sel, cache_sel = run(0)
    lg_park, cache_park = run(s)
    for a, b in zip(lg_sel, lg_park):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        )
    for k in ("k", "v"):  # real rows identical; rows >= s are scratch
        np.testing.assert_allclose(
            np.asarray(cache_park[k][:, :, :, :s]),
            np.asarray(cache_sel[k][:, :, :, :s]),
            rtol=1e-5, atol=1e-5,
        )


def test_forward_pp_park_cuts_decode_bytes(tmp_path):
    """The park path must actually remove the per-tick O(stage cache)
    select: compiled bytes-accessed of a decode step drops vs the
    select-merge path (the select reads+writes the whole stage cache
    every one of the pp ticks). Long seq_len so the cache term dominates
    the tiny model's weights, as it does at real scale."""
    h, params = _params(tmp_path, cfg=dict(CFG4, seq_len=512))
    mesh = make_mesh(pp=4)
    s = h.seq_len

    def compiled_bytes(park):
        cache = init_kv_cache(h, 1, seq_len=s + 8)
        tok = jnp.asarray([[7]], jnp.int32)

        def step(p, t, c):
            return forward_pp(p, h, t, jnp.int32(10), c, mesh, park_pos=park)

        lowered = jax.jit(step).lower(params, tok, cache)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):  # per-device list on some backends
            cost = cost[0]
        return cost.get("bytes accessed", 0.0)

    b_sel = compiled_bytes(0)
    b_park = compiled_bytes(s)
    assert b_park < 0.75 * b_sel, (b_park, b_sel)


def test_engine_pp_x_dp_matches_single_device(tmp_path):
    """pp=2 x dp=2: batch lanes shard over dp inside every stage; each
    prompt's token stream must match its single-device run (the pipeline
    throughput configuration — docs/pp_decode_model.md)."""
    from dllama_tpu.runtime.engine import InferenceEngine

    path = str(tmp_path / "m.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=11, cfg=CFG4)
    prompts = [[1, 2, 3, 4], [9, 8, 7, 6, 5]]
    singles = []
    e1 = InferenceEngine(path, tp=1, dtype=jnp.float32, temperature=0.0)
    for p in prompts:
        e1.reset()
        o, _, _ = e1.generate(p, max_steps=14)
        singles.append(o)
    del e1
    epp = InferenceEngine(
        path, pp=2, dp=2, dtype=jnp.float32, temperature=0.0, batch_size=2
    )
    assert epp.mesh.shape == {"pp": 2, "dp": 2, "tp": 1}
    outs = epp.generate_batch(prompts, max_steps=14)
    del epp
    assert outs == singles, (outs, singles)


def test_engine_pp_x_dp_x_tp_matches_single_device(tmp_path):
    """The full pp=2 x dp=2 x tp=2 composition on 8 virtual devices:
    stages of tp groups with dp-sharded lanes, token parity per prompt."""
    from dllama_tpu.runtime.engine import InferenceEngine

    path = str(tmp_path / "m.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=11, cfg=CFG4)
    prompts = [[1, 2, 3], [7, 6, 5, 4]]
    singles = []
    e1 = InferenceEngine(path, tp=1, dtype=jnp.float32, temperature=0.0)
    for p in prompts:
        e1.reset()
        o, _, _ = e1.generate(p, max_steps=12)
        singles.append(o)
    del e1
    epp = InferenceEngine(
        path, pp=2, dp=2, tp=2, dtype=jnp.float32, temperature=0.0,
        batch_size=2,
    )
    outs = epp.generate_batch(prompts, max_steps=12)
    del epp
    assert outs == singles, (outs, singles)


def test_engine_pp_dp_batch_divisibility(tmp_path):
    from dllama_tpu.runtime.engine import InferenceEngine

    path = str(tmp_path / "m.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=11, cfg=CFG4)
    with pytest.raises(ValueError, match="batch_size"):
        InferenceEngine(path, pp=2, dp=2, batch_size=3, dtype=jnp.float32)


def test_forward_pp_x_sp_matches_single(tmp_path):
    """pp=2 x sp=2: stage-local sequence shards with merged-stats
    attention and owning-shard window writes must reproduce the flat
    forward's logits and cache — prefill chunk AND decode steps,
    including a chunk that straddles the sp shard boundary."""
    h, params = _params(tmp_path)
    mesh = make_mesh(pp=2, sp=2)
    s = h.seq_len  # 64 -> 32-row local shards

    def run(fwd, **kw):
        cache = init_kv_cache(h, 1)
        toks = jnp.asarray([list(range(2, 30))], jnp.int32)  # 28 rows
        logits, cache = fwd(params, h, toks, jnp.int32(0), cache, **kw)
        outs = [logits]
        pos = 28
        # decode across the 32-row shard boundary (positions 28..35)
        for i in range(8):
            nxt = jnp.argmax(logits[0, -1])[None, None].astype(jnp.int32)
            logits, cache = fwd(
                params, h, nxt, jnp.int32(pos), cache, **kw
            )
            outs.append(logits)
            pos += 1
        return outs, cache

    ref, cache_ref = run(forward)
    got, cache_pp = run(forward_pp, mesh=mesh)
    for a, b in zip(ref, got):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-4
        )
    # sp caches use the cyclic layout: global row g sits at axis index
    # (g % sp) * shard + g // sp — undo the permutation before comparing
    sp, shard = 2, s // 2
    g = np.arange(s)
    perm = (g % sp) * shard + g // sp
    for k in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(cache_pp[k])[:, :, :, perm],
            np.asarray(cache_ref[k]),
            rtol=1e-5, atol=1e-5,
        )


def test_engine_pp_x_sp_matches_single_device(tmp_path):
    """Engine pp=2 x sp=2 (and pp x sp x tp on 8 devices): the bucketed
    prefill + block decode path with stage-local sequence shards must
    reproduce single-device tokens."""
    from dllama_tpu.runtime.engine import InferenceEngine

    path = str(tmp_path / "m.m")
    make_tiny_model(path, weight_type=FloatType.Q40, seed=11, cfg=CFG4)
    prompt = [1, 2, 3, 4, 5, 6, 7]
    e1 = InferenceEngine(path, tp=1, dtype=jnp.float32, temperature=0.0)
    expected, _, _ = e1.generate(prompt, max_steps=18)
    del e1
    for kw in (dict(pp=2, sp=2), dict(pp=2, sp=2, tp=2)):
        epp = InferenceEngine(
            path, dtype=jnp.float32, temperature=0.0, **kw
        )
        got, _, _ = epp.generate(prompt, max_steps=18)
        del epp
        assert got == expected, (kw, got, expected)


def test_forward_pp_x_sp_windowed_decode(tmp_path):
    """pp x sp with an ACTIVE attention window (sp-multiple, smaller than
    the cache): the manual-path local prefix slice must reproduce the
    unwindowed logits while the window covers the live prefix."""
    h, params = _params(tmp_path, cfg=dict(CFG4, seq_len=2048))
    mesh = make_mesh(pp=2, sp=2)
    cache0 = init_kv_cache(h, 1)

    toks = jnp.asarray([TOKENS], jnp.int32)
    _, cache = forward_pp(
        params, h, toks, jnp.int32(0), cache0, mesh
    )
    step = jnp.asarray([[9]], jnp.int32)
    lg_full, _ = forward_pp(
        params, h, step, jnp.int32(len(TOKENS)), cache, mesh
    )
    lg_win, _ = forward_pp(
        params, h, step, jnp.int32(len(TOKENS)), cache, mesh,
        attn_window=1024,  # sp multiple, < 2048: local 512-row prefix
    )
    np.testing.assert_allclose(
        np.asarray(lg_win), np.asarray(lg_full), rtol=1e-5, atol=1e-5
    )
    # misaligned windows fail loudly on the manual path too
    with pytest.raises(ValueError, match="multiple of sp"):
        forward_pp(
            params, h, step, jnp.int32(len(TOKENS)), cache, mesh,
            attn_window=1025,
        )


def test_forward_pp_int8_cache_no_park(tmp_path):
    """forward_pp with a QuantKV (int8) cache and NO park rows: the
    invalid-tick cache select must tree-map over the (values, scales)
    pair (r5 regression — found by the 70B rehearsal script)."""
    h, params = _params(tmp_path)
    mesh = make_mesh(pp=2)
    tokens = jnp.asarray([TOKENS], jnp.int32)
    lg_ref, _ = forward(
        params, h, tokens, jnp.int32(0), init_kv_cache(h, 1, dtype=jnp.int8)
    )
    lg_pp, cache_pp = forward_pp(
        params, h, tokens, jnp.int32(0),
        init_kv_cache(h, 1, dtype=jnp.int8), mesh,
    )
    np.testing.assert_allclose(
        np.asarray(lg_pp), np.asarray(lg_ref), rtol=1e-5, atol=1e-5
    )
