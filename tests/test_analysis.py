"""dlint self-tests (PR 8): each rule fires on its bad fixture and stays
quiet on its good one; suppressions and the baseline behave; and the
repo itself lints clean (the same gate CI's fast lane runs).
"""

import json
import pathlib
import subprocess
import sys

import pytest

from dllama_tpu.analysis import all_rules
from dllama_tpu.analysis.core import (
    Finding,
    apply_baseline,
    collect_repo,
    load_baseline,
    run_rules,
    write_baseline,
)
from dllama_tpu.analysis.rules_clock import DirectClockRule
from dllama_tpu.analysis.rules_env import EnvKnobDocsRule
from dllama_tpu.analysis.rules_kv import RetainReleaseRule
from dllama_tpu.analysis.rules_locks import GuardedAttrsRule
from dllama_tpu.analysis.rules_metrics import MetricsDocsRule
from dllama_tpu.analysis.rules_threads import ThreadHygieneRule
from dllama_tpu.analysis.rules_trace import TracePurityRule

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
FIXDIR = "tests/fixtures/dlint"


def lint(fixture: str, rule):
    repo = collect_repo(REPO_ROOT, [f"{FIXDIR}/{fixture}"])
    assert not repo.parse_errors, repo.parse_errors
    findings, n_suppressed = run_rules(repo, [rule])
    return findings, n_suppressed


CASES = [
    (GuardedAttrsRule(), "guarded_attrs"),
    (RetainReleaseRule(), "retain_release"),
    (DirectClockRule(), "direct_clock"),
    (TracePurityRule(), "trace_purity"),
    (ThreadHygieneRule(), "thread_hygiene"),
]


@pytest.mark.fast
@pytest.mark.parametrize(
    "rule,stem", CASES, ids=[r.name for r, _ in CASES]
)
def test_rule_fires_on_bad_fixture(rule, stem):
    findings, _ = lint(f"bad_{stem}.py", rule)
    assert findings, f"{rule.name} found nothing in bad_{stem}.py"
    assert all(f.rule == rule.name for f in findings)


@pytest.mark.fast
@pytest.mark.parametrize(
    "rule,stem", CASES, ids=[r.name for r, _ in CASES]
)
def test_rule_quiet_on_good_fixture(rule, stem):
    findings, _ = lint(f"good_{stem}.py", rule)
    assert findings == [], [f.render() for f in findings]


@pytest.mark.fast
def test_specific_bad_findings_anchor_where_expected():
    findings, _ = lint("bad_guarded_attrs.py", GuardedAttrsRule())
    msgs = [f.message for f in findings]
    assert any("read without a lock in peek()" in m for m in msgs)
    assert any("written without a lock in clobber()" in m for m in msgs)

    findings, _ = lint("bad_retain_release.py", RetainReleaseRule())
    msgs = [f.message for f in findings]
    assert any("not released before the return" in m for m in msgs)
    assert any("kv_publish" in m and "leak" in m for m in msgs)

    findings, _ = lint("bad_trace_purity.py", TracePurityRule())
    msgs = " ".join(f.message for f in findings)
    assert "time.monotonic()" in msgs
    assert "print()" in msgs
    assert "helper()" in msgs  # reached transitively


@pytest.mark.fast
def test_inline_suppression_counts_and_silences():
    # good_guarded_attrs.py carries one justified `# dlint: disable=`
    findings, n_suppressed = lint("good_guarded_attrs.py", GuardedAttrsRule())
    assert findings == []
    assert n_suppressed == 1


@pytest.mark.fast
def test_metrics_docs_rule_both_directions(tmp_path):
    (tmp_path / "dllama_tpu").mkdir()
    (tmp_path / "dllama_tpu" / "m.py").write_text(
        'c = counter("dllama_documented_total", "d")\n'
        'g = gauge("dllama_undocumented_thing", "d")\n'
    )
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "serving_metrics.md").write_text(
        "`dllama_documented_total` — fine\n"
        "`dllama_phantom_metric` — registered nowhere\n"
    )
    repo = collect_repo(tmp_path, ["dllama_tpu"])
    findings, _ = run_rules(repo, [MetricsDocsRule()])
    msgs = " ".join(f.message for f in findings)
    assert "dllama_undocumented_thing" in msgs
    assert "dllama_phantom_metric" in msgs
    assert "dllama_documented_total" not in msgs


@pytest.mark.fast
def test_env_knob_docs_rule_both_directions(tmp_path):
    (tmp_path / "dllama_tpu").mkdir()
    (tmp_path / "dllama_tpu" / "m.py").write_text(
        'a = os.environ.get("DLLAMA_DOCUMENTED_KNOB", "0")\n'
        'b = _env_int(\n    "DLLAMA_UNDOCUMENTED_KNOB", 4)\n'
        'c = os.getenv("DLLAMA_FAM_MEMBER")\n'
        '# a comment naming DLLAMA_ONLY_IN_COMMENT is not a read site\n'
        'os.environ.setdefault("DLLAMA_SETDEFAULT_ONLY", "1")\n'
    )
    (tmp_path / "README.md").write_text(
        "Set `DLLAMA_DOCUMENTED_KNOB` to tune things.\n"
        "`DLLAMA_PHANTOM_KNOB` — documented, read nowhere.\n"
        "The `DLLAMA_FAM_*` family covers its members.\n"
        "The `DLLAMA_GHOSTFAM_*` family matches no read at all.\n"
    )
    repo = collect_repo(tmp_path, ["dllama_tpu"])
    findings, _ = run_rules(repo, [EnvKnobDocsRule()])
    msgs = " ".join(f.message for f in findings)
    assert "DLLAMA_UNDOCUMENTED_KNOB is read here but documented" in msgs
    assert "DLLAMA_PHANTOM_KNOB is documented but read nowhere" in msgs
    assert "family DLLAMA_GHOSTFAM_* is documented but no knob" in msgs
    # documented+read, wildcard-covered, setdefault and comments: quiet
    for quiet in (
        "DLLAMA_DOCUMENTED_KNOB is read",
        "DLLAMA_FAM_MEMBER",
        "DLLAMA_SETDEFAULT_ONLY",
        "DLLAMA_ONLY_IN_COMMENT",
    ):
        assert quiet not in msgs, msgs
    assert len(findings) == 3


@pytest.mark.fast
def test_cli_prune_drops_stale_baseline_entries(tmp_path):
    bad = f"{FIXDIR}/bad_guarded_attrs.py"
    bp = tmp_path / "baseline.json"
    proc = subprocess.run(
        [sys.executable, "-m", "dllama_tpu.analysis",
         "--update-baseline", "--baseline", str(bp), bad],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    real = json.loads(bp.read_text())["findings"]
    assert real
    # graft a stale fingerprint in, then prune: only the ghost goes away
    doc = json.loads(bp.read_text())
    doc["findings"] = sorted(real + ["ghost-rule::gone.py::never"])
    bp.write_text(json.dumps(doc))
    proc = subprocess.run(
        [sys.executable, "-m", "dllama_tpu.analysis",
         "--prune", "--baseline", str(bp), bad],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(bp.read_text())["findings"] == sorted(real)
    # pruning never widens: findings NOT yet in the baseline stay out
    doc = json.loads(bp.read_text())
    doc["findings"] = doc["findings"][:1]
    bp.write_text(json.dumps(doc))
    subprocess.run(
        [sys.executable, "-m", "dllama_tpu.analysis",
         "--prune", "--baseline", str(bp), bad],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
    )
    assert json.loads(bp.read_text())["findings"] == sorted(real)[:1]


@pytest.mark.fast
def test_baseline_roundtrip_and_staleness(tmp_path):
    f1 = Finding(rule="r", path="a.py", line=3, message="m1")
    f2 = Finding(rule="r", path="a.py", line=9, message="m2")
    bp = tmp_path / "baseline.json"
    write_baseline(bp, [f1])
    baseline = load_baseline(bp)
    # f1 baselined, f2 new; a fingerprint with no live finding is stale
    new, old, stale = apply_baseline([f1, f2], baseline | {"r::gone.py::x"})
    assert [f.message for f in new] == ["m2"]
    assert [f.message for f in old] == ["m1"]
    assert stale == {"r::gone.py::x"}
    # fingerprints survive line drift (no line numbers inside)
    drifted = Finding(rule="r", path="a.py", line=33, message="m1")
    assert drifted.fingerprint() == f1.fingerprint()
    assert json.loads(bp.read_text())["findings"] == [f1.fingerprint()]


@pytest.mark.fast
def test_repo_lints_clean():
    """The acceptance gate: `python -m dllama_tpu.analysis` exits 0."""
    proc = subprocess.run(
        [sys.executable, "-m", "dllama_tpu.analysis"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.fast
def test_cli_rule_selection_and_exit_codes():
    proc = subprocess.run(
        [sys.executable, "-m", "dllama_tpu.analysis", "--list-rules"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0
    for r in all_rules():
        assert r.name in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "dllama_tpu.analysis", "--rules", "nope"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 2

    bad = f"{FIXDIR}/bad_guarded_attrs.py"
    proc = subprocess.run(
        [sys.executable, "-m", "dllama_tpu.analysis", "--no-baseline", bad],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1
    assert "guarded-attrs" in proc.stdout
