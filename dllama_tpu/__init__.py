"""dllama-tpu: a TPU-native tensor-parallel LLM inference framework.

A ground-up re-design of the capabilities of b4rtaz/distributed-llama
(reference at /root/reference) for TPU hardware:

- the reference's hand-written C++ graph IR + pthread executor collapse into
  JAX-jitted SPMD programs (one compiled step; XLA schedules and fuses),
- its TCP-socket collectives (all-gather / gather-to-root) become XLA
  collectives over ICI/DCN driven by `jax.sharding.NamedSharding`,
- its NEON/AVX2 kernels (Q40xQ80 matmul, multi-head attention) become Pallas
  TPU kernels riding the MXU,
- the `.m` model format, `.t` tokenizer format, converter tooling, CLI
  surface and OpenAI-compatible API server are kept capability-compatible.

Package layout:
    formats/   .m / .t file formats, Q40/Q80 block quantization
    models/    model configs + pure-functional forward passes (Llama, Qwen3, Qwen3-MoE)
    ops/       compute ops: jnp reference impls + Pallas TPU kernels
    parallel/  device mesh, tensor-parallel sharding rules, collectives
    runtime/   inference engine (KV cache, prefill/decode), sampler, API server
    utils/     logging, timing
"""

__version__ = "0.1.0"
