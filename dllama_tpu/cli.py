"""dllama-compatible CLI: inference / chat / perplexity modes.

Keeps the reference's flag surface (src/app.cpp:24-135) so a
distributed-llama user can switch with the same command lines, with
TPU-native replacements where the concept changed:

    --workers h:p ...   ->  --tp N      (chips on the slice, not LAN hosts;
                                         --workers N is accepted as an alias)
    --nthreads          ->  accepted, ignored (XLA owns threading)
    --buffer-float-type ->  honored on multi-host launches (Q80 psum
                            payloads, parallel/collectives.py); moot on
                            single-host ICI where exact f32 is used
    --gpu-index/--gpu-segments -> rejected (the TPU *is* the device)

Per-token timing surface mirrors dllama.cpp:59-66,88-95 (Eval/Pred + Sync
per line, tokens/s summary blocks).
"""

from __future__ import annotations

import argparse
import sys
import time

import jax


def add_engine_args(p: argparse.ArgumentParser) -> None:
    """Engine/model flags shared by the CLI and the API server
    (reference flag surface: src/app.cpp:24-135)."""
    p.add_argument("--model", required=False)
    p.add_argument("--tokenizer", required=False)
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--topp", type=float, default=0.9)
    p.add_argument("--seed", type=int, default=int(time.time()))
    p.add_argument("--max-seq-len", type=int, default=0)
    p.add_argument("--buffer-float-type", default="q80",
                   choices=["q80", "f32"],
                   help="partial-sum all-reduce payload; applied on "
                        "multi-host (DCN) launches, where sync bytes "
                        "matter like the reference's 1 GbE clusters — "
                        "single-host ICI always syncs exact f32")
    p.add_argument("--nthreads", type=int, default=1, help="accepted for CLI parity; XLA owns threading")
    p.add_argument("--net-turbo", type=int, default=1, help="accepted for CLI parity")
    p.add_argument("--nbatches", "--n-batches", type=int, default=32, dest="nbatches", help="prefill chunk size")
    p.add_argument("--batch-size", type=int, default=1, dest="batch_size",
                   help="decode lanes: >1 lets the API server stream "
                        "multiple requests concurrently (per-lane "
                        "positions over the dp batch axis)")
    p.add_argument("--lane-block-size", type=int, default=None,
                   dest="lane_block_size", metavar="N",
                   help="decode tokens per lane-scheduler block (default: "
                        "env DLLAMA_LANE_BLOCK, else 8) — with "
                        "--admission-chunk this bounds the worst-case "
                        "inter-token gap at one chunk + one block")
    p.add_argument("--kv-page-size", type=int, default=None,
                   dest="kv_page_size", metavar="TOKENS",
                   help="paged-KV pool page size for cross-lane prefix "
                        "sharing on the lane-scheduler path (default: env "
                        "DLLAMA_KV_PAGE_SIZE, else 16); negative disables "
                        "the shared pool entirely (no prefix reuse)")
    p.add_argument("--kv-pool-pages", type=int, default=None,
                   dest="kv_pool_pages", metavar="N",
                   help="pages in the shared KV pool (default: env "
                        "DLLAMA_KV_POOL_PAGES, else auto: two sequences' "
                        "worth, 2*seqLen/pageSize + 1)")
    p.add_argument("--kv-native", type=int, default=None,
                   dest="kv_native", metavar="0|1",
                   help="pool-native paged decode on the lane path: "
                        "lanes read/write KV through a per-lane page "
                        "table straight into the shared pool, so prefix "
                        "adoption is a refcount bump (zero device-copy "
                        "bytes on page-aligned matches) and publish an "
                        "ownership transfer (default: env "
                        "DLLAMA_KV_NATIVE, else 0 = per-lane slab KV "
                        "with adopt/publish page copies); requires "
                        "pp=1 and sp=1")
    p.add_argument("--max-streams", type=int, default=None,
                   dest="max_streams", metavar="N",
                   help="concurrent streams the scheduler may admit, "
                        "oversubscribing the decode lanes: when N > "
                        "batch-size and requests queue, the "
                        "most-progressed lane parks (KV published to "
                        "the shared pool, page list dropped) and the "
                        "parked stream later resumes via radix "
                        "re-match (default: env DLLAMA_MAX_STREAMS, "
                        "else 0 = streams cap at the lane count)")
    p.add_argument("--admission-chunk", type=int, default=None,
                   dest="admission_chunk", metavar="TOKENS",
                   help="max prompt tokens prefilled per scheduler tick "
                        "while admitting a request (default: env "
                        "DLLAMA_ADMISSION_CHUNK, else the largest prefill "
                        "bucket); smaller = tighter inter-token gaps for "
                        "active streams, larger = faster TTFT for the "
                        "incoming prompt")
    p.add_argument("--speculation", default=None,
                   choices=("off", "ngram", "shared", "draft"),
                   help="speculative decoding on the lane path: 'ngram' "
                        "drafts each greedy lane's continuation from its "
                        "own context (prompt lookup) and verifies k tokens "
                        "in one batched dispatch, keeping output "
                        "token-exact; 'shared' also publishes accepted "
                        "runs into a cross-lane store keyed by radix-tree "
                        "node identity, so lanes sharing a prefix draft "
                        "from each other's continuations; 'draft' "
                        "additionally runs a resident draft model "
                        "(--draft-model) when both n-gram sources run "
                        "dry; temperature>0 lanes fall back to the "
                        "normal decode block per lane (default: env "
                        "DLLAMA_SPECULATION, else off = pure bypass)")
    p.add_argument("--spec-k", type=int, default=None,
                   dest="spec_k", metavar="K",
                   help="max draft tokens per speculative verify dispatch "
                        "(compiled shapes are power-of-2 bucketed; each "
                        "lane's drafter adapts below this on low "
                        "acceptance; default: env DLLAMA_SPEC_K, else 4)")
    p.add_argument("--draft-model", default=None, dest="draft_model",
                   metavar="PATH",
                   help="tiny same-tokenizer checkpoint loaded as the "
                        "resident draft model for --speculation draft: "
                        "runs k cheap greedy steps through its own "
                        "AOT-compiled draft_step program and its own KV "
                        "cache; every draft is verified by the target, so "
                        "output stays token-exact (default: env "
                        "DLLAMA_DRAFT_MODEL)")
    p.add_argument("--tp", type=int, default=0, help="tensor-parallel chips (default: all)")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel chips: shard the KV cache's "
                        "sequence axis for long contexts (ring prefill + "
                        "merged-stats decode); total chips = tp x sp")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline stages: each holds nLayers/pp layers + "
                        "that range's KV cache — fits models past the "
                        "tp <= nKvHeads ceiling; composes with --tp "
                        "(stages of tp groups; chips = pp x tp), --dp, "
                        "--sp and --batch-size lanes")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel chips: batch lanes shard across "
                        "dp (requires batch-size %% dp == 0); the "
                        "throughput axis for pp (docs/pp_decode_model.md)")
    p.add_argument("--workers", nargs="*", default=None, help="alias for --tp: pass a chip count (host:port lists are a LAN-cluster concept)")
    p.add_argument("--dtype", default="bf16", choices=["bf16", "f32"])
    p.add_argument("--kv-dtype", default=None,
                   choices=[None, "bf16", "f32", "int8"],
                   help="int8 = per-row quantized KV cache (~2x capacity "
                   "vs bf16; models/transformer.QuantKV)")
    from .tokenizer import CHAT_TEMPLATE_NAMES

    p.add_argument("--chat-template", default=None,
                   choices=[None, *CHAT_TEMPLATE_NAMES])
    p.add_argument("--gpu-index", type=int, default=None)
    p.add_argument("--gpu-segments", default=None)
    p.add_argument("--weight-format", default="auto",
                   choices=["auto", "q40", "q40i8", "q40i4", "dense"],
                   help="q40 keeps weights block-quantized on device "
                        "(Pallas kernel); q40i8 requantizes to grouped "
                        "int8 for MXU integer dots; q40i4 stores packed "
                        "nibbles (0.56 B/weight, in-kernel unpack)")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="write a jax.profiler trace of the run to DIR")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="append one JSON line per finished request/"
                        "generation (request id, queue wait, prefill "
                        "span, TTFT, token counts, finish reason) to "
                        "PATH (obs/trace.py)")
    p.add_argument("--postmortem-dir", default=None, metavar="DIR",
                   help="write the engine flight recorder's event ring "
                        "as a JSON postmortem into DIR when a step or "
                        "the lane-scheduler loop raises (obs/recorder.py)")
    p.add_argument("--timeline-out", default=None, metavar="PATH",
                   help="write the span timeline as Chrome-trace/Perfetto "
                        "JSON to PATH (obs/spans.py; the API server "
                        "rewrites it throttled per finished request, the "
                        "CLI writes it once at exit)")
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="TTFT SLO target in ms for the windowed "
                        "attainment/goodput gauges (obs/slo.py; env "
                        "DLLAMA_SLO_TTFT_MS; unset = no target)")
    p.add_argument("--slo-tpot-ms", type=float, default=None,
                   help="mean-TPOT SLO target in ms for the windowed "
                        "attainment/goodput gauges (obs/slo.py; env "
                        "DLLAMA_SLO_TPOT_MS; unset = no target)")
    p.add_argument("--series-retention", type=float, default=None,
                   metavar="SECONDS",
                   help="in-process metrics time-series retention in "
                        "seconds (obs/timeseries.py; default 3600; env "
                        "DLLAMA_SERIES_RETENTION_S, sampling interval via "
                        "DLLAMA_SERIES_INTERVAL_S; serves /v1/debug/series "
                        "and the /dashboard sparklines)")
    p.add_argument("--moe-decode-dedup", default="auto", nargs="?",
                   const="on",  # bare flag keeps its r4 meaning (force on)
                   choices=["auto", "on", "off"],
                   help="two-tier MoE decode: lax.cond into a small-grid "
                        "grouped kernel when concurrent lanes share most "
                        "experts (docs/moe_decode_dedup.md); auto = on at "
                        ">= 8 decode lanes (routing-correlation study, "
                        "scripts/moe_routing_sim.py)")
    p.add_argument("--replica-id", default=None, dest="replica_id",
                   metavar="NAME",
                   help="name this server instance as a fleet replica: "
                        "reported in /v1/health and used as the chaos "
                        "op filter so a fault spec like "
                        "'sse_flush:op=r1:nth=3' targets one replica "
                        "(fleet/launch.py sets it; docs/fleet.md)")
    p.add_argument("--faults", default=None, metavar="SPEC",
                   help="arm the deterministic chaos plane with a fault "
                        "schedule, e.g. 'dispatch:p=0.05:seed=7,"
                        "kv_alloc:nth=12' (runtime/faults.py; env "
                        "DLLAMA_FAULTS; docs/resilience.md)")
    p.add_argument("--retry-max", type=int, default=None,
                   help="transient-dispatch retries before failing the "
                        "request (scheduler backoff loop; default 3; "
                        "0 disables; env DLLAMA_RETRY_MAX)")
    p.add_argument("--retry-backoff-ms", type=int, default=None,
                   help="base backoff in ms between dispatch retries, "
                        "doubling per attempt (default 5; env "
                        "DLLAMA_RETRY_BACKOFF_MS)")
    p.add_argument("--max-queue-depth", type=int, default=None,
                   help="shed (429 + Retry-After) once this many requests "
                        "wait for a lane; priority 'low' sheds at half "
                        "this, 'high' at double (default 0 = unbounded; "
                        "env DLLAMA_MAX_QUEUE_DEPTH)")
    p.add_argument("--admission-predict", action="store_true", default=None,
                   help="predictive admission control: estimate TTFT/TPOT "
                        "per request from the cost model + occupancy, "
                        "reject-or-queue infeasible deadline-hinted work "
                        "before admitting it, and order admission EDF-style "
                        "(runtime/admission.py; env "
                        "DLLAMA_ADMISSION_PREDICT; default off)")
    p.add_argument("--admission-max-wait-ms", type=int, default=None,
                   help="cap on the predicted queue-drain time advertised "
                        "via Retry-After on shed responses (default 30000; "
                        "env DLLAMA_ADMISSION_MAX_WAIT_MS)")
    p.add_argument("--deadline-default-ms", type=int, default=None,
                   help="effective deadline assigned to requests with no "
                        "deadline_ms/ttft_budget_ms hint, anchoring the "
                        "EDF admission order (default 600000; env "
                        "DLLAMA_DEADLINE_DEFAULT_MS)")
    p.add_argument("--deadline-priority-step-ms", type=int, default=None,
                   help="deadline offset per priority rung for unhinted "
                        "requests: high = -1 step, low = +1 step, so the "
                        "PR 12 priority ladder survives as EDF offsets "
                        "(default 60000; env "
                        "DLLAMA_DEADLINE_PRIORITY_STEP_MS)")
    p.add_argument("--sync-measure", default="auto", choices=["auto", "off"],
                   help="measure per-step collective time via a short "
                   "profiled re-run (multi-device greedy runs only; 'off' "
                   "skips the extra warmup steps)")


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dllama-tpu",
        description="TPU-native distributed-llama: tensor-parallel LLM inference",
    )
    p.add_argument("mode", choices=["inference", "chat", "perplexity", "worker"])
    p.add_argument("--prompt", default=None)
    p.add_argument("--steps", type=int, default=0)
    add_engine_args(p)
    return p


def _resolve_tp(args) -> int:
    if args.gpu_index is not None or args.gpu_segments is not None:
        raise SystemExit(
            "--gpu-index/--gpu-segments are Vulkan-backend options; on TPU "
            "the accelerator is the only device (use --tp to scale chips)"
        )
    if args.tp:
        return args.tp
    if args.workers:
        if len(args.workers) == 1 and args.workers[0].isdigit():
            return int(args.workers[0])
        # host:port lists: map N workers -> N chips, like-for-like
        print(
            f"⚠️  --workers host:port lists are a LAN-cluster concept; using "
            f"tp={len(args.workers)} chips over ICI instead"
        )
        return len(args.workers)
    return 0  # auto: resolved against the model header in _load


def load_engine(args):
    import jax.numpy as jnp

    from .runtime.engine import InferenceEngine
    from .tokenizer import Tokenizer

    if not args.model or not args.tokenizer:
        raise SystemExit("--model and --tokenizer are required")
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    kv_dtype = args.kv_dtype  # engine normalizes the name (incl. int8)
    tok = Tokenizer(args.tokenizer)
    tp = _resolve_tp(args)
    dp = getattr(args, "dp", 1) or 1
    sp = getattr(args, "sp", 1) or 1
    pp = getattr(args, "pp", 1) or 1
    if pp > 1 and tp == 0:
        tp = 1  # with --pp, scale tp explicitly (chips needed = pp x tp)
    if tp == 0:
        from .parallel.mesh import auto_tp

        tp = auto_tp(args.model, n_devices=len(jax.devices()) // (sp * dp))
    # the reference's q80 sync compression pays on DCN (multi-host), not
    # ICI: honor the flag only when processes > 1 (parallel/collectives.py)
    buffer_ft = (
        args.buffer_float_type if jax.process_count() > 1 else "f32"
    )
    engine = InferenceEngine(
        args.model,
        tokenizer=tok,
        tp=tp,
        dp=dp,
        sp=sp,
        pp=pp,
        dtype=dtype,
        kv_dtype=kv_dtype,
        max_seq_len=args.max_seq_len,
        temperature=args.temperature,
        topp=args.topp,
        seed=args.seed,
        prefill_buckets=tuple(sorted({1, args.nbatches, 512})),
        weight_format=args.weight_format,
        batch_size=getattr(args, "batch_size", 1),
        buffer_float_type=buffer_ft,
        moe_decode_dedup={"on": True, "off": False}.get(
            getattr(args, "moe_decode_dedup", "auto"), "auto"
        ),
    )
    h = engine.header
    print(f"💡 Arch: {h.arch.name}")
    print(f"💡 Dim: {h.dim}")
    print(f"💡 HeadDim: {h.head_dim}")
    print(f"💡 HiddenDim: {h.hidden_dim}")
    print(f"💡 VocabSize: {h.vocab_size}")
    print(f"💡 nLayers: {h.n_layers}")
    print(f"💡 nHeads: {h.n_heads}")
    print(f"💡 nKvHeads: {h.n_kv_heads}")
    if h.n_experts:
        print(f"💡 nExperts: {h.n_experts}")
        print(f"💡 nActiveExperts: {h.n_active_experts}")
    print(f"💡 SeqLen: {h.seq_len}")
    print(f"💡 Tp: {tp} chip(s) [{jax.default_backend()}]")
    if dp > 1:
        print(f"💡 Dp: {dp} lane shards")
    if sp > 1:
        print(f"💡 Sp: {sp} sequence shards")
    if pp > 1:
        print(f"💡 Pp: {pp} pipeline stages")
    if tok.vocab_size != h.vocab_size:
        print(
            f"⚠️  tokenizer vocab ({tok.vocab_size}) != model vocab "
            f"({h.vocab_size}); decoding may fail for out-of-range tokens"
        )
    print(f"💡 WeightFormat: {engine.weight_format}")
    from .utils.telemetry import memory_report

    mem = memory_report(
        engine.params, engine.cache, n_devices=tp * dp * sp * pp, tp=tp
    )
    mem.print()
    # startup roofline: the analytic HBM floor for decode next to the
    # memory report — what "as fast as the hardware allows" means in
    # ms/token for THIS model/format/layout (obs/cost.py)
    from .obs.cost import print_roofline_report
    from .obs.device import compare_with_analytic, sample_device_memory
    from .obs.recorder import get_recorder

    from .runtime.spec import resolve_spec_knobs

    spec_mode, spec_k_val = resolve_spec_knobs(
        getattr(args, "speculation", None), getattr(args, "spec_k", None)
    )
    print_roofline_report(
        h, engine.weight_format, tp=tp, pp=pp,
        i8_group=engine.i8_group or 512,
        spec_k=spec_k_val if spec_mode != "off" else 0,
    )
    # live per-chip memory vs the analytic figure: a >10% gap logs a
    # warning (leak / unplanned replication / stale analytic model)
    compare_with_analytic(
        mem.per_device_bytes, sample_device_memory(engine.obs)
    )
    if getattr(args, "postmortem_dir", None):
        get_recorder().postmortem_dir = args.postmortem_dir
    tok.print_header()
    return engine, tok


def run_inference(args) -> None:
    """(reference: dllama.cpp:13-116)"""
    import jax.numpy as jnp

    from .utils.telemetry import profile

    engine, tok = load_engine(args)
    if args.prompt is None:
        raise SystemExit("Prompt is required")
    if args.steps == 0:
        raise SystemExit("Number of steps is required")
    tokens = tok.encode(args.prompt, is_start=True, add_special_tokens=True)
    if len(tokens) > engine.header.seq_len:
        raise SystemExit("The number of prompt tokens is greater than the sequence length")

    # estimated ICI collective traffic fills the reference's Sent/Recv
    # columns (socket bytes there; deterministic from the sharding layout
    # here). The logits all-gather happens once per forward, the per-layer
    # all-reduces once per token.
    from .utils.telemetry import ici_traffic_per_token as _ici
    from .utils.telemetry import measure_sync_ms

    # q80-compressed sync moves 1.125 B/elem (int8 + f32/32 scales);
    # exact f32 psum moves 4. The pp hand-offs always ride uncompressed
    # in the model activation dtype.
    act_bytes = 1.125 if engine._sync_quant else 4.0
    pp_bytes = float(jnp.dtype(engine.dtype).itemsize)
    per_tok_bytes = _ici(
        engine.header, engine.tp, activation_bytes=act_bytes,
        include_logits=False, pp=engine.pp, pp_activation_bytes=pp_bytes,
    )
    logits_bytes = (
        _ici(
            engine.header, engine.tp, activation_bytes=act_bytes,
            pp=engine.pp, pp_activation_bytes=pp_bytes,
        )
        - per_tok_bytes
    )

    # MEASURED sync (collective) time per step type — the reference's
    # per-step sync clock (src/nn/nn-executor.cpp:158-163). Profiled
    # once per step type by re-running the upcoming step at a fixed
    # position (idempotent KV rewrites), then printed on every line;
    # Sent/Recv stay the deterministic sharding-layout estimate (the
    # reference counts actual socket bytes, nn-network.cpp:524-539 —
    # on-chip collectives have no socket to count, so the estimate IS
    # the traffic model). Greedy only: the sampled path's host RNG
    # state would advance during measurement runs.
    measure = (
        engine.mesh.devices.size > 1
        and not args.profile
        and engine.temperature == 0.0
        and getattr(args, "sync_measure", "auto") != "off"
    )
    sync_eval = sync_pred = None

    # one JSONL record for the whole generation, same schema as the API
    # server's --trace-out sink (obs/trace.py)
    from .obs.trace import NULL_SPAN, Tracer

    tracer = (
        Tracer(sink_path=args.trace_out)
        if getattr(args, "trace_out", None)
        else None
    )
    span = tracer.span(path="cli") if tracer is not None else NULL_SPAN
    span.mark_admitted()

    # span timeline of the run (--timeline-out; obs/spans.py): the engine
    # records prefill/decode_step spans itself, this one is the request-
    # attributed envelope the per-request summary hangs off
    from .obs.spans import get_span_tracker

    spans = get_span_tracker()
    gen_span = spans.begin(
        "generate", component="cli", request_id=span.request_id,
        n_prompt=len(tokens), steps=args.steps,
    )

    print(args.prompt)
    with profile(args.profile):
        if measure:
            # steps=1: ONE extra prefill (idempotent row rewrites), so
            # TTFT pays 2x, not 4x; it also warms the compile, so the
            # Eval ms below reports warm-program time
            sync_eval = measure_sync_ms(
                lambda: engine.prefill(tokens), steps=1
            )
        eval_stats = engine.prefill(tokens)
        span.set_prefill_seconds(eval_stats.time_ms / 1000.0)
        eval_kb = (
            per_tok_bytes * max(eval_stats.n_tokens, 1) + logits_bytes
        ) // 1024
        eval_sync = f"{sync_eval:5.1f}" if sync_eval is not None else "    0"
        print(
            f"🔷️ Eval{eval_stats.time_ms:5.0f} ms Sync{eval_sync} ms | "
            f"Sent{eval_kb:6d} kB Recv{eval_kb:6d} kB | "
            f"({eval_stats.n_tokens} tokens)"
        )
        tok.reset_decoder()
        pos = len(tokens) - 1
        token = tokens[-1]
        max_pos = min(engine.header.seq_len, args.steps)
        pred_ms = 0.0
        n_pred = 0
        while pos < max_pos:
            if measure and sync_pred is None:
                # rewriting the same row: the real step below repeats it
                sync_pred = measure_sync_ms(
                    lambda: engine.decode_step(token, pos)
                )
            token, stats = engine.decode_step(token, pos)
            pos += 1
            pred_ms += stats.time_ms
            n_pred += 1
            if n_pred == 1:
                span.mark_first_token()
            piece = tok.decode(token)
            step_kb = (per_tok_bytes + logits_bytes) // 1024
            pred_sync = (
                f"{sync_pred:5.1f}" if sync_pred is not None else "    0"
            )
            print(
                f"🔶 Pred{stats.time_ms:5.0f} ms Sync{pred_sync} ms | "
                f"Sent{step_kb:6d} kB Recv{step_kb:6d} kB | "
                f"{piece if piece is not None else chr(126)}"
            )
            sys.stdout.flush()

    spans.end(gen_span, n_completion=n_pred)
    span.finish("length", n_prompt=len(tokens), n_completion=n_pred)
    if tracer is not None:
        tracer.close()
    if getattr(args, "timeline_out", None):
        n_spans = spans.export_file(args.timeline_out)
        print(f"🧭 timeline: {n_spans} spans -> {args.timeline_out}")

    n_eval = max(len(tokens) - 1, 1)
    print()
    print("Evaluation")
    print(f"   nBatches: {args.nbatches}")
    print(f"    nTokens: {n_eval}")
    print(
        f"   tokens/s: {n_eval * 1000 / max(eval_stats.time_ms, 1e-9):3.2f} "
        f"({eval_stats.time_ms / n_eval:3.2f} ms/tok)"
    )
    print("Prediction")
    print(f"    nTokens: {n_pred}")
    if n_pred:
        print(
            f"   tokens/s: {n_pred * 1000 / max(pred_ms, 1e-9):3.2f} "
            f"({pred_ms / n_pred:3.2f} ms/tok)"
        )


def run_chat(args) -> None:
    """Interactive REPL (reference: dllama.cpp:174-258)."""
    from .tokenizer import (
        CHAT_TEMPLATE_NAMES,
        ChatItem,
        ChatTemplateGenerator,
        ChatTemplateType,
        EosDetector,
        EosResult,
    )

    engine, tok = load_engine(args)
    eos_piece = (
        tok.vocab[tok.eos_token_ids[0]].decode("utf-8", "replace")
        if tok.eos_token_ids
        else ""
    )
    ttype = (
        CHAT_TEMPLATE_NAMES[args.chat_template]
        if args.chat_template
        else ChatTemplateType.UNKNOWN
    )
    gen = ChatTemplateGenerator(ttype, tok.chat_template, eos_piece)
    stops = [tok.vocab[t].decode("utf-8", "replace") for t in tok.eos_token_ids]
    pos = 0
    is_start = True
    print("💬 Chat mode. Type your message (Ctrl-D to exit).")
    while True:
        try:
            user = input("\n👱 You: ")
        except EOFError:
            break
        if not user.strip():
            continue
        chat = gen.generate([ChatItem("user", user)], append_generation_prompt=True)
        tokens = tok.encode(chat.content, is_start=is_start, add_special_tokens=True)
        is_start = False
        # Context exhaustion: stop explicitly instead of silently generating
        # zero tokens forever (the reference prints an explicit stop when the
        # window fills, src/dllama.cpp:242-253).
        if pos + len(tokens) >= engine.header.seq_len:
            print(
                f"\n🚫 Context window full ({engine.header.seq_len} tokens); "
                "restart the chat to continue."
            )
            break
        detector = EosDetector(
            tok.eos_token_ids, stops, padding_left=2, padding_right=2
        )
        print("\n🤖 Assistant: ", end="", flush=True)
        tok.reset_decoder()

        def on_token(t: int):
            piece = tok.decode(t)
            res = detector.append(t, piece)
            if res == EosResult.NOT_EOS:
                delta = detector.get_delta()
                if delta:
                    print(delta, end="", flush=True)
                detector.reset()
            elif res == EosResult.EOS:
                delta = detector.get_delta()
                if delta:
                    print(delta, end="", flush=True)
                return False
            return True

        try:
            out, _, _ = engine.generate(
                tokens,
                max_steps=engine.header.seq_len - 1 - pos,
                on_token=on_token,
                start_pos=pos,
            )
        except KeyboardInterrupt:
            raise
        except Exception as e:
            # a failed dispatch dropped the donated KV cache
            # (engine._cache_guard); the conversation context is gone, so
            # restart the session instead of crashing the REPL (the
            # reference's server retries whole-app init the same way,
            # src/dllama-api.cpp:616-628 — its CLI just dies)
            print(f"\n🚫 Generation failed ({e}); conversation reset.")
            engine.reset()
            pos, is_start = 0, True
            continue
        pos += len(tokens) - 1 + len(out)
        print()


def run_perplexity(args) -> None:
    """Teacher-forced NLL over the prompt — the numerical-quality oracle.
    Scored chunk-by-chunk on device through the engine's bucketed prefill
    programs, shipping one scalar per chunk instead of a [T, vocab] logits
    tensor (the reference walks the prompt in nBatches chunks and reads
    the logits pipe per batch, src/dllama.cpp:132-172)."""
    engine, tok = load_engine(args)
    if args.prompt is None:
        raise SystemExit("Prompt is required")
    tokens = tok.encode(args.prompt, is_start=True, add_special_tokens=True)
    if len(tokens) < 2:
        raise SystemExit("Prompt too short for perplexity")
    nll, ppl, n_scored = engine.perplexity(tokens)
    print(f"    nTokens: {len(tokens)}")
    print(f"    nScored: {n_scored}")
    print(f"        nll: {nll:.4f}")
    print(f" perplexity: {ppl:.4f}")


def main(argv=None) -> None:
    from .parallel.mesh import enable_compilation_cache, reassert_platform

    reassert_platform()
    enable_compilation_cache()
    args = _build_parser().parse_args(argv)
    if args.mode == "worker":
        raise SystemExit(
            "worker mode is a LAN-cluster concept: under SPMD every chip runs "
            "the same program — launch the root command with --tp N instead "
            "(multi-host: one identical launch per host, see "
            "dllama_tpu.parallel.mesh.initialize_multihost)"
        )
    if args.mode == "inference":
        run_inference(args)
    elif args.mode == "chat":
        run_chat(args)
    elif args.mode == "perplexity":
        run_perplexity(args)


if __name__ == "__main__":
    main()
