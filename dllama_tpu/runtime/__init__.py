from .sampler import Sampler

__all__ = ["Sampler"]
