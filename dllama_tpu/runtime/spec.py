"""Model-free speculative drafting for the lane scheduler.

Prompt-lookup speculation (Leviathan et al.'s accept-longest-prefix
verification, with Saxena-style n-gram drafting instead of a draft
model): each greedy lane keeps an n-gram index over its *own* context
(prompt + generated tokens, extended incrementally as tokens stream)
and, when the current suffix has appeared before, proposes the tokens
that followed that earlier occurrence as a draft.  The engine then
verifies the whole draft in ONE batched forward pass
(``InferenceEngine.verify_lanes``) and the scheduler accepts the
longest prefix whose greedy argmax matches, plus one correction token.

Everything in this module is host-side and model-free: no draft
network, no extra device memory, no new weights read.  The payoff is
that an accepted run of ``a`` tokens amortizes one weight pass over
``a + 1`` tokens — on an HBM-bound decode that is a direct tok/s
multiplier for repetitive workloads (code, JSON extraction, quoting).

Greedy output stays token-exact: only tokens the verify pass itself
argmax'd are ever emitted, so the stream is byte-identical to plain
greedy decoding (``tests/test_spec.py`` proves this with the same
seeded parity harness used for chunked admission).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "DEFAULT_SPEC_K",
    "NgramDrafter",
    "NgramIndex",
    "bucket_for",
    "resolve_spec_knobs",
    "spec_buckets",
]

DEFAULT_SPEC_K = 4
DEFAULT_MAX_NGRAM = 3
DEFAULT_COOLDOWN = 4


def spec_buckets(k_max: int) -> Tuple[int, ...]:
    """Draft-length buckets: powers of two up to ``k_max`` plus
    ``k_max`` itself.

    The engine AOT-compiles one verify program per bucket (token width
    ``1 + bucket``) during ``rehearse_admission``, so no new shape ever
    compiles mid-serve; the scheduler pads a draft up to the next
    bucket.
    """
    if k_max < 1:
        return ()
    out: List[int] = []
    b = 1
    while b <= k_max:
        out.append(b)
        b *= 2
    if out[-1] != k_max:
        out.append(k_max)
    return tuple(out)


def bucket_for(k: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits a draft of ``k`` tokens."""
    for b in buckets:
        if k <= b:
            return b
    return buckets[-1]


def resolve_spec_knobs(
    speculation: Optional[str] = None, spec_k: Optional[int] = None
) -> Tuple[str, int]:
    """Resolve the speculation knobs: explicit argument beats the
    environment (``DLLAMA_SPECULATION``, ``DLLAMA_SPEC_K``) beats the
    default (``"off"``, ``DEFAULT_SPEC_K``)."""
    if speculation is None:
        speculation = os.environ.get("DLLAMA_SPECULATION", "").strip() or "off"
    if spec_k is None:
        raw = os.environ.get("DLLAMA_SPEC_K", "").strip()
        spec_k = int(raw) if raw else DEFAULT_SPEC_K
    mode = str(speculation)
    if mode not in ("off", "ngram"):
        raise ValueError(f"speculation must be 'off' or 'ngram', got {mode!r}")
    return mode, max(1, int(spec_k))


class NgramIndex:
    """Last-two-occurrence n-gram index over one lane's token stream.

    For every n in [1, max_n] maps the n-gram ending at each position to
    the *continuation start* of its latest and previous occurrences.
    Two deep matters: the current suffix always matches its own entry
    (whose continuation is empty), so lookups fall back to the previous
    occurrence to find real continuation tokens.
    """

    def __init__(self, max_n: int = DEFAULT_MAX_NGRAM) -> None:
        self.max_n = max(1, int(max_n))
        self.tokens: List[int] = []
        # per n: ngram -> (latest continuation start, previous or -1)
        self._occ: List[Dict[Tuple[int, ...], Tuple[int, int]]] = [
            {} for _ in range(self.max_n)
        ]

    def extend(self, tokens: Sequence[int]) -> None:
        for raw in tokens:
            self.tokens.append(int(raw))
            i = len(self.tokens)
            for n in range(1, self.max_n + 1):
                if i < n:
                    break
                key = tuple(self.tokens[i - n : i])
                d = self._occ[n - 1]
                prev = d.get(key)
                d[key] = (i, prev[0] if prev is not None else -1)

    def lookup(self, k: int) -> List[int]:
        """``k`` tokens predicted to follow the current suffix, read
        from the most recent *earlier* occurrence of the longest
        matching suffix n-gram ([] if the suffix has never been seen
        before).

        When the match sits close to the end of history — a stream in a
        short cycle, where the previous occurrence is one period back —
        the continuation is extended *cyclically*: once the copy runs
        past the end of recorded history it keeps reading from the
        draft itself, predicting that the period-``end - p`` repetition
        continues.  Without this a period-1 stall would only ever yield
        one draft token no matter how large ``k`` is.
        """
        toks = self.tokens
        end = len(toks)
        if end == 0 or k < 1:
            return []
        for n in range(min(self.max_n, end), 0, -1):
            hit = self._occ[n - 1].get(tuple(toks[end - n : end]))
            if hit is None:
                continue
            # hit[0] is the suffix's own (empty-continuation) entry;
            # the previous occurrence is the usable one.
            p = hit[1] if hit[0] >= end else hit[0]
            if p < 0 or p >= end:
                continue
            out: List[int] = []
            for j in range(k):
                src = p + j
                out.append(toks[src] if src < end else out[src - end])
            return out
        return []


class NgramDrafter:
    """Per-lane drafter: n-gram prompt lookup plus AIMD draft-length
    adaptation.

    ``update`` feeds the lane's history (only the unseen tail is
    indexed), ``draft`` proposes up to the current adaptive ``k``
    tokens, and ``feedback`` adapts after each verify: full acceptance
    grows ``k`` additively, under-half acceptance halves it, and zero
    acceptance additionally pauses drafting for a few ticks — the
    context is clearly not in a repetitive stretch, so the lane rejoins
    the plain decode block instead of wasting verify dispatches.
    """

    def __init__(
        self,
        k_max: int = DEFAULT_SPEC_K,
        max_n: int = DEFAULT_MAX_NGRAM,
        cooldown: int = DEFAULT_COOLDOWN,
    ) -> None:
        self.k_max = max(1, int(k_max))
        self.k = self.k_max
        self.index = NgramIndex(max_n)
        self._cooldown_len = max(0, int(cooldown))
        self._cooldown = 0
        self.n_drafted = 0
        self.n_accepted = 0

    def update(self, history: Sequence[int]) -> None:
        seen = len(self.index.tokens)
        if len(history) > seen:
            self.index.extend(history[seen:])

    def draft(self, budget: Optional[int] = None) -> List[int]:
        if self._cooldown > 0:
            self._cooldown -= 1
            return []
        k = self.k if budget is None else min(self.k, budget)
        if k < 1:
            return []
        return self.index.lookup(k)

    def feedback(self, proposed: int, accepted: int) -> None:
        self.n_drafted += proposed
        self.n_accepted += accepted
        if proposed <= 0:
            return
        if accepted >= proposed:
            self.k = min(self.k_max, self.k + 1)
        elif accepted * 2 < proposed:
            self.k = max(1, self.k // 2)
            if accepted == 0:
                self._cooldown = self._cooldown_len
