"""Speculative drafting for the lane scheduler: draft sources + policy.

Prompt-lookup speculation (Leviathan et al.'s accept-longest-prefix
verification, with Saxena-style n-gram drafting instead of a draft
model): each greedy lane keeps an n-gram index over its *own* context
(prompt + generated tokens, extended incrementally as tokens stream)
and, when the current suffix has appeared before, proposes the tokens
that followed that earlier occurrence as a draft.  The engine then
verifies the whole draft in ONE batched forward pass
(``InferenceEngine.verify_lanes``) and the scheduler accepts the
longest prefix whose greedy argmax matches, plus one correction token.

Second-generation sources compose behind the same drafter interface as
a cumulative mode ladder (``off`` ⊂ ``ngram`` ⊂ ``shared`` ⊂ ``draft``):

* ``shared`` adds a **cross-lane shared n-gram store**
  (:class:`SharedNgramStore`) keyed by radix-tree node identity
  (``kv/radix.py`` anchors): every greedy lane publishes its accepted
  continuation-past-anchor under its anchor's id, and a lane whose
  prefix matched the same node drafts from every sibling's published
  continuation — fanout workloads (many users, one system prompt)
  draft from each other's history from token one, exactly where a
  private index is still empty.  Without a KV manager (``kv_page_size
  < 0``) there are no anchors and ``shared`` degrades to per-lane
  ``ngram`` behavior.
* ``draft`` additionally consults a **resident draft model** (a tiny
  Llama-family checkpoint sharing the target's tokenizer, loaded via
  ``InferenceEngine.init_draft_model``) when both n-gram sources run
  dry: the scheduler catches the draft cache up and runs ``k`` cheap
  greedy steps through the engine's AOT ``draft_step`` programs.

Per tick the composed policy is: private n-gram hit → free; else
shared-store hit → free; else (mode ``draft``) the draft model.  One
AIMD draft length ``k`` per lane is shared across all sources.

Greedy output stays token-exact for EVERY source: only tokens the
verify pass itself argmax'd are ever emitted, so the stream is
byte-identical to plain greedy decoding (``tests/test_spec.py`` proves
this with the same seeded parity harness used for chunked admission).
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from ..analysis.lockwatch import make_lock

__all__ = [
    "DEFAULT_SHARED_MAX_NGRAM",
    "DEFAULT_SPEC_K",
    "NgramDrafter",
    "NgramIndex",
    "SPEC_MODES",
    "SOURCE_DRAFT",
    "SOURCE_NGRAM",
    "SOURCE_SHARED",
    "SharedNgramStore",
    "bucket_for",
    "resolve_draft_model",
    "resolve_spec_knobs",
    "spec_buckets",
]

DEFAULT_SPEC_K = 4
DEFAULT_MAX_NGRAM = 3
#: the cross-lane store ranks sources by matched suffix length, so it
#: needs a longer horizon than the private index: a sibling's genuine
#: replay matches a long run, while byte-level self-echoes rarely
#: extend past a trigram — equal horizons would tie on every tick and
#: starve the store
DEFAULT_SHARED_MAX_NGRAM = 12
DEFAULT_COOLDOWN = 4

#: cumulative speculation modes, weakest to strongest (each includes
#: every source to its left); ``off`` is a pure bypass
SPEC_MODES = ("off", "ngram", "shared", "draft")

#: draft-source labels (the ``dllama_spec_source_total{source=}`` values)
SOURCE_NGRAM = "ngram"
SOURCE_SHARED = "shared"
SOURCE_DRAFT = "draft"


def spec_buckets(k_max: int) -> Tuple[int, ...]:
    """Draft-length buckets: powers of two up to ``k_max`` plus
    ``k_max`` itself.

    The engine AOT-compiles one verify program per bucket (token width
    ``1 + bucket``) during ``rehearse_admission``, so no new shape ever
    compiles mid-serve; the scheduler pads a draft up to the next
    bucket.
    """
    if k_max < 1:
        return ()
    out: List[int] = []
    b = 1
    while b <= k_max:
        out.append(b)
        b *= 2
    if out[-1] != k_max:
        out.append(k_max)
    return tuple(out)


def bucket_for(k: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits a draft of ``k`` tokens."""
    for b in buckets:
        if k <= b:
            return b
    return buckets[-1]


def resolve_spec_knobs(
    speculation: Optional[str] = None, spec_k: Optional[int] = None
) -> Tuple[str, int]:
    """Resolve the speculation knobs: explicit argument beats the
    environment (``DLLAMA_SPECULATION``, ``DLLAMA_SPEC_K``) beats the
    default (``"off"``, ``DEFAULT_SPEC_K``)."""
    if speculation is None:
        speculation = os.environ.get("DLLAMA_SPECULATION", "").strip() or "off"
    if spec_k is None:
        raw = os.environ.get("DLLAMA_SPEC_K", "").strip()
        spec_k = int(raw) if raw else DEFAULT_SPEC_K
    mode = str(speculation)
    if mode not in SPEC_MODES:
        raise ValueError(
            f"speculation must be one of {'/'.join(SPEC_MODES)}, got {mode!r}"
        )
    return mode, max(1, int(spec_k))


def resolve_draft_model(draft_model: Optional[str] = None) -> Optional[str]:
    """Resolve the resident-draft-model checkpoint path: explicit
    argument beats the environment (``DLLAMA_DRAFT_MODEL``) beats None.
    Mode ``draft`` requires a path; the server errors out at startup
    otherwise."""
    if draft_model is None:
        draft_model = (
            os.environ.get("DLLAMA_DRAFT_MODEL", "").strip() or None
        )
    return draft_model


class NgramIndex:
    """Last-two-occurrence n-gram index over one lane's token stream.

    For every n in [1, max_n] maps the n-gram ending at each position to
    the *continuation start* of its latest and previous occurrences.
    Two deep matters: the current suffix always matches its own entry
    (whose continuation is empty), so lookups fall back to the previous
    occurrence to find real continuation tokens.
    """

    def __init__(self, max_n: int = DEFAULT_MAX_NGRAM) -> None:
        self.max_n = max(1, int(max_n))
        self.tokens: List[int] = []
        # per n: ngram -> (latest continuation start, previous or -1)
        self._occ: List[Dict[Tuple[int, ...], Tuple[int, int]]] = [
            {} for _ in range(self.max_n)
        ]

    def extend(self, tokens: Sequence[int]) -> None:
        for raw in tokens:
            self.tokens.append(int(raw))
            i = len(self.tokens)
            for n in range(1, self.max_n + 1):
                if i < n:
                    break
                key = tuple(self.tokens[i - n : i])
                d = self._occ[n - 1]
                prev = d.get(key)
                d[key] = (i, prev[0] if prev is not None else -1)

    def lookup(self, k: int) -> List[int]:
        """``k`` tokens predicted to follow the current suffix, read
        from the most recent *earlier* occurrence of the longest
        matching suffix n-gram ([] if the suffix has never been seen
        before).

        When the match sits close to the end of history — a stream in a
        short cycle, where the previous occurrence is one period back —
        the continuation is extended *cyclically*: once the copy runs
        past the end of recorded history it keeps reading from the
        draft itself, predicting that the period-``end - p`` repetition
        continues.  Without this a period-1 stall would only ever yield
        one draft token no matter how large ``k`` is.
        """
        return self.lookup_suffix(self.tokens, k)

    def lookup_suffix(self, suffix: Sequence[int], k: int) -> List[int]:
        """:meth:`lookup` generalized to an EXTERNAL query suffix: ``k``
        tokens this index's stream continued the longest matching
        suffix n-gram of ``suffix`` with.  This is the cross-lane read
        path — a sibling lane asks every store index "how did *your*
        stream continue my current suffix?".  An occurrence that ends
        exactly at this stream's end has an empty continuation and
        falls back to the previous occurrence, same as the own-suffix
        case; cyclic extension applies unchanged."""
        return self.lookup_suffix_n(suffix, k)[0]

    def lookup_suffix_n(
        self, suffix: Sequence[int], k: int
    ) -> Tuple[List[int], int]:
        """:meth:`lookup_suffix` plus the length ``n`` of the suffix
        n-gram that matched (0 on miss) — the cross-source quality
        signal the drafter ranks private vs shared candidates by."""
        toks = self.tokens
        end = len(toks)
        ns = len(suffix)
        if end == 0 or ns == 0 or k < 1:
            return [], 0
        for n in range(min(self.max_n, ns, end), 0, -1):
            key = tuple(int(t) for t in suffix[ns - n:])
            hit = self._occ[n - 1].get(key)
            if hit is None:
                continue
            # hit[0] may be the (empty-continuation) entry ending at
            # this stream's end; the previous occurrence is usable.
            p = hit[1] if hit[0] >= end else hit[0]
            if p < 0 or p >= end:
                continue
            out: List[int] = []
            for j in range(k):
                src = p + j
                out.append(toks[src] if src < end else out[src - end])
            return out, n
        return [], 0


class SharedNgramStore:
    """Cross-lane n-gram store keyed by radix-tree anchor identity.

    One *group* per radix ``node_id`` (the anchor a lane's admission
    match reported — see ``kv/radix.py``); inside a group, one
    :class:`NgramIndex` per publishing stream holding that stream's
    accepted continuation past the anchor.  A lane drafting under
    anchor ``N`` asks every *sibling* stream's index (its own
    continuation already lives in its private index) for the
    continuation of its current suffix, most recently published stream
    first.

    Bounded on every axis (groups, streams per group, tokens per
    stream), all LRU: anchor ids retired by radix eviction simply age
    out.  ``lock`` (lockwatch-tracked, leaf — nothing else is acquired
    under it) serializes scheduler publishes/lookups against `/metrics`
    and debug readers; the publish-while-draft interleavings are
    replayed deterministically in ``tests/test_spec.py``.
    """

    def __init__(
        self,
        max_n: int = DEFAULT_SHARED_MAX_NGRAM,
        max_groups: int = 64,
        max_streams_per_group: int = 8,
        max_tokens_per_stream: int = 4096,
    ) -> None:
        self.max_n = max(1, int(max_n))
        self.max_groups = max(1, int(max_groups))
        self.max_streams_per_group = max(1, int(max_streams_per_group))
        self.max_tokens_per_stream = max(1, int(max_tokens_per_stream))
        self.lock = make_lock("spec.shared_store")
        self._groups: "OrderedDict[int, OrderedDict[str, NgramIndex]]" = (
            OrderedDict()
        )
        self.n_hits = 0
        self.n_misses = 0

    def publish(
        self, anchor: int, stream_id: str, tokens: Sequence[int]
    ) -> None:
        """Append ``tokens`` (an accepted run of ``stream_id``'s
        continuation past ``anchor``) to the stream's group index.
        Tokens past the per-stream cap are dropped (bounded memory; the
        hot fanout prefix repeats early, not at token 4096)."""
        if not tokens:
            return
        with self.lock:
            group = self._groups.get(anchor)
            if group is None:
                group = OrderedDict()
                self._groups[anchor] = group
                while len(self._groups) > self.max_groups:
                    self._groups.popitem(last=False)
            else:
                self._groups.move_to_end(anchor)
            idx = group.get(stream_id)
            if idx is None:
                idx = NgramIndex(self.max_n)
                group[stream_id] = idx
                while len(group) > self.max_streams_per_group:
                    group.popitem(last=False)
            else:
                group.move_to_end(stream_id)
            room = self.max_tokens_per_stream - len(idx.tokens)
            if room > 0:
                idx.extend(list(tokens)[:room])

    def lookup(
        self,
        anchor: int,
        suffix: Sequence[int],
        k: int,
        exclude_stream: Optional[str] = None,
    ) -> List[int]:
        """``k`` tokens some SIBLING stream under ``anchor`` continued
        ``suffix`` with ([] when no sibling has seen it).  Streams are
        consulted most-recently-published first — deterministic for a
        seeded replay, and the freshest sibling is the likeliest to
        share the query lane's trajectory."""
        return self.lookup_n(anchor, suffix, k, exclude_stream)[0]

    def lookup_n(
        self,
        anchor: int,
        suffix: Sequence[int],
        k: int,
        exclude_stream: Optional[str] = None,
    ) -> Tuple[List[int], int]:
        """:meth:`lookup` plus the length of the matched suffix n-gram
        (0 on miss): the BEST match across siblings — longest n wins,
        recency breaks ties — so the drafter can rank the shared
        candidate against its private one on equal terms."""
        best: List[int] = []
        best_n = 0
        with self.lock:
            group = self._groups.get(anchor)
            if group:
                self._groups.move_to_end(anchor)
                for sid in reversed(group):
                    if sid == exclude_stream:
                        continue
                    out, n = group[sid].lookup_suffix_n(suffix, k)
                    if out and n > best_n:
                        best, best_n = out, n
            if best:
                self.n_hits += 1
            else:
                self.n_misses += 1
            return best, best_n

    def stats(self) -> Dict[str, int]:
        """Size/hit counters for the shared-store gauges."""
        with self.lock:
            return {
                "groups": len(self._groups),
                "streams": sum(len(g) for g in self._groups.values()),
                "tokens": sum(
                    len(i.tokens)
                    for g in self._groups.values()
                    for i in g.values()
                ),
                "hits": self.n_hits,
                "misses": self.n_misses,
            }


class NgramDrafter:
    """Per-lane drafter: n-gram prompt lookup plus AIMD draft-length
    adaptation.

    ``update`` feeds the lane's history (only the unseen tail is
    indexed), ``draft`` proposes up to the current adaptive ``k``
    tokens, and ``feedback`` adapts after each verify: full acceptance
    grows ``k`` additively, under-half acceptance halves it, and zero
    acceptance additionally pauses drafting for a few ticks — the
    context is clearly not in a repetitive stretch, so the lane rejoins
    the plain decode block instead of wasting verify dispatches.

    Second-generation sources compose here.  With a
    :class:`SharedNgramStore` attached (mode ``shared``/``draft``),
    ``update`` additionally PUBLISHES the history tail past the lane's
    radix anchor into the store, and ``draft`` ranks the store's best
    sibling continuation against the private candidate by matched
    n-gram length — longest match wins, ties go private; with
    ``use_draft_model`` (mode ``draft``), ``model_budget`` tells the
    scheduler how many draft-model tokens to propose when both n-gram
    sources ran dry this tick, or when the lane is cooling down after
    a fully rejected n-gram draft (the model carries none of the
    discredited n-gram evidence, so the cooldown re-routes the budget
    to it instead of idling).  ``last_source`` records which source
    produced the tick's draft (the ``dllama_spec_source_total`` label);
    the single AIMD ``k`` and cooldown are shared across all sources.
    """

    def __init__(
        self,
        k_max: int = DEFAULT_SPEC_K,
        max_n: int = DEFAULT_MAX_NGRAM,
        cooldown: int = DEFAULT_COOLDOWN,
        shared_store: Optional[SharedNgramStore] = None,
        stream_id: str = "",
        anchor: Optional[int] = None,
        anchor_offset: int = 0,
        use_draft_model: bool = False,
    ) -> None:
        self.k_max = max(1, int(k_max))
        self.k = self.k_max
        self.index = NgramIndex(max_n)
        self._cooldown_len = max(0, int(cooldown))
        self._cooldown = 0
        self.n_drafted = 0
        self.n_accepted = 0
        self.shared_store = shared_store
        self.stream_id = stream_id
        self.anchor = anchor
        # absolute history position where the anchor's continuation
        # begins; tokens before it are the (shared) matched prefix and
        # are never published
        self.anchor_offset = max(0, int(anchor_offset))
        self.use_draft_model = bool(use_draft_model)
        # absolute history length already published to the store
        self._published = self.anchor_offset
        #: source of the last non-empty draft (SOURCE_* label); the
        #: scheduler sets SOURCE_DRAFT itself after model drafting
        self.last_source: Optional[str] = None
        self._skip = False  # this tick is a cooldown tick
        # cooldown tick whose budget is re-routed to the draft model
        self._model_tick = False

    def rebind(self, anchor: Optional[int], anchor_offset: int) -> None:
        """Re-anchor after a park/resume or recovery re-admission whose
        radix match landed on a different node (prefix re-matched after
        eviction, or the first match on a recovery path).  The private
        index, AIMD ``k`` and cooldown all survive — that is the whole
        point of warm-starting; only the publish cursor resets so the
        continuation-past-NEW-anchor is published under the new id."""
        if anchor == self.anchor:
            return
        self.anchor = anchor
        self.anchor_offset = max(0, int(anchor_offset))
        self._published = self.anchor_offset

    def update(self, history: Sequence[int]) -> None:
        seen = len(self.index.tokens)
        if len(history) > seen:
            self.index.extend(history[seen:])
        if self.shared_store is not None and self.anchor is not None:
            if self._published < self.anchor_offset:
                self._published = self.anchor_offset
            if len(history) > self._published:
                start = self._published
                if start == self.anchor_offset and start > 0:
                    # seed the junction on the first publish: without
                    # the tail of the anchor prefix in the index, a
                    # sibling whose suffix still ends in prefix tokens
                    # (its very first post-anchor tick) can never match
                    # the run's opening tokens. The prefix up to the
                    # anchor is shared by every group member (that is
                    # what the radix match certifies), so these tokens
                    # are common knowledge, not a leak.
                    start = max(
                        0, start - (self.shared_store.max_n - 1)
                    )
                self.shared_store.publish(
                    self.anchor, self.stream_id, history[start:]
                )
                self._published = len(history)

    def draft(self, budget: Optional[int] = None) -> List[int]:
        self.last_source = None
        self._skip = False
        self._model_tick = False
        if self._cooldown > 0:
            self._cooldown -= 1
            self._skip = True
            # the n-gram evidence was just contradicted by a verify
            # (zero-acceptance draft); in mode ``draft`` the cooldown
            # re-routes this tick's budget to the resident model —
            # which carries none of that evidence — instead of idling
            self._model_tick = self.use_draft_model
            return []
        k = self.k if budget is None else min(self.k, budget)
        if k < 1:
            self._skip = True
            return []
        # longest-match-wins across the two n-gram sources: a private
        # 1-gram echo must not starve a sibling's max_n-long replay of
        # this exact trajectory (byte-level streams almost always have
        # SOME short self-repeat, so "private first, shared on miss"
        # would never consult the store). Ties go private — the lane's
        # own continuation is the safer bet at equal evidence.
        toks = self.index.tokens
        out, n_private = self.index.lookup_suffix_n(toks, k)
        if out:
            self.last_source = SOURCE_NGRAM
        if (
            self.shared_store is not None
            and self.anchor is not None
            and n_private < self.shared_store.max_n  # a match at the
            # store's full horizon cannot be beaten, so skip the lock
        ):
            suffix = toks[-self.shared_store.max_n:] if toks else []
            shared, n_shared = self.shared_store.lookup_n(
                self.anchor, suffix, k, exclude_stream=self.stream_id
            )
            if shared and n_shared > n_private:
                self.last_source = SOURCE_SHARED
                return shared
        return out

    def model_budget(self, budget: Optional[int] = None) -> int:
        """Draft-model token budget for this tick: the adaptive ``k``
        when the draft model is enabled and this tick's n-gram sources
        came up empty — or the lane is cooling down after an n-gram
        draft was fully rejected (the cooldown re-routes to the model
        rather than idling the lane) — else 0."""
        if not self.use_draft_model or self.last_source:
            return 0
        if self._skip and not self._model_tick:
            return 0
        k = self.k if budget is None else min(self.k, budget)
        return max(0, k)

    def feedback(self, proposed: int, accepted: int) -> None:
        self.n_drafted += proposed
        self.n_accepted += accepted
        if proposed <= 0:
            return
        if accepted >= proposed:
            self.k = min(self.k_max, self.k + 1)
        elif accepted * 2 < proposed:
            self.k = max(1, self.k // 2)
            # a fully rejected n-gram draft discredits the index for a
            # few ticks; a failed MODEL draft must not re-arm the
            # cooldown, or mode ``draft`` would pin a misfiring model
            # to the lane forever (cooldown -> model -> cooldown ...)
            if accepted == 0 and self.last_source != SOURCE_DRAFT:
                self._cooldown = self._cooldown_len
