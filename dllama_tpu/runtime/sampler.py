"""Token sampler: argmax / temperature / top-p nucleus.

Behavioral port of the reference Sampler (src/tokenizer.cpp:392-520),
including its xorshift* RNG so that seeded runs are reproducible across the
two implementations. Operates on host numpy over the final logits row; the
engine also offers fused on-device greedy sampling for the decode hot loop
(see runtime/engine.py) — this class is the reference-parity path.
"""

from __future__ import annotations

import numpy as np

_U64 = (1 << 64) - 1


class XorshiftRng:
    """xorshift* PRNG (reference: src/tokenizer.cpp:25-35)."""

    def __init__(self, seed: int):
        self.state = seed & _U64

    def random_u32(self) -> int:
        s = self.state
        s ^= (s >> 12) & _U64
        s = (s ^ (s << 25)) & _U64
        s ^= (s >> 27) & _U64
        self.state = s
        return ((s * 0x2545F4914F6CDD1D) & _U64) >> 32

    def random_f32(self) -> float:
        # float32 in [0, 1)
        return (self.random_u32() >> 8) / 16777216.0


def softmax(x: np.ndarray) -> np.ndarray:
    x = x - x.max()
    e = np.exp(x, dtype=np.float32)
    return e / e.sum()


def sample_argmax(probs: np.ndarray) -> int:
    return int(np.argmax(probs))


def sample_mult(probs: np.ndarray, coin: float) -> int:
    """Sample from a normalized distribution (reference: sample_mult)."""
    cdf = np.cumsum(probs, dtype=np.float32)
    idx = int(np.searchsorted(cdf, coin, side="right"))
    return min(idx, len(probs) - 1)


def topp_support(probs: np.ndarray, topp: float) -> tuple[np.ndarray, np.ndarray]:
    """Nucleus candidate set: (token ids in descending-prob order, their
    cumulative sums). Keeps the smallest prefix whose mass exceeds topp,
    including the crossing token, over the reference's cutoff pre-filter
    (src/tokenizer.cpp:426-467); the whole filtered set when the f32
    cumsum never crosses. Shared by sample_topp and the device-mask
    equivalence test."""
    n = len(probs)
    cutoff = (1.0 - topp) / (n - 1)
    idx = np.nonzero(probs >= cutoff)[0]
    # descending sort; stable to make ties deterministic
    order = idx[np.argsort(-probs[idx], kind="stable")]
    csum = np.cumsum(probs[order], dtype=np.float32)
    over = np.nonzero(csum > topp)[0]
    last = int(over[0]) if len(over) else len(order) - 1
    return order[: last + 1], csum[: last + 1]


def sample_topp(probs: np.ndarray, topp: float, coin: float) -> int:
    """Nucleus sampling (reference: src/tokenizer.cpp:426-467)."""
    order, csum = topp_support(probs, topp)
    last = len(order) - 1
    r = coin * csum[last]
    pick = int(np.searchsorted(csum, r, side="right"))
    pick = min(pick, last)
    return int(order[pick])


class Sampler:
    """(reference: src/tokenizer.hpp:77-91)"""

    def __init__(self, vocab_size: int, temperature: float, topp: float, seed: int):
        self.vocab_size = vocab_size
        self.temperature = temperature
        self.topp = topp
        self.rng = XorshiftRng(seed)

    def set_temp(self, temperature: float) -> None:
        self.temperature = temperature

    def set_topp(self, topp: float) -> None:
        self.topp = topp

    def set_seed(self, seed: int) -> None:
        self.rng = XorshiftRng(seed)

    def sample(self, logits: np.ndarray) -> int:
        """Sample the next token from a logits row (reference: Sampler::sample)."""
        logits = np.asarray(logits, dtype=np.float32).reshape(-1)
        assert logits.shape[0] == self.vocab_size, (
            f"logits size {logits.shape[0]} != vocab {self.vocab_size}"
        )
        if self.temperature == 0.0:
            return sample_argmax(logits)
        probs = softmax(logits / self.temperature)
        coin = self.rng.random_f32()
        if self.topp <= 0 or self.topp >= 1:
            return sample_mult(probs, coin)
        return sample_topp(probs, self.topp, coin)
