"""Deterministic fault-injection plane for chaos testing the serving path.

The Interleaver (analysis/lockwatch.py) made THREAD SCHEDULES replayable;
this module does the same for FAILURES: a seeded, schedule-driven
``FaultPlane`` whose named injection sites are threaded through the
engine's dispatch entries (decode / verify / prefill-chunk / kv_adopt /
kv_publish), the KV pool's allocation path, the SSE flush, and the AOT
prefetch thread. A chaos run is then an input (``--faults`` /
``DLLAMA_FAULTS``), not an accident — the same spec replays the same
faults at the same draw counts, so a recovery bug reproduces on the
first try instead of the thousandth soak.

Spec grammar (comma-separated schedules)::

    site[:key=value]*[,site[:key=value]*]...

    dispatch:p=0.05:seed=7          5% of dispatch draws fail (seeded)
    kv_alloc:nth=12                 exactly the 12th kv_alloc draw fails
    dispatch:every=40:kind=poison   every 40th draw poisons the cache
    dispatch:op=decode_lanes:nth=3  3rd decode_lanes dispatch only
    sse_flush:p=0.01:seed=3:n=5     at most 5 injected flush failures

Keys: ``p`` (per-draw probability, seeded), ``nth`` (1-based draw index,
fires once), ``every`` (periodic), ``n`` (cap on total injections),
``seed`` (per-schedule RNG seed), ``kind`` (``transient`` — raised
BEFORE the donated-buffer guard, KV state intact, retryable; ``poison``
— raised INSIDE the guard, the cache epoch moves and the scheduler must
recover lanes), ``op`` (restrict a ``dispatch`` schedule to one engine
entry point). Exactly one of ``p``/``nth``/``every`` per schedule.

Sites today: ``dispatch`` (all five engine entries, filter with ``op=``),
``kv_alloc`` (pool-allocation failure on the publish path), ``sse_flush``
(client socket death mid-stream), ``prefetch`` (AOT compile thread).

Every injection increments ``dllama_faults_injected_total{site}`` and
records a ``fault_injected`` event in the flight recorder, so a chaos
run's postmortems show which failures were injected vs organic.
See docs/resilience.md for the failure taxonomy and recovery semantics.
"""

from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass, field

from ..obs.metrics import get_registry
from ..obs.recorder import get_recorder

KNOWN_SITES = ("dispatch", "kv_alloc", "sse_flush", "prefetch")
KINDS = ("transient", "poison")


class FaultSpecError(ValueError):
    """A ``--faults`` / ``DLLAMA_FAULTS`` spec that cannot be parsed."""


class InjectedFault(RuntimeError):
    """The exception an armed schedule raises at its site. ``poison``
    tells the raiser WHERE to raise it (inside or outside the
    donated-buffer guard), which is what makes the two failure classes
    distinguishable to the scheduler's epoch check."""

    def __init__(self, site: str, op: str | None, kind: str, seq: int):
        self.site = site
        self.op = op
        self.kind = kind
        self.seq = seq  # per-schedule injection index (1-based)
        where = f"{site}:{op}" if op else site
        super().__init__(
            f"injected {kind} fault #{seq} at {where} (chaos schedule)"
        )

    @property
    def poison(self) -> bool:
        return self.kind == "poison"


@dataclass
class _Schedule:
    site: str
    op: str | None = None
    p: float = 0.0
    nth: int = 0
    every: int = 0
    n: int = 0  # max injections (0 = nth fires once, p/every unbounded)
    seed: int = 0
    kind: str = "transient"
    draws: int = 0
    injected: int = 0
    rng: random.Random = field(default_factory=random.Random)

    def should_fire(self) -> bool:
        """Called with the plane lock held; advances this schedule's draw
        counter and decides deterministically."""
        self.draws += 1
        cap = self.n if self.n > 0 else (1 if self.nth > 0 else 0)
        if cap and self.injected >= cap:
            return False
        if self.nth > 0:
            fire = self.draws == self.nth
        elif self.every > 0:
            fire = self.draws % self.every == 0
        else:
            fire = self.rng.random() < self.p
        if fire:
            self.injected += 1
        return fire


def parse_fault_spec(spec: str) -> list[_Schedule]:
    """Parse the ``--faults`` grammar into schedules (see module
    docstring); raises :class:`FaultSpecError` on malformed input so a
    typo'd chaos run dies at startup, not silently fault-free."""
    schedules: list[_Schedule] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        site = fields[0].strip()
        if site not in KNOWN_SITES:
            raise FaultSpecError(
                f"unknown fault site {site!r} (known: {', '.join(KNOWN_SITES)})"
            )
        sched = _Schedule(site=site)
        for f in fields[1:]:
            key, sep, value = f.partition("=")
            key = key.strip()
            if not sep:
                raise FaultSpecError(f"expected key=value, got {f!r}")
            try:
                if key == "p":
                    sched.p = float(value)
                    if not 0.0 <= sched.p <= 1.0:
                        raise FaultSpecError(f"p={value} outside [0, 1]")
                elif key == "nth":
                    sched.nth = int(value)
                    if sched.nth < 1:
                        raise FaultSpecError("nth must be >= 1")
                elif key == "every":
                    sched.every = int(value)
                    if sched.every < 1:
                        raise FaultSpecError("every must be >= 1")
                elif key == "n":
                    sched.n = int(value)
                elif key == "seed":
                    sched.seed = int(value)
                elif key == "kind":
                    if value not in KINDS:
                        raise FaultSpecError(
                            f"unknown fault kind {value!r} "
                            f"(known: {', '.join(KINDS)})"
                        )
                    sched.kind = value
                elif key == "op":
                    sched.op = value
                else:
                    raise FaultSpecError(f"unknown fault key {key!r}")
            except ValueError as e:
                if isinstance(e, FaultSpecError):
                    raise
                raise FaultSpecError(f"bad value in {f!r}: {e}") from e
        n_triggers = sum(
            1 for v in (sched.p > 0, sched.nth > 0, sched.every > 0) if v
        )
        if n_triggers != 1:
            raise FaultSpecError(
                f"schedule {part!r} needs exactly one of p=/nth=/every="
            )
        sched.rng = random.Random(sched.seed)
        schedules.append(sched)
    return schedules


class FaultPlane:
    """Holds the armed schedules and serves ``draw()`` calls from the
    injection sites. With no schedules (the production default) a draw
    is one attribute read and an early return — the plane costs nothing
    when chaos is off."""

    def __init__(self, spec: str = "") -> None:
        self.spec = spec
        self.schedules = parse_fault_spec(spec) if spec else []
        self._lock = threading.Lock()
        self._m_injected = None
        if self.schedules:
            self._m_injected = get_registry().counter(
                "dllama_faults_injected_total",
                "Faults injected by the chaos plane, by site "
                "(runtime/faults.py; 0 series when no schedule is armed).",
                labelnames=("site",),
            )

    @property
    def armed(self) -> bool:
        return bool(self.schedules)

    def draw(self, site: str, op: str | None = None) -> InjectedFault | None:
        """One potential injection point was reached: every schedule for
        ``site`` (whose ``op`` filter matches) advances its draw counter;
        the first that fires wins. Returns the fault to raise, or None."""
        if not self.schedules:
            return None
        fault = None
        with self._lock:
            for s in self.schedules:
                if s.site != site or (s.op is not None and s.op != op):
                    continue
                if s.should_fire() and fault is None:
                    fault = InjectedFault(site, op, s.kind, s.injected)
        if fault is not None:
            if self._m_injected is not None:
                self._m_injected.labels(site=site).inc()
            get_recorder().record(
                "fault_injected", site=site, op=op, fault_kind=fault.kind,
                seq=fault.seq,
            )
        return fault

    def counts(self) -> dict[str, int]:
        """Injected-fault totals by site (test/bench introspection)."""
        out: dict[str, int] = {}
        with self._lock:
            for s in self.schedules:
                out[s.site] = out.get(s.site, 0) + s.injected
        return out


_PLANE = FaultPlane(os.environ.get("DLLAMA_FAULTS", ""))


def get_fault_plane() -> FaultPlane:
    """The process-wide plane every injection site consults."""
    return _PLANE


def set_fault_plane(spec: str) -> FaultPlane:
    """Arm (or with ``""`` disarm) the process-wide plane; returns it.
    Tests and the bench install per-run schedules through this, the CLI
    through ``--faults``."""
    global _PLANE
    _PLANE = FaultPlane(spec)
    return _PLANE
