"""Predictive SLO-aware admission control (ISSUE 20).

PR 12's admission gate is reactive: it sheds on queue depth after the
queue has already built up, with a constant ``Retry-After``. This module
closes the loop from measurement to control. :class:`LoadPredictor`
forecasts a candidate request's TTFT and steady-state TPOT *before* the
scheduler commits a lane to it, from three inputs the engine already
tracks:

* the per-program cost model (``obs/cost.py`` analytic bytes over the
  chip's HBM peak — the cold-start floor before any step has run) and
  the observed ``dllama_engine_step_seconds`` percentiles for the
  relevant prefill-chunk / decode-block kinds once they exist;
* current occupancy — active lanes, parked streams, queued admission
  chunks, and the pending queue ahead of the candidate
  (:class:`OccupancySnapshot`, assembled by the scheduler under its
  lock);
* the radix-tree match length: a matched prefix is prefill the engine
  will skip, so a warm-prefix request is predicted (and admitted)
  cheaper than a cold one of the same length.

Requests carry optional deadline hints (``deadline_ms`` /
``ttft_budget_ms`` body fields; ``x-dllama-deadline-ms`` forwarded by
the fleet router). The scheduler turns the forecast into three control
actions:

* **infeasible-reject** — a hinted request whose predicted TTFT cannot
  meet its budget even if admitted now is rejected up front with a
  structured retryable error whose ``Retry-After`` is the predicted
  queue-drain time (monotonic in queue depth), not a constant. Unhinted
  requests are NEVER infeasible-rejected: with no hints the controller
  degrades exactly to the PR 12 ladder.
* **EDF lane picking** — the pending queue is ordered by earliest
  effective deadline (:func:`effective_deadline_ms`). The PR 12
  priority ladder becomes deadline *offsets* (high before normal before
  low, FIFO within a class), so ordering is unchanged when no hints are
  given.
* **deadline preemption** — an over-budget or deadline-blown
  low-priority stream is parked through the PR 16 ``_park_stream`` /
  resume contract when that flips a feasible hinted request from
  "reject" to "meet SLO". Parking never alters tokens, so preempted
  streams stay byte-identical on resume.

Prediction error (estimated vs observed TTFT/TPOT) is a first-class
metric; an EWMA multiplicative correction factor folds the observed
ratio back into the predictor so it self-calibrates on real hardware.
Prediction only gates and orders work — it never touches
``decode_lanes`` inputs — so greedy output under predictive admission
is byte-identical to predictive-off runs by construction.
"""

from __future__ import annotations

import math
import os
import threading
import time

from typing import Callable

# step-histogram kinds the predictor reads (engine._m_step labels)
PREFILL_KIND = "prefill_lane_chunk"
DECODE_KIND = "decode_lanes"

# priority -> effective-deadline offset multiplier (offset = mult * step)
PRIORITY_OFFSET_MULT = {"high": -1.0, "normal": 0.0, "low": 1.0}

# EWMA correction clamp: a single wild observation (compile stall, GC
# pause) must not swing the predictor by more than this factor per side
_CORR_MIN, _CORR_MAX = 0.1, 10.0


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    return int(v) if v else default


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name, "").strip().lower()
    if not v:
        return default
    return v not in ("0", "off", "false", "no")


def resolve_admission_knobs(
    predict: bool | None = None,
    max_wait_ms: int | None = None,
) -> tuple[bool, int]:
    """Predictive-admission knob resolution, same precedence as the lane
    knobs: explicit (CLI flag) beats env beats default.

    * ``DLLAMA_ADMISSION_PREDICT`` — enable the predictive controller
      (infeasible-reject, EDF ordering, deadline preemption); default
      off = pure PR 12 reactive ladder.
    * ``DLLAMA_ADMISSION_MAX_WAIT_MS`` — cap on the predicted queue
      wait a hint-less request may be quoted in ``Retry-After``
      (default 30000; also the ceiling for the drain estimate so one
      absurd forecast cannot quote an hour).
    """
    if predict is None:
        predict = _env_bool("DLLAMA_ADMISSION_PREDICT")
    if max_wait_ms is None:
        max_wait_ms = _env_int("DLLAMA_ADMISSION_MAX_WAIT_MS", 30_000)
    return bool(predict), int(max_wait_ms)


def resolve_deadline_knobs(
    default_ms: int | None = None,
    priority_step_ms: int | None = None,
) -> tuple[int, int]:
    """Deadline-synthesis knobs for requests with no hints.

    * ``DLLAMA_DEADLINE_DEFAULT_MS`` — synthetic deadline horizon for
      unhinted requests (default 600000 = 10 min: effectively "no
      deadline" for feasibility, but it anchors EDF ordering).
    * ``DLLAMA_DEADLINE_PRIORITY_STEP_MS`` — the offset between
      priority rungs (default 60000): ``high`` runs one step earlier
      than ``normal``, ``low`` one step later, so strict priority
      ordering is preserved for any queue that drains inside a step
      while a long-starved ``low`` request still ages into service.
    """
    if default_ms is None:
        default_ms = _env_int("DLLAMA_DEADLINE_DEFAULT_MS", 600_000)
    if priority_step_ms is None:
        priority_step_ms = _env_int(
            "DLLAMA_DEADLINE_PRIORITY_STEP_MS", 60_000
        )
    return int(default_ms), int(priority_step_ms)


def effective_deadline_ms(
    arrival_ms: float,
    priority: str = "normal",
    deadline_ms: float | None = None,
    ttft_budget_ms: float | None = None,
    default_ms: int = 600_000,
    priority_step_ms: int = 60_000,
) -> float:
    """The EDF sort key for one request, in the caller's clock domain.

    A hinted request's effective deadline is its arrival plus the
    tighter of its hints. An unhinted request gets a synthetic deadline
    ``arrival + default + offset(priority)`` — the priority ladder as
    deadline offsets, so with no hints EDF ordering is (priority class,
    arrival), exactly the PR 12 contract.
    """
    hint = None
    for h in (deadline_ms, ttft_budget_ms):
        if h is not None and (hint is None or h < hint):
            hint = h
    if hint is not None:
        return arrival_ms + float(hint)
    mult = PRIORITY_OFFSET_MULT.get(priority, 0.0)
    return arrival_ms + float(default_ms) + mult * float(priority_step_ms)


class OccupancySnapshot:
    """One consistent view of scheduler load, taken under the scheduler
    condition variable (see ``LaneScheduler.occupancy``). The engine
    contributes the static shape (lane count, chunk/block sizes); the
    scheduler contributes the dynamic load."""

    __slots__ = (
        "lanes_total", "active_lanes", "parked", "admitting",
        "admitting_chunks", "queue_depth", "block_size", "admission_chunk",
    )

    def __init__(
        self,
        lanes_total: int,
        active_lanes: int,
        parked: int = 0,
        admitting: int = 0,
        admitting_chunks: int = 0,
        queue_depth: int = 0,
        block_size: int = 16,
        admission_chunk: int = 128,
    ) -> None:
        self.lanes_total = lanes_total
        self.active_lanes = active_lanes
        self.parked = parked
        self.admitting = admitting
        self.admitting_chunks = admitting_chunks
        self.queue_depth = queue_depth
        self.block_size = block_size
        self.admission_chunk = admission_chunk

    @property
    def free_lanes(self) -> int:
        return max(
            0, self.lanes_total - self.active_lanes - self.admitting
        )

    @property
    def oversubscription(self) -> float:
        """Streams per lane (>= 1.0): parked streams time-share lanes
        through the PR 16 park/resume rotation, stretching every
        stream's effective TPOT by roughly this factor."""
        if self.lanes_total <= 0:
            return 1.0
        streams = self.active_lanes + self.admitting + self.parked
        return max(1.0, streams / self.lanes_total)

    def as_dict(self) -> dict:
        return {
            "lanes_total": self.lanes_total,
            "active_lanes": self.active_lanes,
            "free_lanes": self.free_lanes,
            "parked": self.parked,
            "admitting": self.admitting,
            "admitting_chunks": self.admitting_chunks,
            "queue_depth": self.queue_depth,
            "oversubscription": round(self.oversubscription, 3),
        }


class Prediction:
    """One forecast: predicted TTFT / steady-state TPOT for a candidate
    plus the queue-drain estimate behind its ``Retry-After``."""

    __slots__ = ("ttft_ms", "tpot_ms", "queue_wait_ms", "prefill_chunks")

    def __init__(
        self,
        ttft_ms: float,
        tpot_ms: float,
        queue_wait_ms: float,
        prefill_chunks: int,
    ) -> None:
        self.ttft_ms = ttft_ms
        self.tpot_ms = tpot_ms
        self.queue_wait_ms = queue_wait_ms
        self.prefill_chunks = prefill_chunks

    def as_dict(self) -> dict:
        return {
            "ttft_ms": round(self.ttft_ms, 3),
            "tpot_ms": round(self.tpot_ms, 3),
            "queue_wait_ms": round(self.queue_wait_ms, 3),
            "prefill_chunks": self.prefill_chunks,
        }


class LoadPredictor:
    """TTFT/TPOT forecaster over the engine's own physics.

    Step costs come from the measured ``dllama_engine_step_seconds``
    p50 per kind once at least ``min_step_samples`` dispatches exist;
    before that, from the XLA cost model (bytes accessed over the HBM
    peak) via :func:`~dllama_tpu.obs.cost.analytic_step_seconds`; and
    as a last resort from conservative floor constants, so the
    predictor always returns a finite forecast. An EWMA correction
    factor (observed/predicted ratio per signal) self-calibrates the
    model against what the serving path actually delivers.

    Thread-safety: predictions run on HTTP handler threads while
    observations land from the scheduler thread; the correction state
    takes one short lock.
    """

    # floors used before any measurement or cost model exists; generous
    # on purpose — an optimistic cold predictor would admit infeasible
    # work, a pessimistic one merely queues the first request
    COLD_PREFILL_CHUNK_S = 0.050
    COLD_DECODE_STEP_S = 0.020
    MIN_STEP_SAMPLES = 5

    def __init__(
        self,
        engine: object,
        clock: Callable[[], float] = time.monotonic,
        alpha: float = 0.2,
    ) -> None:
        self.engine = engine
        self._clock = clock
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        # multiplicative EWMA corrections, observed/predicted
        self._ttft_corr = 1.0
        self._tpot_corr = 1.0
        self._n_obs = 0
        # analytic per-kind step seconds, resolved lazily once (the
        # compile cache walk is not free; invalidated never — the cost
        # model only tightens as more programs compile, and measured
        # percentiles take over after MIN_STEP_SAMPLES anyway)
        self._analytic: dict[str, float | None] = {}

    # -- step costs --------------------------------------------------------

    def _measured_step_s(self, kind: str) -> float | None:
        hist = getattr(self.engine, "_m_step", None)
        if hist is None:
            return None
        try:
            child = hist.labels(kind=kind)
        except Exception:  # dlint: disable=silent-except — best-effort cost probe; the predictor's cold floor is the documented fallback
            return None
        if getattr(child, "count", 0) < self.MIN_STEP_SAMPLES:
            return None
        return child.percentile(0.5)

    def _analytic_step_s(self, kind: str) -> float | None:
        if kind in self._analytic:
            return self._analytic[kind]
        est = None
        try:
            from ..obs.cost import analytic_step_seconds, hbm_peak_bytes_per_s

            peak = hbm_peak_bytes_per_s()
            report = self.engine.cost_report()
            info = report.get("kinds", {}).get(kind)
            if info is not None:
                est = analytic_step_seconds(
                    info.get("bytes_accessed"), peak
                )
        except Exception:  # dlint: disable=silent-except — cost model is advisory; a failed walk degrades to the cold floor, never blocks admission
            est = None
        self._analytic[kind] = est
        return est

    def step_seconds(self, kind: str, cold_default: float) -> float:
        """Best available estimate of one dispatch of ``kind``:
        measured p50 > analytic cost model > cold floor."""
        s = self._measured_step_s(kind)
        if s is not None and s > 0:
            return s
        s = self._analytic_step_s(kind)
        if s is not None and s > 0:
            return s
        return cold_default

    # -- forecasting -------------------------------------------------------

    def predict(
        self,
        n_prompt_tokens: int,
        occ: OccupancySnapshot,
        matched_tokens: int = 0,
    ) -> Prediction:
        """Forecast TTFT and steady-state TPOT for a candidate with
        ``n_prompt_tokens`` of prompt, of which ``matched_tokens`` are
        already resident in the radix tree (prefill the engine skips)."""
        chunk = max(1, occ.admission_chunk)
        prefill_s = self.step_seconds(PREFILL_KIND, self.COLD_PREFILL_CHUNK_S)
        decode_s = self.step_seconds(DECODE_KIND, self.COLD_DECODE_STEP_S)
        todo = max(0, int(n_prompt_tokens) - int(matched_tokens))
        # at least one chunk always runs: admission replays the last
        # matched token to produce the first logits
        n_chunks = max(1, math.ceil(todo / chunk))
        queue_wait_s = self.queue_drain_seconds(occ)
        # the admission loop interleaves one prefill chunk per tick with
        # the active lanes' decode block, so each chunk's wall time is
        # the chunk itself plus one decode dispatch when lanes are busy
        interleave_s = decode_s if occ.active_lanes > 0 else 0.0
        ttft_s = queue_wait_s + n_chunks * (prefill_s + interleave_s)
        # steady-state: one decode dispatch per token, stretched by the
        # park/resume rotation when streams oversubscribe lanes
        tpot_s = decode_s * occ.oversubscription
        with self._lock:
            ttft_corr, tpot_corr = self._ttft_corr, self._tpot_corr
        return Prediction(
            ttft_ms=ttft_s * 1000.0 * ttft_corr,
            tpot_ms=tpot_s * 1000.0 * tpot_corr,
            queue_wait_ms=queue_wait_s * 1000.0 * ttft_corr,
            prefill_chunks=n_chunks,
        )

    def queue_drain_seconds(self, occ: OccupancySnapshot) -> float:
        """Predicted time until the CURRENT backlog is admitted — what a
        shed response should quote as ``Retry-After``. Monotonic in
        queue depth by construction: every queued request adds its
        expected admission cost on top of the in-flight chunk backlog.
        """
        chunk_s = self.step_seconds(PREFILL_KIND, self.COLD_PREFILL_CHUNK_S)
        decode_s = self.step_seconds(DECODE_KIND, self.COLD_DECODE_STEP_S)
        # chunks still owed by streams mid-admission
        backlog_s = occ.admitting_chunks * chunk_s
        # each queued request: assume one admission-chunk prefill, plus
        # a share of a lane becoming free when none is (half a block of
        # decode per wave of lane turnover — a deliberately coarse but
        # monotonic stand-in for remaining stream length, which the
        # server cannot know)
        per_req_s = chunk_s
        if occ.free_lanes <= 0:
            per_req_s += max(1, occ.block_size) * decode_s * 0.5
        with self._lock:
            corr = self._ttft_corr
        return (backlog_s + occ.queue_depth * per_req_s) * corr

    def retry_after_s(
        self, occ: OccupancySnapshot, max_wait_ms: int = 30_000
    ) -> int:
        """``Retry-After`` seconds derived from the predicted drain:
        at least 1 (HTTP Retry-After is integral seconds and "now" is
        what the client just tried), capped by the max-wait knob."""
        drain_s = self.queue_drain_seconds(occ)
        cap_s = max(1.0, max_wait_ms / 1000.0)
        return int(min(cap_s, max(1.0, math.ceil(drain_s))))

    # -- feasibility -------------------------------------------------------

    def infeasible(
        self,
        pred: Prediction,
        ttft_budget_ms: float | None = None,
        deadline_ms: float | None = None,
        slack_factor: float = 1.0,
    ) -> bool:
        """Whether a hinted candidate cannot meet its budget even if
        admitted against the current occupancy. Callers must only apply
        this to requests that actually carry hints."""
        budget = None
        for h in (ttft_budget_ms, deadline_ms):
            if h is not None and (budget is None or h < budget):
                budget = h
        if budget is None:
            return False
        return pred.ttft_ms > budget * slack_factor

    # -- self-calibration --------------------------------------------------

    def observe_ttft(
        self, predicted_ms: float, observed_ms: float
    ) -> None:
        """Fold one (predicted, observed) TTFT pair into the EWMA
        correction. The ratio is clamped so one compile stall cannot
        poison the model."""
        if predicted_ms <= 0 or observed_ms <= 0:
            return
        ratio = min(_CORR_MAX, max(_CORR_MIN, observed_ms / predicted_ms))
        with self._lock:
            self._ttft_corr += self.alpha * (
                ratio * self._ttft_corr - self._ttft_corr
            )
            self._ttft_corr = min(
                _CORR_MAX, max(_CORR_MIN, self._ttft_corr)
            )
            self._n_obs += 1

    def observe_tpot(
        self, predicted_ms: float, observed_ms: float
    ) -> None:
        if predicted_ms <= 0 or observed_ms <= 0:
            return
        ratio = min(_CORR_MAX, max(_CORR_MIN, observed_ms / predicted_ms))
        with self._lock:
            self._tpot_corr += self.alpha * (
                ratio * self._tpot_corr - self._tpot_corr
            )
            self._tpot_corr = min(
                _CORR_MAX, max(_CORR_MIN, self._tpot_corr)
            )

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ttft_correction": round(self._ttft_corr, 4),
                "tpot_correction": round(self._tpot_corr, 4),
                "n_observations": self._n_obs,
                "prefill_chunk_s": round(
                    self.step_seconds(
                        PREFILL_KIND, self.COLD_PREFILL_CHUNK_S
                    ), 6,
                ),
                "decode_step_s": round(
                    self.step_seconds(
                        DECODE_KIND, self.COLD_DECODE_STEP_S
                    ), 6,
                ),
            }
