"""OpenAI-compatible HTTP API server.

Capability port of the reference's `dllama-api` (src/dllama-api.cpp):

* ``POST /v1/chat/completions`` — chat completion with ``stream`` (SSE),
  ``temperature``, ``seed``, ``max_tokens``, ``stop`` parameters
  (src/dllama-api.cpp:491-520);
* ``GET /v1/models`` — single-model listing (src/dllama-api.cpp:538-547);
* ``GET /metrics`` — Prometheus text exposition of the serving/engine
  metrics (obs/metrics.py; see docs/serving_metrics.md);
* ``GET /v1/health`` — model name, lane occupancy, queue depth, uptime;
* **NaiveCache** — on the serialized (batch_size == 1) path, KV positions
  are reused when a new request's messages are a strict superset of the
  previous conversation (src/dllama-api.cpp:298-343);
* ``GET /v1/debug/kv`` — paged-KV pool / radix-tree introspection
  (lane-scheduler path);
* ``GET /v1/debug/timeline`` — Chrome-trace/Perfetto span timeline
  (``?request_id=`` narrows to one request and adds its millisecond
  accounting; obs/spans.py);
* ``GET /v1/debug/slo`` — windowed SLO attainment / goodput snapshot
  (obs/slo.py);
* ``GET /v1/debug/series`` — in-process metrics time-series
  (obs/timeseries.py; ``?name=&window=`` for points, bare for the index);
* ``GET /v1/debug/xlalint`` — compiled-program lint over the live
  compile cache (analysis/xlalint.py; docs/static_analysis.md);
* ``GET /dashboard`` — zero-dependency live dashboard, a single
  self-contained HTML page of canvas sparklines (obs/dashboard.py);
* ``POST /v1/debug/profile`` — on-demand ``jax.profiler`` capture
  ({"seconds": 2.0}; hardened, CPU-safe; 409 while one runs).

``/v1/health`` reports ``status: degraded`` while the engine watchdog
(obs/watchdog.py) detects a stall OR the anomaly monitor
(obs/anomaly.py) has an active signal; ``degraded_reasons`` lists every
contributing source.

The reference hand-rolls an HTTP/1.1 server over raw sockets; here Python's
stdlib ThreadingHTTPServer carries the protocol. With a batch_size == 1
engine a lock serializes model access (the reference's single-threaded
accept loop, same effective policy); with batch_size > 1 a LaneScheduler
serves requests CONCURRENTLY over the engine's batch lanes — per-lane
parked prefill admits new requests while other conversations stream, a
capability the reference does not have. On the lane path, prompt-prefix
reuse is CROSS-LANE: a PagedKVManager (kv/manager.py) matches every
admission against a shared radix tree of previously served prefixes and
adopts the covering pool pages into the lane, so a system prompt fanned
out over N streams is prefilled and stored once.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from ..analysis.lockwatch import make_condition, make_lock
from ..obs.anomaly import AnomalyMonitor, build_default_rules
from ..obs.dashboard import DASHBOARD_CONTENT_TYPE, render_dashboard
from ..obs.device import compare_with_analytic, sample_device_memory
from ..obs.metrics import DEFAULT_TOKEN_BUCKETS_S, get_registry
from ..obs.recorder import get_recorder
from ..obs.slo import SloTracker, resolve_slo_knobs
from ..obs.spans import get_span_tracker, set_thread_replica
from ..obs.timeseries import (
    MetricsSampler,
    SeriesStore,
    resolve_series_knobs,
)
from ..obs.trace import NULL_SPAN, Tracer
from ..obs.watchdog import EngineWatchdog, resolve_watchdog_knobs
from ..tokenizer import (
    CHAT_TEMPLATE_NAMES,
    ChatItem,
    ChatTemplateGenerator,
    ChatTemplateType,
    EosDetector,
    EosResult,
    Tokenizer,
)
from .admission import (
    LoadPredictor,
    OccupancySnapshot,
    effective_deadline_ms,
    resolve_admission_knobs,
    resolve_deadline_knobs,
)
from .engine import InferenceEngine
from .faults import get_fault_plane, set_fault_plane
from .spec import (
    DEFAULT_SPEC_K,
    SOURCE_DRAFT,
    NgramDrafter,
    SharedNgramStore,
    bucket_for,
    resolve_draft_model,
    resolve_spec_knobs,
    spec_buckets,
)


@dataclass
class ChatMessage:
    role: str
    content: str


@dataclass
class NaiveCacheItem:
    end_pos: int
    message: ChatMessage


class NaiveCache:
    """Prompt-prefix KV reuse (reference: src/dllama-api.cpp:298-343)."""

    def __init__(self):
        self.items: list[NaiveCacheItem] = []

    def push(self, item: NaiveCacheItem) -> None:
        self.items.append(item)

    def clear(self) -> None:
        self.items = []

    def _matches_all(self, messages: list[ChatMessage]) -> bool:
        """True when `messages` strictly extends the cached conversation
        (single source of the match rule for probe and resolve)."""
        n = len(self.items)
        if n == 0 or len(messages) <= n:
            return False
        return all(
            self.items[i].message.role == messages[i].role
            and self.items[i].message.content == messages[i].content
            for i in range(n)
        )

    def probe(self, messages: list[ChatMessage]) -> int:
        """Start position a resolve would reuse, WITHOUT mutating — the
        lane scheduler peeks at every free lane's cache to route a
        continuing conversation back to its lane."""
        if self._matches_all(messages):
            return self.items[-1].end_pos
        return 0

    def resolve_delta_prompt(
        self, messages: list[ChatMessage]
    ) -> tuple[list[ChatMessage], int]:
        """If `messages` extends the cached conversation, return only the new
        suffix plus the cache's end position; else reset."""
        if not self.items:
            return messages, 0
        if self._matches_all(messages):
            n = len(self.items)
            start_pos = self.items[-1].end_pos
            print(f"🐤 Found naive cache for {n} messages, pos={start_pos}")
            return messages[n:], start_pos
        self.clear()
        return messages, 0


@dataclass
class InferenceParams:
    messages: list[ChatMessage] = field(default_factory=list)
    temperature: float = 0.8
    top_p: float = 0.9
    seed: int | None = None
    stream: bool = False
    max_tokens: int = -1
    stop: list[str] = field(default_factory=list)
    # admission priority class for load shedding (docs/resilience.md):
    # under queue pressure or a degraded engine, "low" sheds first,
    # "high" last — the reason-tagged 429/503 + Retry-After path
    priority: str = "normal"
    # fleet failover (docs/fleet.md): a raw token history replaces the
    # chat template + tokenizer entirely — the router re-issues a dead
    # replica's stream with prompt + already-emitted tokens, and the
    # recovery-admission path (radix re-match + chunked re-prefill)
    # continues it byte-identically (greedy). Lane scheduler only.
    resume_tokens: list[int] | None = None
    # attribute each SSE delta with the exact generated token ids and
    # their raw decoded piece text (dllama_tokens / dllama_piece chunk
    # fields) so a router can reconstruct the token history mid-stream
    include_tokens: bool = False
    # fleet trace propagation (ISSUE 19): the router mints a trace id +
    # request id per client request and forwards them as x-dllama-trace /
    # x-dllama-request on every relay INCLUDING failover re-issues;
    # admission adopts them so replica spans, recorder events and trace
    # JSONL carry fleet-level identity. None outside a fleet.
    trace_id: str | None = None
    request_id: str | None = None
    # predictive admission (ISSUE 20): optional per-request latency
    # budgets. deadline_ms bounds the WHOLE completion, ttft_budget_ms
    # just the first token; either makes the request "hinted" — the
    # predictive controller may infeasible-reject it up front and EDF
    # orders it by its effective deadline. The fleet router forwards
    # x-dllama-deadline-ms so a budget survives relays and failovers.
    deadline_ms: float | None = None
    ttft_budget_ms: float | None = None

    @property
    def deadline_hinted(self) -> bool:
        return self.deadline_ms is not None or self.ttft_budget_ms is not None


class LaneJob:
    """One admitted request: the scheduler thread produces events, the
    HTTP handler thread consumes them. Events: ("delta", str),
    ("done", finish_reason), ("error", message). The handler sets
    `cancelled` when the client disconnects; the scheduler then frees the
    lane instead of decoding to max_pos for nobody."""

    def __init__(self, params: InferenceParams):
        self.params = params
        self.events: queue.Queue = queue.Queue()
        self.n_prompt_tokens = 0
        self.n_completion = 0
        self.buffer = ""
        self.cancelled = False
        # lifecycle span (obs/trace.py): submit() swaps in a live one; the
        # scheduler marks admit/first-token/finish, the handler reads the
        # derived metadata for the response
        self.span = NULL_SPAN
        # timeline queue span (obs/spans.py): begun at submit on the
        # handler thread, ended by the scheduler when admission starts
        self.queue_span = None
        # predictive admission (ISSUE 20): the EDF sort key (set at
        # submit from the deadline hints / priority offsets) and the
        # forecast recorded at admission start for error tracking —
        # _finish compares it against the observed TTFT/TPOT and folds
        # the ratio back into the LoadPredictor's EWMA correction
        self.edf_deadline_ms: float | None = None
        self.submit_t: float | None = None
        self.predicted_ttft_ms: float | None = None
        self.predicted_tpot_ms: float | None = None


@dataclass
class _LaneState:
    job: LaneJob
    pos: int
    token: int
    max_pos: int
    detector: EosDetector
    decoder: object  # tokenizer StreamDecoder
    temperature: float
    top_p: float
    seed: int | None = None  # per-lane sampled-stream reproducibility
    # every token FED to the engine so far (prompt + generated, in feed
    # order). KV rows [0, pos) hold exactly history[:pos]; the final entry
    # is the pending token whose row is written by the next decode step.
    # _finish publishes history[:pos] into the shared page pool.
    history: list = field(default_factory=list)
    # include_tokens attribution: (token id, raw piece text) consumed
    # since the last flushed delta. The EOS detector's holdback means a
    # flushed delta's TEXT can lag the consumed tokens; the tape carries
    # the exact ids + piece text so each delta event reports both, and a
    # fleet router can rebuild the full token history at any flush point
    tape: list = field(default_factory=list)
    # timeline span covering the lane's whole decode stretch (admission
    # done -> finish); the request-attributed backbone of the timeline
    decode_span: object = None
    # warm-start carry (runtime/spec.py): a park/recovery stashes the
    # lane's NgramDrafter here so the resume reinstalls it — learned
    # AIMD k, private n-gram index, and shared-store publish cursor all
    # survive instead of paying a cold-start acceptance dip
    drafter: object = None


@dataclass
class _AdmittingLane:
    """A request mid-admission: its prompt prefills one bounded chunk per
    scheduler tick (interleaved with decode blocks for the active lanes)
    instead of one monolithic prefill_lane call that freezes every other
    stream for the whole prompt. Everything the old _admit computed before
    touching the engine lives here, held across loop iterations until the
    last fill token lands and the lane flips to a _LaneState."""

    job: LaneJob
    tokens: list[int]  # full conversation prompt, pending token included
    pos0: int
    cursor: int  # fill tokens already in the lane's cache (adopted rows
    # count: the chunked prefill starts at the radix-match point)
    prompt_end: int
    max_pos: int
    public_prompt: str
    start_pos: int  # reused (adopted) prefix length, 0 = fresh prefill
    adopt_pages: list = field(default_factory=list)  # pool pages to copy in
    adopted: bool = False  # the adopt dispatch ran (it is its own tick)
    n_chunks: int = 0
    prefill_s: float = 0.0  # chunk dispatch time only, decode excluded
    # crash recovery (PR 12): when set, this admission is a poisoned
    # lane's resume — `tokens` is the lane's full fed history and
    # _finish_admission reinstalls this preserved _LaneState (decoder,
    # detector, counts) instead of building a fresh one, so the client's
    # stream continues byte-identically after the re-prefill
    resume_state: "_LaneState | None" = None
    # oversubscription (PR 16): this admission resumes a PARKED stream —
    # same reinstall contract as a crash-recovery resume, but the park
    # was voluntary (scheduler made room for a queued request), so it
    # gets its own recorder event + metrics instead of "lane_recovered"
    from_park: bool = False


def _env_int(name: str, default: int) -> int:
    import os

    v = os.environ.get(name, "")
    return int(v) if v else default


def resolve_lane_knobs(
    lane_block_size: int | None = None, admission_chunk: int | None = None
) -> tuple[int, int]:
    """Scheduler knob resolution: explicit value (CLI flag) beats the env
    override (DLLAMA_LANE_BLOCK / DLLAMA_ADMISSION_CHUNK) beats the
    default (block 8; admission chunk 0 = auto, the engine's largest
    prefill bucket)."""
    if lane_block_size is None:
        lane_block_size = _env_int("DLLAMA_LANE_BLOCK", 8)
    if admission_chunk is None:
        admission_chunk = _env_int("DLLAMA_ADMISSION_CHUNK", 0)
    return int(lane_block_size), int(admission_chunk)


def resolve_kv_knobs(
    kv_page_size: int | None = None,
    kv_pool_pages: int | None = None,
    kv_native: bool | None = None,
) -> tuple[int, int, bool]:
    """Paged-KV knob resolution, same precedence as the lane knobs:
    explicit (CLI flag) beats env (DLLAMA_KV_PAGE_SIZE /
    DLLAMA_KV_POOL_PAGES / DLLAMA_KV_NATIVE) beats default. page_size
    0 = the manager's default (16); page_size < 0 DISABLES the paged
    pool (the lane path then has no prefix reuse at all — the
    sharing-off baseline the serving bench compares against).
    pool_pages 0 = auto-size from the engine (2 * seq_len/page_size + 1
    slab mode; one pool per lane + headroom in native mode). kv_native
    1 = pool-native paged decode: lanes read/write KV through a page
    table straight into the shared pool, adopt is a refcount bump and
    publish an ownership transfer (zero device copies on page-aligned
    prefixes)."""
    if kv_page_size is None:
        kv_page_size = _env_int("DLLAMA_KV_PAGE_SIZE", 0)
    if kv_pool_pages is None:
        kv_pool_pages = _env_int("DLLAMA_KV_POOL_PAGES", 0)
    if kv_native is None:
        kv_native = bool(_env_int("DLLAMA_KV_NATIVE", 0))
    return int(kv_page_size), int(kv_pool_pages), bool(kv_native)


def resolve_stream_knobs(max_streams: int | None = None) -> int:
    """Oversubscription knob, same precedence chain: explicit
    (--max-streams) beats env (DLLAMA_MAX_STREAMS) beats default 0 =
    off (streams cap at the lane count, the pre-PR16 behavior). A value
    above the lane count lets the scheduler admit that many concurrent
    streams, PARKING active lanes (publish whole pages + drop the page
    list, radix entry kept) to make room, and resuming parked streams
    through the recovery-admission path with near-zero re-prefill."""
    if max_streams is None:
        max_streams = _env_int("DLLAMA_MAX_STREAMS", 0)
    return int(max_streams)


def resolve_resilience_knobs(
    retry_max: int | None = None,
    retry_backoff_ms: int | None = None,
    max_queue_depth: int | None = None,
) -> tuple[int, int, int]:
    """Retry/shed knob resolution, same precedence as the lane knobs:
    explicit (CLI flag) beats env (DLLAMA_RETRY_MAX /
    DLLAMA_RETRY_BACKOFF_MS / DLLAMA_MAX_QUEUE_DEPTH) beats default.
    retry_max is attempts AFTER the first failure (0 disables retries);
    max_queue_depth 0 disables queue-depth shedding (unbounded queue,
    the pre-PR12 behavior)."""
    if retry_max is None:
        retry_max = _env_int("DLLAMA_RETRY_MAX", 3)
    if retry_backoff_ms is None:
        retry_backoff_ms = _env_int("DLLAMA_RETRY_BACKOFF_MS", 5)
    if max_queue_depth is None:
        max_queue_depth = _env_int("DLLAMA_MAX_QUEUE_DEPTH", 0)
    return int(retry_max), int(retry_backoff_ms), int(max_queue_depth)


class LaneScheduler:
    """Continuous-batching loop over the engine's batch lanes.

    A central thread owns ALL engine calls: it admits pending requests
    into free lanes (per-lane parked prefill keeps the other lanes'
    caches intact) and steps every active lane together in shared decode
    blocks, each lane at its own position with its own sampling settings.
    This is the concurrency surface the reference's single-threaded
    accept loop (src/dllama-api.cpp:563-574) lacks entirely: N clients
    stream simultaneously at roughly the single-stream decode rate.

    Prompt-prefix reuse is CROSS-LANE and shared (PR6, replacing the
    per-lane NaiveCaches): every admission retokenizes the full
    conversation and matches it against the PagedKVManager's radix tree
    of previously served token prefixes. Matched pool pages are adopted
    (device-copied) into the lane and only the unmatched suffix runs
    through the chunked prefill; on finish, the lane's fed history is
    published back into the pool, deduplicated against the tree so a
    prefix N streams share is physically stored once. Any free lane can
    serve any conversation — affinity routing is gone because the prefix
    store is no longer trapped in lane-local KV.
    """

    def __init__(
        self,
        state: "ApiState",
        block_size: int = 8,
        admission_chunk: int | None = None,
        speculation: str = "off",
        spec_k: int = DEFAULT_SPEC_K,
        max_streams: int = 0,
    ):
        self.state = state
        self.engine = state.engine
        self.block_size = max(1, int(block_size))
        # oversubscription (PR 16): admit up to max_streams concurrent
        # streams over batch_size lanes by parking/resuming (0 = off).
        # Parking needs the shared pool to hold the parked KV, so the
        # knob is inert when kv sharing is disabled.
        self.max_streams = max(0, int(max_streams))
        # tokens generated since the lane was last (re)admitted: a park
        # victim must have decoded at least one full block since, so a
        # pathological queue can't thrash park/resume without progress
        self._progress: list[int] = [0] * state.engine.batch_size
        self._n_parked = 0
        # speculation mode ladder (runtime/spec.py): greedy lanes draft
        # from their own context — plus, cumulatively, every sibling's
        # published continuation ("shared") and a resident draft model
        # ("draft") — and verify k tokens per dispatch; "off" is a pure
        # bypass (no drafters, no store, no verify/draft programs)
        self.spec_mode = speculation
        self.spec_on = speculation != "off"
        # verify rows are 1 + k wide and parked lanes write them into
        # the padding rows, so k is capped by the lane padding
        self.spec_k = max(1, min(int(spec_k), self.engine._lane_pad - 1))
        self.spec_buckets = spec_buckets(self.spec_k)
        self.drafters: dict[int, NgramDrafter] = {}
        # cross-lane shared n-gram store, keyed by radix anchors: only
        # meaningful with the KV manager on (no manager -> no anchors ->
        # drafters degrade to private-ngram behavior, store stays empty)
        self.spec_store = (
            SharedNgramStore()
            if speculation in ("shared", "draft")
            else None
        )
        # resident-draft-model catch-up cursors: rows [0, _draft_pos[l])
        # of the draft cache hold lane l's verified history prefix; the
        # epoch snapshot detects a rebuilt draft cache (cursors reset)
        self._draft_pos: dict[int, int] = {}
        self._draft_epoch = getattr(state.engine, "draft_cache_epoch", 0)
        # lane -> (position, k) of this tick's draft-model propose, so
        # the verify outcome can advance the catch-up cursor past the
        # accepted rows instead of re-feeding them
        self._draft_fed: dict[int, tuple[int, int]] = {}
        # admission chunk budget: at most this many prompt tokens prefill
        # per scheduler tick (0/None = the largest prefill bucket), so the
        # worst-case inter-token gap an active stream sees is one chunk +
        # one decode block, never one full prefill
        self.admission_chunk = (
            int(admission_chunk)
            if admission_chunk
            else max(self.engine.prefill_buckets)
        )
        self.lanes: list[_LaneState | None] = [None] * self.engine.batch_size
        # shared paged-KV pool + radix prefix tree (None = sharing off)
        self.kv = state.kv_manager
        # admission counter per lane: fresh admissions prefer the
        # least-recently-used free lane (keeps a rough spread for the
        # flight recorder; no KV state rides on the choice anymore)
        self.lane_used: list[int] = [0] * self.engine.batch_size
        self._admission_count = 0
        # lanes mid-admission (resumable chunked prefill state machine)
        self.admitting: dict[int, _AdmittingLane] = {}
        self._rr = -1  # round-robin cursor over concurrently admitting lanes
        # injectable clock for the stall/prefill accounting (fake-clock
        # scheduler tests replace it; production uses the monotonic timer)
        self._clock = time.perf_counter
        self._last_decode_end: float | None = None
        # transient-dispatch retry policy (resolve_resilience_knobs):
        # attempts after the first failure, exponential backoff base.
        # _sleep is injectable so chaos tests don't pay real backoff.
        self.retry_max = int(getattr(state, "retry_max", 3))
        self.retry_backoff_s = (
            int(getattr(state, "retry_backoff_ms", 5)) / 1000.0
        )
        self._sleep = time.sleep
        self.pending: list[LaneJob] = []
        self.cv = make_condition("sched.cv")
        self._stop = False
        # build the admission-path programs (every prefill bucket + the
        # decode block + the speculative verify buckets) off-thread NOW,
        # so the first admission under load doesn't pay a synchronous
        # compile stall
        self.engine.rehearse_admission(
            self.block_size, spec_k=self.spec_k if self.spec_on else 0
        )
        self.thread = threading.Thread(
            target=self._loop, daemon=True, name="dllama-scheduler"
        )
        self.thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the scheduler thread (idempotent; used by server close and
        by tests that churn many servers in one process)."""
        with self.cv:
            self._stop = True
            self.cv.notify_all()
        if self.thread.is_alive():
            self.thread.join(timeout=timeout)

    def submit(self, params: InferenceParams) -> LaneJob:
        job = LaneJob(params)
        # EDF sort key (ISSUE 20): hints win; otherwise the priority
        # ladder becomes deadline offsets, so with no hints the pick
        # order is (priority class, arrival) — the PR 12 contract
        job.submit_t = self._clock()
        job.edf_deadline_ms = effective_deadline_ms(
            job.submit_t * 1000.0,
            priority=params.priority,
            deadline_ms=params.deadline_ms,
            ttft_budget_ms=params.ttft_budget_ms,
            default_ms=self.state.deadline_default_ms,
            priority_step_ms=self.state.deadline_priority_step_ms,
        )
        # adopt router-propagated identity when present: the span's
        # request id (and thus every timeline span keyed on it) is the
        # FLEET request id, so a failover's two half-timelines share it
        job.span = self.state.tracer.span(
            request_id=params.request_id, path="lanes",
            trace_id=params.trace_id,
        )
        # queue span: begins here on the handler thread, ends on the
        # scheduler thread once admission work (tokenize + radix match)
        # is done — so timeline "queue" covers wait AND admission setup
        job.queue_span = self.state.spans.begin(
            "queue", component="scheduler", request_id=job.span.request_id
        )
        with self.cv:
            self.pending.append(job)
            self.state.m_queue_depth.set(len(self.pending))
            self.cv.notify()
        return job

    def _set_lane_gauge(self) -> None:
        self.state.m_lanes_active.set(
            sum(1 for ls in self.lanes if ls is not None)
        )

    def occupancy(self) -> OccupancySnapshot:
        """Dynamic load snapshot for the LoadPredictor: the engine's
        occupancy() contributes the static shape, this overlays active
        lanes / admitting chunks / parked streams / queue depth. Takes
        the scheduler cv briefly so the queue-depth read is consistent
        with the lane fields; callable from any thread (the scheduler
        itself only calls it outside its cv block)."""
        chunk = max(1, self.admission_chunk)
        with self.cv:
            active = sum(1 for ls in self.lanes if ls is not None)
            admitting = list(self.admitting.values())
            queue_depth = len(self.pending)
            parked = self._n_parked
        chunks_left = 0
        for adm in admitting:
            todo = max(0, adm.prompt_end - adm.cursor)
            chunks_left += max(1, -(-todo // chunk))
        return OccupancySnapshot(
            lanes_total=len(self.lanes),
            active_lanes=active,
            parked=parked,
            admitting=len(admitting),
            admitting_chunks=chunks_left,
            queue_depth=queue_depth,
            block_size=self.block_size,
            admission_chunk=chunk,
        )

    # -- failure classification + recovery (PR 12) -------------------------

    def _retry_dispatch(self, what: str, fn):
        """Bounded exponential-backoff retry for engine dispatches whose
        failure left the donated buffers intact: the cache epoch did not
        move, so the guard never fired, lane KV is exactly as it was
        before the call, and re-issuing the dispatch is safe and
        idempotent. A failure that DID move the epoch re-raises
        immediately — retrying against the rebuilt (zeroed) cache would
        decode garbage; the caller's recovery path owns that class."""
        attempt = 0
        while True:
            epoch = self.engine.cache_epoch
            try:
                return fn()
            except Exception as e:
                if (
                    self.engine.cache_epoch != epoch
                    or attempt >= self.retry_max
                ):
                    raise
                attempt += 1
                self.state.m_dispatch_retries.inc()
                self.state.recorder.record(
                    "dispatch_retry", step=what, attempt=attempt,
                    error=str(e), error_type=type(e).__name__,
                )
                self._sleep(self.retry_backoff_s * (2 ** (attempt - 1)))

    def _fail_active(self, lane: int, err: dict) -> None:
        """Error out one ACTIVE lane's request with a structured payload
        and free the lane (no publish: its slab KV is not trustworthy on
        any path that reaches here)."""
        ls = self.lanes[lane]
        self.state.spans.end(ls.decode_span, error=err["message"])
        ls.job.events.put(("error", err))
        if ls.job.span.finish(
            "error", n_completion=ls.job.n_completion
        ) is not None:
            self.state.m_finished.labels(reason="error").inc()
        self.lanes[lane] = None
        self.drafters.pop(lane, None)
        self._draft_pos.pop(lane, None)
        if self.kv is not None:
            self.kv.release_lane(lane)

    def _fail_admitting(self, lane: int, err: dict) -> None:
        """Error out one MID-ADMISSION request with a structured payload,
        releasing its adopted-page retains (satellite-audited leak path:
        every drop route must pop self.admitting AND release the lane)."""
        adm = self.admitting.pop(lane, None)
        if adm is None:
            return
        if adm.from_park:
            # a parked stream that failed to resume is parked no more
            self._n_parked -= 1
            self.state.m_streams_parked.set(self._n_parked)
        if adm.resume_state is not None:
            # a recovery resume that failed again: the original stream's
            # decode span is still open — close it with the error
            self.state.spans.end(
                adm.resume_state.decode_span, error=err["message"]
            )
        adm.job.events.put(("error", err))
        if adm.job.span.finish(
            "error", n_completion=adm.job.n_completion
        ) is not None:
            self.state.m_finished.labels(reason="error").inc()
        self.drafters.pop(lane, None)
        self._draft_pos.pop(lane, None)
        if self.kv is not None:
            self.kv.release_lane(lane)

    def _drop_all(self, e: Exception) -> None:
        """Retries exhausted on an intact cache (or recovery itself is
        impossible): fail every in-flight request with a structured
        RETRYABLE error and keep the scheduler thread alive — the
        pre-PR 12 behavior, now with clients told to come back."""
        err = {"message": str(e), "retryable": True}
        for lane in range(len(self.lanes)):
            if self.lanes[lane] is not None:
                self._fail_active(lane, err)
        # iterate the dict, not range(len(lanes)): an admitting lane is
        # exactly the kind of entry a lanes-indexed loop can miss
        for lane in list(self.admitting):
            self._fail_admitting(lane, err)
        if self.kv is not None:
            # belt and suspenders after the per-lane releases: no retain
            # may survive a drop-all (pool pages themselves are NOT
            # donated by decode/prefill, so stored prefixes stay valid)
            self.kv.release_all_lanes()
        self.drafters.clear()
        self._draft_pos.clear()
        self._set_lane_gauge()

    def _recover(self, e: Exception, culprit: int | None) -> None:
        """A poisoning failure rebuilt the donated cache: every lane's
        slab KV is zeroed, but the shared page pool is NOT (dispatches
        never donate it), so each surviving lane's state is recoverable
        from host-side truth. Active lanes flip back to _AdmittingLane
        resumes: radix re-match their fed history against the pool
        (published prefixes adopt back in; only the unpublished suffix
        re-prefills, chunked as usual) and the preserved _LaneState is
        reinstalled on completion — the client's stream continues
        byte-identically, never seeing the fault. Mid-admission lanes
        rewind their chunk cursor to the adopted prefix (their page
        retains survived). Only ``culprit`` — the lane whose own
        admission dispatch poisoned the cache — gets a structured
        retryable error."""
        err = {"message": str(e), "retryable": True}
        native = self.kv is not None and getattr(self.kv, "native", False)
        if native:
            # pool-native lanes decode straight out of the pool; the
            # guard that moved the epoch rebuilt the POOL buffer too, so
            # every page id (lane retains, radix entries, mid-admission
            # adopt lists) points into dead memory — reset the host
            # accounting to match (reset_device=False: the dispatch
            # guard already rebuilt the buffer)
            self.kv.reset(reset_device=False)
        n_resumed = 0
        for lane in list(self.admitting):
            adm = self.admitting[lane]
            if lane == culprit:
                self._fail_admitting(lane, err)
                continue
            # the partial prefill died with the cache; the adopt copy
            # must re-run too (it targeted the old buffer)
            adm.cursor = adm.start_pos
            adm.adopted = False
            if native:
                # the adopted prefix's pages died with the pool: this
                # admission restarts from position 0 with a fresh page
                # allocation on its re-run adopt tick
                adm.cursor = 0
                adm.start_pos = 0
                adm.adopt_pages = []
        for lane in range(len(self.lanes)):
            ls = self.lanes[lane]
            if ls is None:
                continue
            if lane == culprit:
                self._fail_active(lane, err)
                continue
            if ls.job.cancelled:
                # no client to resume for; _finish("cancelled") publishes
                # nothing (the slab KV backing the history is garbage)
                self._finish(lane, "cancelled")
                continue
            self.lanes[lane] = None
            # warm-start: the drafter rides the preserved state through
            # the recovery re-admission (its index/AIMD k are host-side
            # truth the crash never touched); _finish_admission rebinds
            # it to the re-matched radix anchor and reinstalls it
            ls.drafter = self.drafters.pop(lane, None)
            self._draft_pos.pop(lane, None)
            start_pos, pages = 0, []
            if self.kv is not None:
                start_pos, pages = self.kv.match(lane, ls.history)
            self.admitting[lane] = _AdmittingLane(
                job=ls.job,
                tokens=list(ls.history),
                pos0=0,
                cursor=start_pos,
                prompt_end=len(ls.history) - 1,
                max_pos=ls.max_pos,
                public_prompt="",
                start_pos=start_pos,
                adopt_pages=pages,
                resume_state=ls,
            )
            n_resumed += 1
        self.state.recorder.record(
            "lane_recovery", error=str(e), error_type=type(e).__name__,
            culprit=culprit, n_resumed=n_resumed,
            n_admitting=len(self.admitting),
        )
        self._set_lane_gauge()
        with self.cv:
            self.cv.notify_all()

    # -- scheduler thread --------------------------------------------------

    def _loop(self) -> None:
        if self.state.replica_id is not None:
            # the scheduler thread is replica-owned for its lifetime:
            # every span it begins (admission, decode, publish, park)
            # carries the replica tag (obs/spans.py, ISSUE 19)
            set_thread_replica(self.state.replica_id)
        while True:
            with self.cv:
                while (
                    not self._stop
                    and not self.pending
                    and not any(self.lanes)
                    and not self.admitting
                ):
                    self.cv.wait()
                if self._stop:
                    return
                admissions = []
                free = [
                    i
                    for i in range(len(self.lanes))
                    if self.lanes[i] is None and i not in self.admitting
                ]
                while self.pending and free:
                    # EDF pick (ISSUE 20, predictive mode): earliest
                    # effective deadline first, queue order breaking
                    # ties — priorityless no-hint traffic degenerates to
                    # FIFO. Predictive off keeps the PR 12 pop(0).
                    # Objects without an edf key (tests inject opaque
                    # queue fillers) sort last instead of crashing.
                    idx = 0
                    if self.state.admission_predict:
                        idx = min(
                            range(len(self.pending)),
                            key=lambda i: (
                                getattr(
                                    self.pending[i], "edf_deadline_ms",
                                    None,
                                )
                                if getattr(
                                    self.pending[i], "edf_deadline_ms",
                                    None,
                                ) is not None
                                else float("inf"),
                                i,
                            ),
                        )
                    job = self.pending.pop(idx)
                    # any lane serves any conversation (the prefix store is
                    # the shared pool, not lane KV): take the
                    # least-recently-used free lane
                    lane = min(free, key=lambda i: self.lane_used[i])
                    free.remove(lane)
                    self._admission_count += 1
                    self.lane_used[lane] = self._admission_count
                    admissions.append((lane, job))
                n_pending = len(self.pending)
                self.state.m_queue_depth.set(n_pending)
            # liveness heartbeat: the watchdog's scheduler-stalled rule
            # audits the gap between these
            wd = self.state.watchdog
            if wd is not None:
                wd.beat(
                    n_active=sum(1 for ls in self.lanes if ls is not None),
                    n_admitting=len(self.admitting),
                )
            tick_sp = self.state.spans.begin(
                "sched_tick", component="scheduler",
                n_pending=n_pending, n_admitting=len(self.admitting),
            )
            for lane, job in admissions:
                self._begin_admission(lane, job)
            # oversubscription (PR 16): requests queued while every lane
            # is busy and --max-streams allows more concurrency — park
            # the most-progressed lane (publish + drop page list); it
            # frees this tick and the queued request admits next tick
            self._maybe_park(n_pending)
            # deadline preemption (ISSUE 20): park an over-budget /
            # deadline-blown lower-priority stream when that flips a
            # feasible hinted request from "blows its budget waiting"
            # to "meets SLO" — reuses the PR 16 park/resume contract,
            # so the victim's stream stays byte-identical on resume
            self._maybe_preempt(n_pending)
            # stall-free admission: at most ONE bounded prefill chunk per
            # tick, then a decode block for every active lane — the worst
            # case inter-token gap is one chunk + one block, and two
            # pending jobs can never prefill back-to-back while another
            # lane is mid-stream
            self._admission_tick()
            if any(self.lanes):
                epoch0 = self.engine.cache_epoch
                try:
                    self._step_block()
                except Exception as e:
                    # the scheduler thread must survive any engine error
                    # (the reference's crash-retry loop plays this role
                    # for its single stream, dllama-api.cpp:616-628).
                    # _retry_dispatch already absorbed transient failures;
                    # what reaches here is classified by the cache epoch:
                    # moved => the dispatch guard rebuilt the donated
                    # cache (every lane's slab KV is gone) and the lanes
                    # RESUME from the shared page pool; unchanged =>
                    # retries exhausted on an intact cache — fail the
                    # in-flight requests with a structured retryable
                    # error and keep serving.
                    import logging

                    poisoned = self.engine.cache_epoch != epoch0
                    logging.getLogger(__name__).exception(
                        "lane scheduler step failed (%s); %s",
                        "cache poisoned" if poisoned else "cache intact",
                        "recovering lanes" if poisoned
                        else "dropping in-flight lanes",
                    )
                    self.state.m_sched_errors.inc()
                    self.state.recorder.record(
                        "scheduler_error",
                        error=str(e),
                        error_type=type(e).__name__,
                        poisoned=poisoned,
                        n_lanes=sum(
                            1 for ls in self.lanes if ls is not None
                        ),
                    )
                    # black-box dump: the ring holds the dispatches that
                    # led here (written only when a postmortem dir is set)
                    self.state.recorder.postmortem("scheduler-loop", e)
                    if poisoned:
                        # batched dispatch: no single lane is culpable, so
                        # every lane resumes (none of them caused it)
                        self._recover(e, culprit=None)
                    else:
                        self._drop_all(e)
                    with self.cv:
                        self.cv.notify_all()
            self.state.spans.end(tick_sp)
            if not any(self.lanes):
                # decode went idle: the next dispatch starts a new stall
                # window, don't charge it for the quiet period
                self._last_decode_end = None

    # -- oversubscription: park / resume (PR 16) ---------------------------

    def _maybe_park(self, n_pending: int) -> None:
        """Park ONE active lane when requests wait, no lane is free, and
        the stream cap (--max-streams > lanes) says the queue pressure
        is oversubscription, not overload. The victim is the lane that
        decoded the most tokens since its last (re)admission, and it
        must have at least one full block of progress — so a deep queue
        rotates lanes round-robin instead of thrashing park/resume.
        ``n_pending`` is the tick's queue-depth snapshot (taken under
        the cv in _loop)."""
        if (
            self.max_streams <= len(self.lanes)
            or self.kv is None
            or n_pending <= 0
            or self.admitting
        ):
            return
        if any(
            self.lanes[i] is None and i not in self.admitting
            for i in range(len(self.lanes))
        ):
            return
        victim, best = -1, self.block_size - 1
        for lane, ls in enumerate(self.lanes):
            if ls is None or ls.job.cancelled:
                continue
            if self._progress[lane] > best:
                victim, best = lane, self._progress[lane]
        if victim >= 0:
            self._park_stream(victim)

    def _maybe_preempt(self, n_pending: int) -> None:
        """Deadline preemption (ISSUE 20, predictive mode only): when
        the EDF head is a HINTED request that blows its budget if it
        waits for natural lane turnover, but would meet it on a lane
        freed right now, park ONE active lower-priority (or already
        deadline-blown) stream through the PR 16 contract. The victim
        requeues with its later effective deadline, so EDF resumes it
        after the deadline traffic — paused, never restarted, its
        token stream byte-identical. Preemption never fires when the
        head is infeasible either way: burning a victim cannot save
        it."""
        st = self.state
        if (
            not st.admission_predict
            or st.predictor is None
            or self.kv is None
            or n_pending <= 0
            or self.admitting
        ):
            return
        if any(
            self.lanes[i] is None and i not in self.admitting
            for i in range(len(self.lanes))
        ):
            return
        head, head_key = None, None
        with self.cv:
            pending = list(self.pending)
        for j in pending:
            key = getattr(j, "edf_deadline_ms", None)
            if key is None:
                continue
            if head_key is None or key < head_key:
                head, head_key = j, key
        if head is None or not head.params.deadline_hinted:
            return
        now_ms = self._clock() * 1000.0
        remaining_ms = head_key - now_ms
        if remaining_ms <= 0:
            return
        n_tok = head.n_prompt_tokens or st.estimate_prompt_tokens(
            head.params
        )
        occ = self.occupancy()
        wait_pred = st.predictor.predict(n_tok, occ)
        if wait_pred.ttft_ms <= remaining_ms:
            return  # feasible by waiting — no victim needed
        # forecast against a freed lane: zero queue wait, admission
        # starts next tick
        occ_freed = self.occupancy()
        occ_freed.queue_depth = 0
        occ_freed.active_lanes = max(0, occ_freed.active_lanes - 1)
        now_pred = st.predictor.predict(n_tok, occ_freed)
        if now_pred.ttft_ms > remaining_ms:
            return  # infeasible either way
        prio_rank = {"low": 0, "normal": 1, "high": 2}
        head_rank = prio_rank.get(head.params.priority, 1)
        victim, v_score, v_blown = -1, None, False
        for lane, ls in enumerate(self.lanes):
            if ls is None or ls.job.cancelled:
                continue
            # same no-thrash floor as _maybe_park: at least one full
            # block of progress since (re)admission
            if self._progress[lane] <= self.block_size - 1:
                continue
            r = prio_rank.get(ls.job.params.priority, 1)
            vkey = getattr(ls.job, "edf_deadline_ms", None)
            vkey = vkey if vkey is not None else float("inf")
            blown = vkey < now_ms
            if r >= head_rank and not blown:
                continue  # only lower-priority or deadline-blown streams
            score = (r, -vkey)
            if v_score is None or score < v_score:
                victim, v_score, v_blown = lane, score, blown
        if victim < 0:
            return
        reason = "deadline_blown" if v_blown else "priority"
        rid = self.lanes[victim].job.span.request_id
        self._park_stream(victim)
        st.m_preemptions.labels(reason=reason).inc()
        st.recorder.record(
            "stream_preempt", lane=victim, reason=reason,
            victim_request=rid,
            head_request=head.span.request_id,
            head_remaining_ms=round(remaining_ms, 3),
            predicted_ttft_ms=round(now_pred.ttft_ms, 3),
        )

    def _park_stream(self, lane: int) -> None:
        """Evict an active stream from its lane to make room for a
        queued request: publish the fed history's whole pages into the
        shared pool (so the resume re-matches nearly everything), drop
        the lane's page list (radix entry kept), and requeue the job
        carrying its preserved _LaneState — exactly the
        recovery-admission contract (_AdmittingLane resume_state=),
        minus the crash. The decode span stays open: the client's
        stream pauses but never observably restarts."""
        ls = self.lanes[lane]
        st = self.state
        rid = ls.job.span.request_id
        with st.spans.span(
            "park", component="scheduler", request_id=rid, lane=lane,
            pos=ls.pos,
        ):
            # publish failures self-narrow inside the manager (the
            # culprit pages release, survivors stay); a 0-token store
            # just means the resume re-prefills more
            self.kv.publish(lane, ls.history[: ls.pos])
            self.kv.release_lane(lane)
        self.lanes[lane] = None
        # warm-start (spec satellite): the drafter parks WITH the stream
        # instead of being discarded — the resume rebinds + reinstalls it
        ls.drafter = self.drafters.pop(lane, None)
        self._draft_pos.pop(lane, None)
        self._progress[lane] = 0
        ls.job._park_resume = ls
        # parked = queue-visible again: a fresh queue span covers the
        # parked wait so the timeline shows where the stream's time went
        ls.job.queue_span = st.spans.begin(
            "queue", component="scheduler", request_id=rid, parked=True
        )
        self._n_parked += 1
        st.m_streams_parked.set(self._n_parked)
        self._set_lane_gauge()
        with self.cv:
            self.pending.append(ls.job)
            n_pending = len(self.pending)
            st.m_queue_depth.set(n_pending)
            self.cv.notify()
        st.recorder.record(
            "stream_park", lane=lane, pos=ls.pos,
            n_pending=n_pending, n_parked=self._n_parked,
        )

    def _begin_admission(self, lane: int, job: LaneJob) -> None:
        """Resolve the prompt and park it as an _AdmittingLane — the front
        half of the old monolithic _admit, with NO engine work: the adopt
        copy and the prefill chunks run one per tick in _admission_tick.
        Validation failures here precede any engine call.

        The FULL conversation is retokenized every time and matched
        against the shared radix tree: a continuing conversation reuses
        its stored prefix from ANY lane (the template renders
        prefix-stable transcripts, so turn N's rendering begins with turn
        N-1's), and so does an unrelated request that shares a system
        prompt. The match is token-granular; the chunked prefill then
        covers only positions [start_pos, prompt_end)."""
        state, tok = self.state, self.state.tokenizer
        p = job.params
        ls0 = getattr(job, "_park_resume", None)
        if ls0 is not None:
            # parked-stream resume: no retokenize (the preserved state's
            # history IS the fed token stream) — radix re-match, chunked
            # re-prefill of whatever wasn't published, then
            # _finish_admission reinstalls the state untouched
            self._resume_parked(lane, job, ls0)
            return
        try:
            if p.resume_tokens is not None:
                # fleet mid-stream failover (docs/fleet.md): the router
                # replays a dead sibling's fed history (prompt +
                # already-emitted tokens) as raw ids — no template, no
                # tokenizer. The radix match + chunked prefill below
                # treat it like any other prompt, so a shared prefix
                # adopts from the pool and the stream continues
                # byte-identically (greedy) from tokens[-1].
                if len(p.resume_tokens) < 2:
                    raise ValueError(
                        "resume_tokens needs at least 2 token ids"
                    )
                tokens = [int(t) for t in p.resume_tokens]
                public_prompt = ""
            else:
                items = [ChatItem(m.role, m.content) for m in p.messages]
                prompt = state.template.generate(
                    items, append_generation_prompt=True
                )
                tokens = tok.encode(
                    prompt.content, is_start=True, add_special_tokens=True
                )
                public_prompt = prompt.public_prompt or ""
            start_pos, adopt_pages = 0, []
            if self.kv is not None:
                # match retains the pages for this lane immediately —
                # the adopt copy runs a tick later and unpinned pages
                # could be evicted/reallocated in between
                start_pos, adopt_pages = self.kv.match(lane, tokens)
            if start_pos > 0:
                state.m_prefix_hits.inc()
                state.m_reused_tokens.inc(start_pos)
                self.kv.note_hit(start_pos)
            else:
                state.m_prefix_misses.inc()
            qw = job.span.mark_admitted(
                lane=lane, reused_prefix_tokens=start_pos
            )
            # the queue span absorbs tokenize+match above, so per-request
            # timeline coverage only misses inter-tick bookkeeping
            state.spans.end(
                job.queue_span, lane=lane, n_prompt=len(tokens),
                reused_prefix_tokens=start_pos,
            )
            state.m_queue_wait.observe(qw)
            state.m_admissions.inc()
            # admission-time forecast (ISSUE 20): the queue wait is now
            # known exactly and the radix match says how much prefill
            # is skipped — record the prediction _finish scores against
            # the observed TTFT/TPOT to self-calibrate the predictor
            if state.predictor is not None and qw is not None:
                fc = state.predictor.predict(
                    len(tokens), self.occupancy(),
                    matched_tokens=start_pos,
                )
                job.predicted_ttft_ms = (
                    qw * 1000.0 + fc.ttft_ms - fc.queue_wait_ms
                )
                job.predicted_tpot_ms = fc.tpot_ms
                state.m_predicted_ttft.observe(job.predicted_ttft_ms)
            seq_len = self.engine.header.seq_len
            prompt_end = len(tokens) - 1
            if prompt_end >= seq_len:
                raise ValueError(
                    f"prompt of {len(tokens)} tokens exceeds "
                    f"seqLen {seq_len}"
                )
            max_pos = (
                min(prompt_end + p.max_tokens, seq_len)
                if p.max_tokens > 0
                else seq_len
            )
            job.n_prompt_tokens = len(tokens)
            self.admitting[lane] = _AdmittingLane(
                job=job,
                tokens=tokens,
                pos0=0,
                cursor=start_pos,
                prompt_end=prompt_end,
                max_pos=max_pos,
                public_prompt=public_prompt,
                start_pos=start_pos,
                adopt_pages=adopt_pages,
            )
        except Exception as e:
            state.spans.end(job.queue_span, error=str(e))
            # validation failures (bad template, prompt too long) are the
            # client's to fix, not to retry — retryable stays False
            job.events.put(
                ("error", {"message": str(e), "retryable": False})
            )
            if job.span.finish("error") is not None:
                state.m_finished.labels(reason="error").inc()
            if self.kv is not None:
                # a validation failure after the match (e.g. prompt too
                # long) must drop the pages match() just retained
                self.kv.release_lane(lane)

    def _resume_parked(
        self, lane: int, job: LaneJob, ls: "_LaneState"
    ) -> None:
        """Front half of a parked stream's re-admission: the park
        published the history's whole pages, so the radix match adopts
        them back (zero device copies in pool-native mode) and only the
        page-tail + generated suffix re-prefills."""
        state = self.state
        job._park_resume = None
        try:
            # park requires the shared pool, so self.kv is non-None here
            start_pos, adopt_pages = self.kv.match(lane, ls.history)
            if start_pos > 0:
                state.m_prefix_hits.inc()
                state.m_reused_tokens.inc(start_pos)
                self.kv.note_hit(start_pos)
            state.spans.end(
                job.queue_span, lane=lane, resumed_from_park=True,
                reused_prefix_tokens=start_pos,
            )
            self.admitting[lane] = _AdmittingLane(
                job=job,
                tokens=list(ls.history),
                pos0=0,
                cursor=start_pos,
                prompt_end=len(ls.history) - 1,
                max_pos=ls.max_pos,
                public_prompt="",
                start_pos=start_pos,
                adopt_pages=adopt_pages,
                resume_state=ls,
                from_park=True,
            )
        except Exception as e:
            state.spans.end(job.queue_span, error=str(e))
            state.spans.end(ls.decode_span, error=str(e))
            job.events.put(
                ("error", {"message": str(e), "retryable": True})
            )
            if job.span.finish(
                "error", n_completion=job.n_completion
            ) is not None:
                state.m_finished.labels(reason="error").inc()
            self._n_parked -= 1
            state.m_streams_parked.set(self._n_parked)
            self.kv.release_lane(lane)

    def _admission_tick(self) -> None:
        """Run at most ONE bounded prefill chunk for ONE admitting lane
        per scheduler tick, round-robin across concurrent admissions, and
        flip the lane into decode once its last fill token lands."""
        if not self.admitting:
            return
        order = sorted(self.admitting)
        lane = min((i for i in order if i > self._rr), default=order[0])
        self._rr = lane
        adm = self.admitting[lane]
        job = adm.job
        if job.cancelled:
            self._abort_admission(lane, "cancelled")
            return
        fills = adm.tokens[:-1]
        wd = self.state.watchdog
        rid = job.span.request_id
        epoch0 = self.engine.cache_epoch
        # pool-native mode runs the adopt tick even on a zero-token
        # match: kv.adopt() is where the lane's private pages allocate
        # and its page table installs — without it there is no KV home
        # for the prefill to write into
        adopt_needed = self.kv is not None and (
            bool(adm.adopt_pages) or getattr(self.kv, "native", False)
        )
        try:
            if adopt_needed and not adm.adopted:
                # the adopt copy is this lane's first tick action and is
                # its own tick (one bounded engine dispatch per tick, same
                # budget discipline as a prefill chunk)
                sp = self.state.spans.begin(
                    "adopt", component="scheduler", request_id=rid,
                    lane=lane, n_pages=len(adm.adopt_pages),
                )
                if wd is not None:
                    wd.dispatch_begin("kv_adopt")
                t0 = self._clock()
                try:
                    self._retry_dispatch(
                        "kv_adopt",
                        lambda: self.kv.adopt(lane, adm.adopt_pages),
                    )
                finally:
                    if wd is not None:
                        wd.dispatch_end()
                    self.state.spans.end(sp)
                adm.prefill_s += self._clock() - t0
                adm.adopted = True
            elif adm.cursor < len(fills):
                sp = self.state.spans.begin(
                    "admission_chunk", component="scheduler",
                    request_id=rid, lane=lane, pos=adm.pos0 + adm.cursor,
                )
                if wd is not None:
                    wd.dispatch_begin("prefill_lane_chunk")
                t0 = self._clock()
                try:
                    width = self._retry_dispatch(
                        "prefill_lane_chunk",
                        lambda: self.engine.prefill_lane_chunk(
                            lane,
                            fills[adm.cursor:],
                            adm.pos0 + adm.cursor,
                            budget=self.admission_chunk,
                        ),
                    )
                finally:
                    if wd is not None:
                        wd.dispatch_end()
                    self.state.spans.end(sp)
                adm.prefill_s += self._clock() - t0
                adm.cursor += width
                adm.n_chunks += 1
                self.state.m_admission_chunks.inc()
                self.state.recorder.record(
                    "admission_chunk", lane=lane, chunk=adm.n_chunks,
                    pos=adm.pos0 + adm.cursor - width, n_tokens=width,
                    done=adm.cursor >= len(fills),
                )
            if adm.cursor >= len(fills) and (
                adm.adopted or not adopt_needed
            ):
                self._finish_admission(lane, adm)
        except Exception as e:
            self.state.recorder.record(
                "admission_error", lane=lane, error=str(e),
                error_type=type(e).__name__,
                poisoned=self.engine.cache_epoch != epoch0,
            )
            if self.engine.cache_epoch != epoch0:
                # the failed adopt/chunk ran inside the engine's donated-
                # buffer guard: the WHOLE cache was rebuilt, so every
                # other lane's slab KV died with this admission — recover
                # them all, failing only this lane's request (before
                # PR 12 this path silently left active lanes decoding
                # against a zeroed cache)
                self._recover(e, culprit=lane)
            else:
                # cache intact (retries exhausted on a transient fault):
                # only this admission is affected — error the job and
                # drop its page retains (the lane's partial KV is
                # overwritten by the next admission anyway)
                self._fail_admitting(
                    lane, {"message": str(e), "retryable": True}
                )

    def _finish_admission(self, lane: int, adm: _AdmittingLane) -> None:
        """Last fill token landed: install the decode-side _LaneState.
        `seed` is honored PER LANE (r5): decode_lanes derives each lane's
        sampling keys from (its seed, its absolute positions), so a seeded
        request reproduces regardless of which other lanes are active,
        how blocks split — or how its admission was chunked."""
        state, tok = self.state, self.state.tokenizer
        job, p = adm.job, adm.job.params
        if adm.resume_state is not None:
            # crash-recovery OR park resume: the re-prefill just
            # restored KV rows [0, pos) of the preserved lane state's
            # history — reinstall that state untouched (stream decoder,
            # EOS detector, token counts all intact) and the client's
            # stream continues exactly where it paused. No prompt delta,
            # no fresh spans, no second "admit": the request never
            # observably restarted.
            self.lanes[lane] = adm.resume_state
            del self.admitting[lane]
            self._progress[lane] = 0
            self._set_lane_gauge()
            # warm-start (spec satellite): reinstall the drafter the
            # park/recovery stashed on the preserved state — learned
            # AIMD k and n-gram index intact, rebound to the re-matched
            # radix anchor. A resume without one (e.g. speculation
            # turned on between park and resume) builds fresh.
            if self.spec_on and adm.resume_state.temperature <= 0.0:
                dr = adm.resume_state.drafter
                adm.resume_state.drafter = None
                if not isinstance(dr, NgramDrafter):
                    dr = self._make_drafter(lane, adm.job.span.request_id)
                else:
                    dr.rebind(*self._lane_anchor(lane))
                self.drafters[lane] = dr
            if adm.from_park:
                self._n_parked -= 1
                state.m_streams_parked.set(self._n_parked)
                state.m_stream_resumes.inc()
                state.recorder.record(
                    "stream_resume", lane=lane, pos=adm.resume_state.pos,
                    reused_prefix_tokens=adm.start_pos,
                    n_chunks=adm.n_chunks,
                )
            else:
                state.m_lanes_recovered.inc()
                state.recorder.record(
                    "lane_recovered", lane=lane, pos=adm.resume_state.pos,
                    reused_prefix_tokens=adm.start_pos,
                    n_chunks=adm.n_chunks,
                )
            return
        job.span.set_prefill_seconds(adm.prefill_s)
        job.span.set_tokens(n_prompt=len(adm.tokens))
        state.m_prefill.observe(adm.prefill_s)
        if adm.public_prompt:
            job.buffer += adm.public_prompt
            job.events.put(("delta", adm.public_prompt))
        detector = EosDetector(
            tok.eos_token_ids,
            state.stops if not p.stop else p.stop,
            padding_left=state.max_stop_len,
            padding_right=state.max_stop_len,
        )
        self.lanes[lane] = _LaneState(
            job=job,
            pos=adm.prompt_end,
            token=adm.tokens[-1],
            max_pos=adm.max_pos,
            detector=detector,
            decoder=tok.stream_decoder(),
            temperature=p.temperature,
            top_p=p.top_p,
            seed=p.seed,
            history=list(adm.tokens),
            decode_span=state.spans.begin(
                "decode", component="scheduler",
                request_id=job.span.request_id, lane=lane,
                n_prompt=len(adm.tokens),
            ),
        )
        del self.admitting[lane]
        self._progress[lane] = 0
        if self.spec_on and p.temperature <= 0.0:
            # greedy lanes only: a sampled lane's next token is not the
            # argmax the verify pass returns, so it stays on the decode
            # block (the fallback is per-lane, not per-server)
            self.drafters[lane] = self._make_drafter(
                lane, job.span.request_id
            )
        self._set_lane_gauge()
        state.recorder.record(
            "admit", lane=lane, reused_prefix_tokens=adm.start_pos,
            n_prompt=len(adm.tokens), n_chunks=adm.n_chunks,
        )

    def _abort_admission(self, lane: int, reason: str) -> None:
        """Client went away mid-admission: stop prefilling for nobody."""
        adm = self.admitting.pop(lane)
        job = adm.job
        if adm.from_park:
            self._n_parked -= 1
            self.state.m_streams_parked.set(self._n_parked)
        if adm.resume_state is not None:
            # recovery resume cancelled mid-re-prefill: the original
            # stream's decode span is still open — close it here
            self.state.spans.end(adm.resume_state.decode_span, reason=reason)
        if job.span.finish(
            reason, n_prompt=len(adm.tokens), n_completion=job.n_completion
        ) is not None:
            self.state.m_finished.labels(reason=reason).inc()
            if reason == "cancelled":
                self.state.m_cancellations.inc()
        job.events.put(("done", reason))
        self.drafters.pop(lane, None)
        self._draft_pos.pop(lane, None)
        if self.kv is not None:
            # nothing publishable mid-admission; just drop page retains
            self.kv.release_lane(lane)
        self.state.recorder.record(
            "finish", lane=lane, reason=reason, pos=adm.pos0 + adm.cursor,
            n_completion=job.n_completion,
        )

    def _finish(self, lane: int, reason: str) -> None:
        ls = self.lanes[lane]
        rid = ls.job.span.request_id
        self.state.spans.end(
            ls.decode_span, reason=reason,
            n_completion=ls.job.n_completion,
        )
        if self.kv is not None:
            if reason in ("stop", "length"):
                # publish the fed history's whole pages into the shared
                # pool BEFORE signalling done, so a client's immediate
                # follow-up request (any lane) matches this conversation.
                # Dedup inside publish keeps shared prefixes stored once.
                with self.state.spans.span(
                    "publish", component="scheduler", request_id=rid,
                    lane=lane, n_tokens=ls.pos,
                ):
                    self.kv.publish(lane, ls.history[: ls.pos])
            # cancelled/errored streams publish nothing; either way the
            # lane's adopted-page retains are released now
            self.kv.release_lane(lane)
        if ls.job.span.finish(
            reason,
            n_prompt=ls.job.n_prompt_tokens,
            n_completion=ls.job.n_completion,
        ) is not None:
            self.state.m_finished.labels(reason=reason).inc()
            if reason == "cancelled":
                self.state.m_cancellations.inc()
        self.state.slo.observe_span(
            ls.job.span, deadline_ms=ls.job.params.deadline_ms
        )
        self._score_prediction(ls.job, reason)
        self.state.spans.maybe_flush()
        ls.job.events.put(("done", reason))
        self.state.recorder.record(
            "finish", lane=lane, reason=reason, pos=ls.pos,
            n_completion=ls.job.n_completion,
        )
        self.lanes[lane] = None
        self.drafters.pop(lane, None)
        self._draft_pos.pop(lane, None)
        self._set_lane_gauge()
        with self.cv:
            self.cv.notify()

    def _score_prediction(self, job: LaneJob, reason: str) -> None:
        """Estimated-vs-observed TTFT/TPOT for one finished request
        (ISSUE 20): the absolute error feeds the first-class error
        histogram and the EWMA correction folds the observed/predicted
        ratio back into the LoadPredictor. Only clean finishes score —
        a cancelled stream's latency says nothing about the model."""
        st = self.state
        pred = st.predictor
        if (
            pred is None
            or job.predicted_ttft_ms is None
            or reason not in ("stop", "length")
        ):
            return
        span = job.span
        ttft_s = getattr(span, "ttft_s", None)
        if ttft_s is not None and ttft_s > 0:
            obs_ms = ttft_s * 1000.0
            err_ms = abs(obs_ms - job.predicted_ttft_ms)
            st.m_predict_error.labels(signal="ttft").observe(err_ms)
            st.note_predict_error(err_ms)
            pred.observe_ttft(job.predicted_ttft_ms, obs_ms)
        total_s = getattr(span, "total_s", None)
        n = job.n_completion
        if (
            job.predicted_tpot_ms is not None
            and total_s is not None
            and ttft_s is not None
            and n > 1
        ):
            obs_tpot_ms = (total_s - ttft_s) / (n - 1) * 1000.0
            st.m_predict_error.labels(signal="tpot").observe(
                abs(obs_tpot_ms - job.predicted_tpot_ms)
            )
            pred.observe_tpot(job.predicted_tpot_ms, obs_tpot_ms)

    def _consume_token(self, lane: int, t: int) -> bool:
        """Advance one lane by one generated token — lane state, history,
        SSE delta, EOS/length detection. Returns False once the lane
        finished (callers stop feeding it; any remaining burst tokens'
        KV rows sit beyond the lane's final position and are never
        published). Shared by the decode-block row loop and the
        speculative verify path, so an accepted draft run flushes
        through EXACTLY the same per-token machinery as plain decode —
        that is what makes spec-on streams byte-identical."""
        ls = self.lanes[lane]
        if ls is None:
            return False
        self._progress[lane] += 1
        ls.pos += 1
        ls.token = t
        ls.history.append(t)
        ls.job.n_completion += 1
        if ls.job.n_completion == 1:
            ttft = ls.job.span.mark_first_token()
            if ttft is not None:
                self.state.m_ttft.observe(ttft)
        piece = ls.decoder.decode(t)
        if ls.job.params.include_tokens:
            ls.tape.append((t, piece or ""))
        eos_type = ls.detector.append(t, piece)
        if eos_type in (EosResult.NOT_EOS, EosResult.EOS):
            delta = ls.detector.get_delta()
            if delta:
                ls.job.buffer += delta
                if ls.job.params.include_tokens:
                    # attribute the flush with the exact consumed tokens:
                    # cumulative `tokens` across deltas == the generated
                    # history, cumulative `piece` == its exact text (the
                    # delta text lags by the detector's holdback)
                    ls.job.events.put(
                        (
                            "delta",
                            {
                                "text": delta,
                                "tokens": [tid for tid, _ in ls.tape],
                                "piece": "".join(px for _, px in ls.tape),
                            },
                        )
                    )
                    ls.tape = []
                else:
                    ls.job.events.put(("delta", delta))
            ls.detector.reset()
        if eos_type == EosResult.EOS:
            self._finish(lane, "stop")
            return False
        if ls.pos >= ls.max_pos:
            self._finish(lane, "length")
            return False
        return True

    def _lane_anchor(self, lane: int) -> tuple[int | None, int]:
        """The lane's current radix anchor (node_id, matched tokens) —
        the shared-store grouping key captured by the admission match —
        or (None, 0) when sharing is off / nothing matched."""
        if self.spec_store is not None and self.kv is not None:
            a = self.kv.anchor_for(lane)
            if a is not None:
                return a
        return (None, 0)

    def _make_drafter(self, lane: int, stream_id: str) -> NgramDrafter:
        anchor, aoff = self._lane_anchor(lane)
        return NgramDrafter(
            k_max=self.spec_k,
            shared_store=self.spec_store,
            stream_id=stream_id,
            anchor=anchor,
            anchor_offset=aoff,
            use_draft_model=(
                self.spec_mode == "draft" and self.engine.has_draft_model
            ),
        )

    def _spec_drafts(self) -> dict[int, list[int]]:
        """Collect this tick's draft proposals: greedy lanes whose
        drafter proposes >=1 token within the lane's remaining budget
        (both max_tokens and seq_len cap the accepted run). The source
        ladder runs per lane — private n-gram vs the shared store's
        sibling continuations, longest suffix match winning, then
        (mode draft) one batched draft-model propose over every lane
        both n-gram sources left dry."""
        out: dict[int, list[int]] = {}
        st = self.state
        seq_len = self.engine.header.seq_len
        self._draft_fed.clear()
        model_lanes: dict[int, int] = {}  # lane -> model-draft budget
        for lane, dr in self.drafters.items():
            ls = self.lanes[lane]
            if ls is None:
                continue
            dr.update(ls.history)
            # an accepted run emits up to len(draft)+1 tokens from pos
            room = min(ls.max_pos, seq_len) - ls.pos - 1
            if room < 1:
                continue
            budget = min(self.spec_k, room)
            d = dr.draft(budget=budget)
            if d:
                out[lane] = d
                if st.m_spec_source is not None and dr.last_source:
                    st.m_spec_source.labels(source=dr.last_source).inc(
                        len(d)
                    )
                continue
            mb = dr.model_budget(budget)
            if mb > 0:
                model_lanes[lane] = mb
        if model_lanes:
            for lane, d in self._draft_with_model(model_lanes).items():
                dr = self.drafters.get(lane)
                if dr is not None:
                    dr.last_source = SOURCE_DRAFT
                out[lane] = d
                if st.m_spec_source is not None:
                    st.m_spec_source.labels(source=SOURCE_DRAFT).inc(
                        len(d)
                    )
        if self.spec_store is not None and st.g_spec_store_groups is not None:
            stats = self.spec_store.stats()
            st.g_spec_store_groups.set(stats["groups"])
            st.g_spec_store_streams.set(stats["streams"])
            st.g_spec_store_tokens.set(stats["tokens"])
            st.g_spec_store_hits.set(stats["hits"])
            st.g_spec_store_misses.set(stats["misses"])
        return out

    def _draft_with_model(
        self, budgets: dict[int, int]
    ) -> dict[int, list[int]]:
        """Resident-draft-model proposals for lanes whose n-gram sources
        ran dry: per lane, catch the draft KV cache up on the verified
        history it has not seen (bucketed draft_prefill chunks), then
        ONE batched draft_step dispatch autoregresses k greedy tokens
        for every such lane. Purely advisory — any failure here skips
        model drafting for the tick (the lanes fall back to the decode
        block) and never touches the target cache."""
        eng = self.engine
        b = len(self.lanes)
        dseq = eng.draft_seq_len
        if eng.draft_cache_epoch != self._draft_epoch:
            # the draft cache was rebuilt (draft-side dispatch failure):
            # every lane's draft context is gone; cursors restart at 0
            # and the catch-up below re-derives them from host history
            self._draft_pos.clear()
            self._draft_epoch = eng.draft_cache_epoch
        k = 0
        lanes: list[int] = []
        try:
            for lane in budgets:
                ls = self.lanes[lane]
                if ls is None or ls.pos + budgets[lane] > dseq:
                    continue
                dpos = self._draft_pos.get(lane, 0)
                if dpos < ls.pos:
                    # feed history[dpos:pos] at dpos: rows past a verify
                    # rewind are overwritten here before any draft query
                    # can attend to them (same causal-mask argument as
                    # the target's rewind)
                    eng.draft_prefill(lane, ls.history[dpos:ls.pos], dpos)
                    self._draft_pos[lane] = ls.pos
                lanes.append(lane)
                k = max(k, budgets[lane])
            if not lanes or k < 1:
                return {}
            k = bucket_for(k, self.spec_buckets)
            tokens = [0] * b
            pos = [0] * b
            act = [False] * b
            for lane in lanes:
                ls = self.lanes[lane]
                tokens[lane] = ls.token
                pos[lane] = ls.pos
                act[lane] = True
            props = eng.draft_propose(tokens, pos, act, k)
        except Exception as e:
            self.state.recorder.record(
                "draft_model_error", error=str(e),
                error_type=type(e).__name__, n_lanes=len(budgets),
            )
            return {}
        if not props:
            return {}
        out: dict[int, list[int]] = {}
        for lane in lanes:
            d = props[lane][: budgets[lane]]
            if d:
                out[lane] = d
                self._draft_fed[lane] = (pos[lane], len(d))
        return out

    def _spec_verify(self, drafts: dict[int, list[int]]) -> None:
        """One batched verify dispatch for every drafting lane: build the
        shared-width rows [pending, draft..., pads], accept each lane's
        longest matching prefix + 1 correction token, and flush the run
        through the normal per-token path. Lanes too close to seq_len
        for the shared bucket width drop out and decode normally."""
        st = self.state
        b = len(self.lanes)
        seq_len = self.engine.header.seq_len
        t = 1 + bucket_for(
            max(len(d) for d in drafts.values()), self.spec_buckets
        )
        for lane in list(drafts):
            ls = self.lanes[lane]
            if ls is None or ls.pos + t > seq_len:
                del drafts[lane]
        if not drafts:
            return
        rows = [[0] * t for _ in range(b)]
        pos = [0] * b
        act = [False] * b
        for lane, d in drafts.items():
            ls = self.lanes[lane]
            rows[lane] = [ls.token, *d] + [0] * (t - 1 - len(d))
            pos[lane] = ls.pos
            act[lane] = True
        # a verify dispatch IS token progress: it participates in the
        # same stall window accounting as the decode block
        now = self._clock()
        if self._last_decode_end is not None:
            st.m_decode_stall.observe(now - self._last_decode_end)
        t0 = time.perf_counter()
        wd = st.watchdog
        sp = st.spans.begin(
            "spec_verify", component="scheduler",
            n_lanes=len(drafts), t=t,
        )
        if wd is not None:
            wd.dispatch_begin("verify_lanes")
        try:
            grid = self._retry_dispatch(
                "verify_lanes",
                lambda: self.engine.verify_lanes(rows, pos, act),
            )
        finally:
            if wd is not None:
                wd.dispatch_end()
            st.spans.end(sp)
        self._last_decode_end = self._clock()
        dt = time.perf_counter() - t0
        n_emitted = 0
        for lane, d in drafts.items():
            out = grid[lane]
            # out[0] is the greedy token after the pending one (what a
            # decode step at this position would emit); out[j] is the
            # greedy token after draft j-1 — accept while they agree,
            # then emit out[a] as the correction/continuation token
            a = 0
            while a < len(d) and out[a] == d[a]:
                a += 1
            emitted = d[:a] + [out[a]]
            dr = self.drafters.get(lane)
            if dr is not None:
                dr.feedback(len(d), a)
            fed = self._draft_fed.pop(lane, None)
            if fed is not None:
                # draft-cache rows p+j hold history[p+j] for j <= a (row
                # p is the pending token, row p+i is draft i-1, valid
                # iff i-1 accepted drafts agree); rows past the rewind
                # point are stale and re-fed by catch-up before use
                self._draft_pos[lane] = fed[0] + min(a + 1, fed[1])
            st.m_spec_drafted.inc(len(d))
            st.m_spec_accepted.inc(a)
            st.m_spec_accept_len.observe(float(a))
            st.recorder.record(
                "spec_verify", lane=lane, k=len(d), accepted=a,
                pos=pos[lane],
            )
            n_emitted += len(emitted)
            # the accepted run flushes as a burst, but per-token latency
            # accounting stays honest: this lane got len(emitted) tokens
            # for one dispatch's wall time
            st.m_tpot.observe(dt / len(emitted))
            for tok in emitted:
                if not self._consume_token(lane, tok):
                    break
        st.slo.note_tokens(n_emitted)
        if st.m_spec_drafted.value > 0:
            st.g_spec_rate.set(
                st.m_spec_accepted.value / st.m_spec_drafted.value
            )
        if (
            st.g_spec_tokens_per_pass is not None
            and st.m_spec_accept_len.count > 0
        ):
            # each verify dispatch is one weight pass emitting 1+a tokens
            st.g_spec_tokens_per_pass.set(
                1.0 + st.m_spec_accept_len.sum / st.m_spec_accept_len.count
            )

    def _step_block(self) -> None:
        b = len(self.lanes)
        # free lanes whose client went away before paying for more decode
        for lane in range(b):
            ls = self.lanes[lane]
            if ls is not None and ls.job.cancelled:
                self._finish(lane, "cancelled")
        if not any(ls is not None for ls in self.lanes):
            return
        # speculative verify first: greedy lanes whose drafter proposes a
        # continuation take ONE batched verify dispatch; everyone else —
        # temperature>0 lanes, greedy lanes with nothing to propose —
        # shares the normal decode block in the same tick, so mixed
        # batches fall back transparently per lane, not per server
        verified: set[int] = set()
        if self.spec_on and self.drafters:
            drafts = self._spec_drafts()
            if drafts:
                self._spec_verify(drafts)
                verified = set(drafts)
        active = [
            ls is not None and lane not in verified
            for lane, ls in enumerate(self.lanes)
        ]
        if not any(active):
            return
        tokens = [ls.token if ls else 0 for ls in self.lanes]
        pos = [ls.pos if ls else 0 for ls in self.lanes]
        temps = [ls.temperature if ls else 0.0 for ls in self.lanes]
        topps = [ls.top_p if ls else 1.0 for ls in self.lanes]
        seeds = [ls.seed if ls else None for ls in self.lanes]
        # decode stall: the gap since the previous decode-block dispatch
        # finished, while >=1 lane was active the whole time — whatever sat
        # in between (admission chunks, host work) is latency a streaming
        # client ate. Chunked admission bounds it by one chunk + one block.
        now = self._clock()
        if self._last_decode_end is not None:
            self.state.m_decode_stall.observe(now - self._last_decode_end)
        t0 = time.perf_counter()
        wd = self.state.watchdog
        if wd is not None:
            wd.dispatch_begin("decode_lanes")
        try:
            rows = self._retry_dispatch(
                "decode_lanes",
                lambda: self.engine.decode_lanes(
                    tokens, pos, self.block_size, active, temps, topps,
                    seeds=seeds
                ),
            )
        finally:
            if wd is not None:
                wd.dispatch_end()
        self._last_decode_end = self._clock()
        if rows:
            # every active stream advanced len(rows) tokens in this block
            self.state.m_tpot.observe(
                (time.perf_counter() - t0) / len(rows)
            )
            self.state.slo.note_tokens(
                len(rows) * sum(1 for a in active if a)
            )
        if not rows:
            # every decode-side lane is out of sequence space (verified
            # lanes already advanced this tick and are not touched)
            for lane in range(b):
                if self.lanes[lane] is not None and active[lane]:
                    self._finish(lane, "length")
            return
        for row in rows:
            for lane in range(b):
                if self.lanes[lane] is None or not active[lane]:
                    continue
                if not self._consume_token(lane, row[lane]):
                    active[lane] = False


class ApiState:
    """Engine + tokenizer + conversation cache shared across requests."""

    def __init__(
        self,
        engine: InferenceEngine,
        tokenizer: Tokenizer,
        model_name: str = "dllama-tpu",
        chat_template_type: ChatTemplateType = ChatTemplateType.UNKNOWN,
        tracer: Tracer | None = None,
        lane_block_size: int = 8,
        admission_chunk: int | None = None,
        kv_page_size: int = 0,
        kv_pool_pages: int = 0,
        kv_native: bool = False,
        max_streams: int = 0,
        slo_ttft_ms: float | None = None,
        slo_tpot_ms: float | None = None,
        series_retention: float | None = None,
        speculation: str = "off",
        spec_k: int = DEFAULT_SPEC_K,
        retry_max: int = 3,
        retry_backoff_ms: int = 5,
        max_queue_depth: int = 0,
        replica_id: str | None = None,
        admission_predict: bool = False,
        admission_max_wait_ms: int = 30_000,
        deadline_default_ms: int = 600_000,
        deadline_priority_step_ms: int = 60_000,
    ):
        self.engine = engine
        self.tokenizer = tokenizer
        self.model_name = model_name
        # fleet identity (docs/fleet.md): names this replica in
        # /v1/health and scopes chaos injection (sse_flush op filter)
        self.replica_id = replica_id
        self.start_unix = time.time()
        # resilience knobs (resolve_resilience_knobs): the scheduler reads
        # the retry policy off this state; admission_decision() reads the
        # shed threshold (0 = unbounded queue, shedding off)
        self.retry_max = int(retry_max)
        self.retry_backoff_ms = int(retry_backoff_ms)
        self.max_queue_depth = int(max_queue_depth)
        # predictive admission (ISSUE 20, resolve_admission_knobs /
        # resolve_deadline_knobs): predict gates the whole controller
        # (infeasible-reject, EDF ordering, deadline preemption); the
        # deadline knobs shape the synthetic effective deadlines that
        # keep PR 12 priority semantics when no hints are given. The
        # LoadPredictor itself also backs the derived Retry-After on
        # every shed path, predictive mode on or off.
        self.admission_predict = bool(admission_predict)
        self.admission_max_wait_ms = int(admission_max_wait_ms)
        self.deadline_default_ms = int(deadline_default_ms)
        self.deadline_priority_step_ms = int(deadline_priority_step_ms)
        # bounded ring of recent |predicted - observed| TTFT errors in
        # ms: /v1/debug/admission reports p50/p95 off it (the bench's
        # prediction-error readout); appends on the scheduler thread
        from collections import deque

        self.predict_errors: deque = deque(maxlen=512)
        # graceful drain (POST /v1/drain, SIGTERM): admission stops, the
        # in-flight streams finish, sinks flush, /v1/health says so
        self.draining = False
        self.draining_since: float | None = None
        self.drained = threading.Event()
        # serving observability (obs/): the registry families behind
        # GET /metrics and the tracer behind --trace-out. Handles are
        # created up front (before the scheduler thread starts using them)
        # so the hot path never pays a registry lookup.
        self.obs = get_registry()
        self.recorder = get_recorder()
        self.tracer = tracer if tracer is not None else Tracer()
        # span timeline (GET /v1/debug/timeline, --timeline-out) and
        # windowed SLO attainment/goodput (GET /v1/debug/slo)
        self.spans = get_span_tracker()
        ttft_ms, tpot_ms = resolve_slo_knobs(slo_ttft_ms, slo_tpot_ms)
        self.slo = SloTracker(
            ttft_target_ms=ttft_ms, tpot_target_ms=tpot_ms
        )
        # one refresh path for every on-demand gauge: the /metrics scrape
        # and the series sampler both call run_refresh_hooks(), so the SLO
        # windows / device memory / step cost are never scrape-only stale.
        # Keyed registration: test churn rebuilds ApiState against the
        # process-global registry, and each rebuild REPLACES the hooks.
        self.obs.add_refresh_hook(
            "device_memory", lambda: sample_device_memory(self.obs)
        )
        self.obs.add_refresh_hook("slo", self.slo.snapshot)
        # in-process time-series store + sampler thread + anomaly monitor
        # (obs/timeseries.py, obs/anomaly.py): /v1/debug/series and the
        # /dashboard sparklines read the store; the anomaly monitor rides
        # the sampler tick and feeds /v1/health's degraded status
        retention_s, interval_s = resolve_series_knobs(series_retention)
        self.series = SeriesStore(
            interval_s=interval_s, retention_s=retention_s
        )
        self.sampler = MetricsSampler(self.series)
        self.anomaly = AnomalyMonitor(build_default_rules(self.series))
        self.sampler.on_sample.append(self.anomaly.evaluate)
        # POST /v1/debug/profile concurrency guard (one capture at a time)
        self.profile_lock = make_lock("api.profile")
        # analytic per-chip accounting, computed once: /v1/debug/memory
        # compares it against the live device.memory_stats() snapshot
        from ..utils.telemetry import memory_report

        self.mem_report = memory_report(
            engine.params,
            engine.cache,
            n_devices=engine.mesh.devices.size,
            tp=engine.tp,
        )
        self.m_http = self.obs.counter(
            "dllama_http_requests_total",
            "HTTP requests by path (unknown paths fold into 'other').",
            labelnames=("path",),
        )
        self.m_queue_depth = self.obs.gauge(
            "dllama_queue_depth",
            "Requests waiting for a free lane (lane-scheduler path).",
        )
        self.m_lanes_total = self.obs.gauge(
            "dllama_lanes_total", "Serving lanes this engine exposes."
        )
        self.m_lanes_active = self.obs.gauge(
            "dllama_lanes_active", "Lanes currently decoding a request."
        )
        self.m_queue_wait = self.obs.histogram(
            "dllama_queue_wait_seconds",
            "Submit -> admission wait (lane assignment or engine lock).",
        )
        self.m_prefill = self.obs.histogram(
            "dllama_prefill_seconds",
            "Prompt prefill wall time at admission.",
        )
        self.m_ttft = self.obs.histogram(
            "dllama_ttft_seconds",
            "Submit -> first generated token (time to first token).",
        )
        self.m_tpot = self.obs.histogram(
            "dllama_tpot_seconds",
            "Per-token decode latency a streaming client observes "
            "(block wall time / tokens per lane in the block).",
            buckets=DEFAULT_TOKEN_BUCKETS_S,
        )
        self.m_admissions = self.obs.counter(
            "dllama_admissions_total", "Requests admitted into a lane."
        )
        self.m_prefix_hits = self.obs.counter(
            "dllama_prefix_cache_hits_total",
            "Admissions that reused a stored prompt prefix (radix-tree "
            "match on the lane path, NaiveCache on the serialized path).",
        )
        self.m_prefix_misses = self.obs.counter(
            "dllama_prefix_cache_misses_total",
            "Admissions that prefilled from position 0.",
        )
        self.m_reused_tokens = self.obs.counter(
            "dllama_reused_prefix_tokens_total",
            "KV positions skipped thanks to prompt-prefix reuse.",
        )
        self.m_evictions = self.obs.counter(
            "dllama_cache_evictions_total",
            "Stored prompt prefixes dropped to make room: radix-tree LRU "
            "page evictions on the lane path (see also "
            "dllama_radix_evictions_total).",
        )
        self.m_cancellations = self.obs.counter(
            "dllama_sse_cancellations_total",
            "Streaming requests whose client disconnected mid-response.",
        )
        self.m_finished = self.obs.counter(
            "dllama_requests_finished_total",
            "Completed requests by finish reason "
            "(stop/length/cancelled/error).",
            labelnames=("reason",),
        )
        self.m_sched_errors = self.obs.counter(
            "dllama_scheduler_errors_total",
            "Engine errors swallowed by the lane-scheduler loop (each one "
            "dropped every in-flight lane; see the traceback log).",
        )
        # resilience (PR 12): retry/recovery/shed/drain observability
        self.m_dispatch_retries = self.obs.counter(
            "dllama_dispatch_retries_total",
            "Transient engine-dispatch failures re-issued by the "
            "scheduler's bounded-backoff retry (the cache epoch did not "
            "move, so lane KV survived the failure).",
        )
        self.m_lanes_recovered = self.obs.counter(
            "dllama_lanes_recovered_total",
            "Lanes resumed after a poisoning dispatch failure: the donated "
            "cache was rebuilt, the lane radix re-matched its published "
            "prefix and re-prefilled the unpublished suffix, and its "
            "stream continued byte-identically.",
        )
        self.m_shed = self.obs.counter(
            "dllama_requests_shed_total",
            "Requests refused at admission with 429/503 + Retry-After, by "
            "reason (draining / queue_full / degraded).",
            labelnames=("reason",),
        )
        self.g_draining = self.obs.gauge(
            "dllama_draining",
            "1 while the server drains (admission stopped, in-flight "
            "streams finishing), else 0.",
        )
        self.m_admission_chunks = self.obs.counter(
            "dllama_admission_chunks_total",
            "Bounded prefill chunks dispatched by the chunked admission "
            "state machine (one per scheduler tick per admitting lane).",
        )
        self.m_decode_stall = self.obs.histogram(
            "dllama_decode_stall_seconds",
            "Gap between consecutive decode-block dispatches while >=1 "
            "lane is active — the inter-token stall streaming clients "
            "see; bounded by one admission chunk + one block.",
        )
        # model-free speculation (runtime/spec.py): draft/accept volume,
        # the per-dispatch acceptance-length distribution, and the
        # cumulative acceptance ratio the /dashboard sparkline tracks
        self.m_spec_drafted = self.obs.counter(
            "dllama_spec_draft_tokens_total",
            "Draft tokens proposed by the n-gram speculator across "
            "verify dispatches.",
        )
        self.m_spec_accepted = self.obs.counter(
            "dllama_spec_accepted_tokens_total",
            "Draft tokens accepted by batched verification (the greedy "
            "argmax agreed with the draft at that position).",
        )
        self.m_spec_accept_len = self.obs.histogram(
            "dllama_spec_accept_length",
            "Accepted draft-prefix length per lane per verify dispatch "
            "(0 = the first draft token already diverged; each dispatch "
            "still emits one correction token).",
            buckets=(0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0),
        )
        self.g_spec_rate = self.obs.gauge(
            "dllama_spec_acceptance_rate",
            "Cumulative accepted/drafted token ratio of the n-gram "
            "speculator (0 until the first verify dispatch).",
        )
        # second-generation speculation (PR 18): per-source draft volume,
        # shared-store occupancy, and the roofline-facing tokens-per-
        # weight-pass gauge. Registered only when speculation is on so
        # `--speculation off` stays a pure bypass (no new series).
        self.m_spec_source = None
        self.g_spec_tokens_per_pass = None
        self.g_spec_store_groups = None
        self.g_spec_store_streams = None
        self.g_spec_store_tokens = None
        self.g_spec_store_hits = None
        self.g_spec_store_misses = None
        if speculation != "off":
            self.m_spec_source = self.obs.counter(
                "dllama_spec_source_total",
                "Draft tokens proposed, by source: the lane's private "
                "n-gram index, a sibling continuation from the shared "
                "store, or the resident draft model.",
                labelnames=("source",),
            )
            self.g_spec_tokens_per_pass = self.obs.gauge(
                "dllama_spec_tokens_per_weight_pass",
                "Mean tokens emitted per verify dispatch (1 + mean "
                "accepted prefix length) — compare against the roofline "
                "ceiling printed at startup.",
            )
        if speculation in ("shared", "draft"):
            self.g_spec_store_groups = self.obs.gauge(
                "dllama_spec_shared_store_groups",
                "Anchor groups (radix node identities) currently held "
                "by the cross-lane shared n-gram store.",
            )
            self.g_spec_store_streams = self.obs.gauge(
                "dllama_spec_shared_store_streams",
                "Published stream continuations across all anchor "
                "groups in the shared n-gram store.",
            )
            self.g_spec_store_tokens = self.obs.gauge(
                "dllama_spec_shared_store_tokens",
                "Accepted tokens retained across all shared-store "
                "stream continuations.",
            )
            self.g_spec_store_hits = self.obs.gauge(
                "dllama_spec_shared_store_hits",
                "Cumulative shared-store lookups that returned a "
                "sibling continuation.",
            )
            self.g_spec_store_misses = self.obs.gauge(
                "dllama_spec_shared_store_misses",
                "Cumulative shared-store lookups that found no usable "
                "sibling continuation.",
            )
        # oversubscription (PR 16): streams beyond the lane count park
        # (publish + drop page list, radix entry kept) and resume via
        # the recovery-admission path
        self.m_streams_parked = self.obs.gauge(
            "dllama_streams_parked",
            "Admitted streams currently parked out of their lane "
            "(--max-streams oversubscription): KV published to the "
            "shared pool, page list dropped, waiting to resume.",
        )
        self.m_stream_resumes = self.obs.counter(
            "dllama_stream_resumes_total",
            "Parked streams resumed into a lane via radix re-match "
            "through the recovery-admission path (near-zero re-prefill "
            "when the parked history published page-aligned).",
        )
        # predictive admission (ISSUE 20): forecast + error tracking.
        # Millisecond-scale buckets: TTFT forecasts span ~1ms (warm
        # prefix, idle engine) to tens of seconds (deep queue).
        _ms_buckets = (
            1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
            1000.0, 2000.0, 5000.0, 10000.0, 30000.0, 60000.0,
        )
        self.m_predicted_ttft = self.obs.histogram(
            "dllama_admission_predicted_ttft_ms",
            "LoadPredictor TTFT forecast recorded at admission (known "
            "queue wait + cost-model/percentile prefill forecast over "
            "the radix-matched suffix), in milliseconds.",
            buckets=_ms_buckets,
        )
        self.m_predict_error = self.obs.histogram(
            "dllama_admission_predict_error_ms",
            "Absolute estimated-vs-observed error of the admission "
            "forecast on clean finishes, by signal (ttft / tpot), in "
            "milliseconds; the EWMA correction factor feeds on the "
            "same pairs.",
            labelnames=("signal",),
            buckets=_ms_buckets,
        )
        self.m_admission_rejected = self.obs.counter(
            "dllama_admission_rejected_total",
            "Requests rejected by the PREDICTIVE controller before "
            "touching the queue, by reason (infeasible = the forecast "
            "says the deadline/TTFT budget cannot be met even if "
            "admitted now).",
            labelnames=("reason",),
        )
        self.m_preemptions = self.obs.counter(
            "dllama_preemptions_total",
            "Active streams parked by deadline preemption so a feasible "
            "hinted request could meet its SLO, by reason (priority = "
            "lower-priority victim; deadline_blown = the victim's own "
            "effective deadline had already passed).",
            labelnames=("reason",),
        )
        # request defaults captured once: per-request sampler mutations must
        # not leak into later requests' defaults
        self.default_temperature = engine.temperature
        self.default_top_p = engine.sampler.topp
        stops = [
            tokenizer.vocab[t].decode("utf-8", "replace")
            for t in tokenizer.eos_token_ids
        ]
        eos_piece = stops[0] if stops else ""
        self.stops = stops
        self.max_stop_len = max((len(s) for s in stops), default=0)
        self.template = ChatTemplateGenerator(
            chat_template_type, tokenizer.chat_template, eos_piece
        )
        self.naive_cache = NaiveCache()
        self.lock = make_lock("api.state")
        # batch_size > 1 engines serve requests CONCURRENTLY over the
        # engine's batch lanes (the reference's accept loop — and the
        # batch_size == 1 path here — serves one request at a time)
        lanes_on = engine.batch_size > 1 and engine.sp == 1
        # shared paged-KV pool + radix prefix tree for the lane path
        # (kv_page_size < 0 = sharing off, the bench baseline)
        self.kv_manager = None
        if lanes_on and kv_page_size >= 0:
            from ..kv.manager import PagedKVManager

            self.kv_manager = PagedKVManager(
                engine,
                page_size=kv_page_size,
                n_pages=kv_pool_pages,
                evict_counter=self.m_evictions,
                native=kv_native,
            )
        # LoadPredictor (ISSUE 20): always built on the lane path — the
        # derived Retry-After reads it even with predictive mode off;
        # the predictive gates (infeasible-reject, EDF, preemption)
        # additionally consult it when admission_predict is on. Must
        # exist BEFORE the scheduler thread starts (admission records
        # forecasts through it).
        self.predictor = LoadPredictor(engine) if lanes_on else None
        # engine watchdog audits the scheduler loop; it must exist BEFORE
        # the scheduler thread starts (the loop beats it every tick). The
        # decode-stalled threshold scales off the engine's own p99 block
        # time so slow models don't false-alarm.
        self.watchdog = None
        if lanes_on:
            self.watchdog = EngineWatchdog(
                block_p99=lambda: engine._m_step.labels(
                    kind="decode_lanes"
                ).percentile(0.99),
                recorder=self.recorder,
                **resolve_watchdog_knobs(),
            )
            self.watchdog.start()
        self.scheduler = (
            LaneScheduler(
                self,
                block_size=lane_block_size,
                admission_chunk=admission_chunk,
                speculation=speculation,
                spec_k=spec_k,
                max_streams=max_streams,
            )
            if lanes_on
            else None
        )
        self.m_lanes_total.set(
            engine.batch_size if self.scheduler is not None else 1
        )
        # postmortem context (satellite, PR 12): every ring dump embeds a
        # /v1/health snapshot plus the trailing 60 s of the anomaly-rule
        # series, so a dump is diagnosable without the live server
        self.recorder.add_context_provider("health", self.health_snapshot)
        self.recorder.add_context_provider("series_60s", self._series_context)
        # sampler last: every gauge/hook it snapshots now exists
        self.sampler.start()

    # -- health / drain / shed (PR 12) -----------------------------------

    def degraded_reasons(self) -> list[str]:
        """Composed degradation: the watchdog (hard stall) and the anomaly
        monitor (soft baseline deviation) each contribute reasons — never
        last-writer-wins. Shared by /v1/health and admission_decision."""
        reasons: list[str] = []
        wd = self.watchdog
        if wd is not None and wd.degraded:
            reasons.append(f"watchdog:{wd.status().get('reason')}")
        if self.anomaly.degraded:
            reasons.extend(
                f"anomaly:{s}" for s in self.anomaly.active_signals()
            )
        return reasons

    def health_snapshot(self) -> dict:
        """The /v1/health payload — also embedded into postmortem dumps
        via the recorder's context providers, so it must never take the
        scheduler cv (a postmortem can fire on the scheduler thread):
        the lane/pending reads are GIL-atomic snapshots."""
        sched = self.scheduler
        total = self.engine.batch_size if sched is not None else 1
        if sched is not None:
            active = sum(1 for ls in sched.lanes if ls is not None)
            queued = len(sched.pending)
        else:
            active = 1 if self.lock.locked() else 0
            queued = 0
        if sched is not None:
            admitting = len(sched.admitting)
            parked = sched._n_parked
            max_streams = max(sched.max_streams, total)
        else:
            admitting = 0
            parked = 0
            max_streams = 1
        payload = {
            "status": "ok",
            "model": self.model_name,
            "uptime_s": round(time.time() - self.start_unix, 3),
            "lanes": {
                "total": total,
                "active": active,
                "free": total - active,
            },
            "queue_depth": queued,
            "cache_epoch": self.engine.cache_epoch,
            # router-facing capacity (docs/fleet.md): what a front door
            # needs for admission-aware spill decisions — the stream
            # ceiling, everything currently holding a slot toward it,
            # and whether the pool is native (parks/resumes are cheap)
            "capacity": {
                "lanes": total,
                "max_streams": max_streams,
                "in_flight": active + admitting + parked + queued,
                "parked": parked,
                "kv_native": bool(
                    self.kv_manager is not None
                    and getattr(self.kv_manager, "native", False)
                ),
            },
        }
        if self.replica_id is not None:
            payload["replica"] = self.replica_id
        reasons = self.degraded_reasons()
        wd = self.watchdog
        if wd is not None and wd.degraded:
            payload["watchdog"] = wd.status()
        if self.anomaly.degraded:
            payload["anomaly"] = self.anomaly.status()
        if reasons:
            # a degraded engine is still accepting connections — health
            # says so, so a probe/router can act on it
            payload["status"] = "degraded"
            payload["degraded_reasons"] = reasons
        if self.draining:
            # draining wins: routers must stop sending traffic regardless
            # of how healthy the engine itself looks
            payload["status"] = "draining"
            payload["draining_since_unix"] = self.draining_since
        return payload

    def _series_context(self) -> dict:
        from ..obs.anomaly import DEFAULT_SIGNAL_SERIES

        out = {}
        for name in DEFAULT_SIGNAL_SERIES:
            q = self.series.query(name, 60.0)
            if q is not None:
                out[name] = q
        return out

    def estimate_prompt_tokens(self, params: InferenceParams) -> int:
        """Coarse pre-tokenize prompt-length estimate for the PRE-QUEUE
        feasibility gate (~4 chars/token plus template overhead per
        message). Deliberately conservative — it assumes zero radix
        match; the accurate forecast (real token count, real match
        length) is recorded at admission and the EWMA correction
        absorbs the residual bias."""
        if params.resume_tokens is not None:
            return len(params.resume_tokens)
        n_chars = sum(len(m.content) for m in params.messages)
        return max(2, n_chars // 4 + 8 * max(1, len(params.messages)))

    def note_predict_error(self, err_ms: float) -> None:
        self.predict_errors.append(float(err_ms))

    def predict_error_stats(self) -> dict:
        """p50/p95 of the recent TTFT prediction errors (ms) — the
        bench's prediction-error readout via /v1/debug/admission."""
        errs = sorted(self.predict_errors)
        n = len(errs)
        if not n:
            return {"n": 0, "p50_ms": None, "p95_ms": None}
        return {
            "n": n,
            "p50_ms": round(errs[n // 2], 3),
            "p95_ms": round(errs[min(n - 1, int(n * 0.95))], 3),
        }

    def predicted_retry_after(self, floor: int = 1) -> int:
        """Retry-After derived from the predicted queue-drain time
        (ISSUE 20) — monotonic in queue depth — replacing the PR 12
        constants everywhere the structured retryable error is built.
        Falls back to ``floor`` on the serialized path (no scheduler,
        no queue to predict)."""
        sched, pred = self.scheduler, self.predictor
        if sched is None or pred is None:
            return floor
        return max(
            floor,
            pred.retry_after_s(
                sched.occupancy(), self.admission_max_wait_ms
            ),
        )

    def admission_snapshot(self) -> dict:
        """GET /v1/debug/admission: the predictor's calibration state,
        the live occupancy it forecasts against, and recent prediction
        error percentiles."""
        out: dict = {
            "predictive": self.admission_predict,
            "max_wait_ms": self.admission_max_wait_ms,
            "deadline_default_ms": self.deadline_default_ms,
            "deadline_priority_step_ms": self.deadline_priority_step_ms,
            "prediction_error": self.predict_error_stats(),
        }
        sched, pred = self.scheduler, self.predictor
        if sched is not None and pred is not None:
            occ = sched.occupancy()
            out["occupancy"] = occ.as_dict()
            out["predictor"] = pred.snapshot()
            out["retry_after_s"] = pred.retry_after_s(
                occ, self.admission_max_wait_ms
            )
        return out

    def admission_decision(
        self, priority: str, params: InferenceParams | None = None
    ) -> tuple[str, int] | None:
        """Load-shedding gate, consulted by the handler BEFORE a request
        touches the scheduler queue. None admits; otherwise returns
        (reason, retry_after_s) and the handler refuses with 429/503 +
        Retry-After. The priority ladder sheds lowest first: a "low"
        request is refused at half the queue threshold and whenever the
        engine is degraded; "high" rides out twice the threshold.

        Every Retry-After is DERIVED from the predicted queue-drain
        time (ISSUE 20) instead of the old constants, with the PR 12
        constants kept as floors. With predictive mode on, a HINTED
        request whose forecast cannot meet its budget even if admitted
        now is additionally rejected as ``infeasible`` — unhinted
        requests never are, so with no hints this gate is exactly the
        PR 12 ladder."""
        if self.draining:
            return ("draining", self.predicted_retry_after(floor=5))
        sched = self.scheduler
        if sched is not None and self.max_queue_depth > 0:
            factor = {"low": 0.5, "high": 2.0}.get(priority, 1.0)
            if len(sched.pending) >= self.max_queue_depth * factor:
                return ("queue_full", self.predicted_retry_after())
        if priority == "low" and self.degraded_reasons():
            return ("degraded", self.predicted_retry_after(floor=2))
        if (
            self.admission_predict
            and params is not None
            and params.deadline_hinted
            and sched is not None
            and self.predictor is not None
        ):
            budget = min(
                h for h in (params.deadline_ms, params.ttft_budget_ms)
                if h is not None
            )
            pred = self.predictor.predict(
                self.estimate_prompt_tokens(params), sched.occupancy()
            )
            if pred.ttft_ms > budget:
                self.m_admission_rejected.labels(
                    reason="infeasible"
                ).inc()
                return ("infeasible", self.predicted_retry_after())
        return None

    def begin_drain(self) -> dict:
        """Start a graceful drain (POST /v1/drain, SIGTERM): admission
        flips to shedding, in-flight streams run to completion, then the
        span/trace sinks flush and ``drained`` is set. Idempotent."""
        sched = self.scheduler
        if sched is not None:
            in_flight = (
                sum(1 for ls in sched.lanes if ls is not None)
                + len(sched.admitting)
                + len(sched.pending)
            )
        else:
            in_flight = 1 if self.lock.locked() else 0
        if not self.draining:
            self.draining = True
            self.draining_since = time.time()
            self.g_draining.set(1)
            self.recorder.record("drain_begin", in_flight=in_flight)
            t = threading.Thread(  # dlint: disable=thread-hygiene — the drained event is the join surface; the process exits after it fires
                target=self._drain_watch, daemon=True, name="dllama-drain"
            )
            t.start()
        return {
            "status": "draining",
            # the streams still running RIGHT NOW plus whether the drain
            # already finished — a rolling restart polls this endpoint
            # until drained flips true (docs/fleet.md runbook)
            "in_flight": in_flight,
            "drained": self.drained.is_set(),
            "since_unix": self.draining_since,
        }

    def _drain_watch(self) -> None:
        """Poll until every in-flight request finished, then flush the
        observability sinks and signal ``drained`` (the SIGTERM handler
        waits on it before shutting the HTTP server down)."""
        sched = self.scheduler
        while True:
            if sched is not None:
                idle = (
                    not any(sched.lanes)
                    and not sched.admitting
                    and not sched.pending
                )
            else:
                idle = not self.lock.locked()
            if idle:
                break
            time.sleep(0.05)
        self.spans.flush()
        self.recorder.record("drain_complete")
        # the rolling-restart poll target: in-flight hit zero, sinks are
        # flushed, the process is safe to replace (drain_s from the
        # POST /v1/drain that started the drain)
        since = self.draining_since
        self.recorder.record(
            "drained",
            in_flight=0,
            drain_s=(
                round(time.time() - since, 3) if since is not None else 0.0
            ),
        )
        self.drained.set()

    # -- completion ------------------------------------------------------

    def complete(self, params: InferenceParams, emit, span=None) -> dict:
        """Run one chat completion; `emit(delta)` is called per text delta
        (streaming). Returns the non-stream response dict.
        (reference: ApiServer::complete, src/dllama-api.cpp:367-487)

        Crash consistency (single-stream analogue of the lane
        scheduler's error path, and of the reference's 3 s whole-app
        retry loop, src/dllama-api.cpp:616-628): a dispatch failure has
        already dropped the engine's donated KV cache
        (engine._cache_guard), so the positions recorded in the
        NaiveCache no longer exist. The cache EPOCH is the exact
        signal — any exception class can be raised inside a guarded
        dispatch (even ValueError, at trace time), so "which exception"
        does not tell us whether KV state survived; the epoch does.
        Client-caused errors raised before any dispatch leave the
        epoch, and therefore the prompt cache, untouched."""
        if span is None:
            span = NULL_SPAN
        epoch = self.engine.cache_epoch
        try:
            return self._complete(params, emit, span)
        except BaseException as e:
            if self.engine.cache_epoch != epoch:
                self.naive_cache.clear()
            # an OSError here came from emit -> the client's socket: the
            # request was cancelled, not broken
            reason = "cancelled" if isinstance(e, OSError) else "error"
            if span.finish(reason) is not None:
                self.m_finished.labels(reason=reason).inc()
                if reason == "cancelled":
                    self.m_cancellations.inc()
            raise

    def _complete(self, params: InferenceParams, emit, span=NULL_SPAN) -> dict:
        engine, tok = self.engine, self.tokenizer
        engine.temperature = params.temperature
        engine.sampler.set_temp(params.temperature)
        engine.sampler.set_topp(params.top_p)
        if params.seed is not None:
            engine.set_seed(params.seed)

        delta_prompt, start_pos = self.naive_cache.resolve_delta_prompt(
            params.messages
        )
        if start_pos > 0:
            self.m_prefix_hits.inc()
            self.m_reused_tokens.inc(start_pos)
        else:
            self.m_prefix_misses.inc()
        span.set_reused_prefix(start_pos)
        if start_pos == 0:
            engine.reset()

        items = [ChatItem(m.role, m.content) for m in delta_prompt]
        prompt = self.template.generate(items, append_generation_prompt=True)
        tokens = tok.encode(
            prompt.content, is_start=start_pos == 0, add_special_tokens=True
        )
        n_prompt_tokens = len(tokens)
        seq_len = engine.header.seq_len
        prompt_end_pos = min(start_pos + n_prompt_tokens - 1, seq_len)
        max_pred_pos = (
            min(prompt_end_pos + params.max_tokens, seq_len)
            if params.max_tokens > 0
            else seq_len
        )

        buffer = ""
        if prompt.public_prompt:
            emit(prompt.public_prompt)
            buffer += prompt.public_prompt

        tok.reset_decoder()
        detector = EosDetector(
            tok.eos_token_ids,
            self.stops if not params.stop else params.stop,
            padding_left=self.max_stop_len,
            padding_right=self.max_stop_len,
        )

        # On-device block decode via the engine's shared loop (one host
        # dispatch per ~8 tokens). EOS is detected per consumed token; the
        # KV rows a block wrote past the stop are masked garbage until the
        # next prefill overwrites them. NB: sampled (temperature>0) decode
        # uses the engine's on-device JAX PRNG — seeded-reproducible, but a
        # different RNG than the reference's xorshift host sampler (which
        # remains available via engine.decode_step / Sampler).
        state = {"hit_eos": False, "buffer": buffer}
        t_gen = time.perf_counter()

        def on_token(t: int):
            self.slo.note_tokens(1)
            ttft = span.mark_first_token()
            if ttft is not None:
                self.m_ttft.observe(ttft)
                # prefill span on this path: generate() start -> first
                # token readback (prefill + the first decode dispatch)
                pf = time.perf_counter() - t_gen
                span.set_prefill_seconds(pf)
                self.m_prefill.observe(pf)
            piece = tok.decode(t)
            eos_type = detector.append(t, piece)
            if eos_type in (EosResult.NOT_EOS, EosResult.EOS):
                delta = detector.get_delta()
                if delta:
                    emit(delta)
                    state["buffer"] += delta
                detector.reset()
            if eos_type == EosResult.EOS:
                state["hit_eos"] = True
                return False
            return True

        out_tokens, _, _ = engine.generate(
            tokens,
            max_steps=max_pred_pos - start_pos,
            on_token=on_token,
            start_pos=start_pos,
        )
        pos = prompt_end_pos + len(out_tokens)
        token = out_tokens[-1] if out_tokens else tokens[-1]
        hit_eos = state["hit_eos"]
        buffer = state["buffer"]

        n_completion = pos - prompt_end_pos
        if not hit_eos and pos < seq_len:
            # (block decode already wrote this KV row if the block ran past
            # max_pred_pos, but re-writing the same row is idempotent)
            # max_tokens truncation: the last sampled token's text is in
            # `buffer` but its KV entry was never written; run one KV-only
            # step so a cached continuation resumes from a complete context
            # (the reference skips this and silently degrades, dllama-api.cpp:470-475).
            engine.decode_step(token, pos)
            pos += 1

        message = ChatMessage("assistant", buffer)
        if pos >= seq_len:
            self.naive_cache.clear()
            engine.reset()
        else:
            # Record the conversation only now that its KV entries really
            # exist (pushing before prefill would let a failed request
            # poison the cache with positions that were never written).
            for m in delta_prompt:
                self.naive_cache.push(NaiveCacheItem(prompt_end_pos, m))
            self.naive_cache.push(NaiveCacheItem(pos, message))

        reason = "stop" if hit_eos else "length"
        if span.finish(
            reason, n_prompt=n_prompt_tokens, n_completion=n_completion
        ) is not None:
            self.m_finished.labels(reason=reason).inc()
            self.slo.observe_span(span)
            self.spans.maybe_flush()
        return _completion_response(
            self,
            buffer,
            reason,
            n_prompt_tokens,
            n_completion,
            span=span,
        )


def _span_metadata(span) -> dict | None:
    """Serving metadata exposed to clients (`dllama` field of the
    non-stream response and the final SSE chunk): request id, TTFT,
    queue wait, lane, reused prefix."""
    if span is None or span is NULL_SPAN:
        return None
    return {
        "request_id": span.request_id,
        "lane": span.lane,
        "ttft_ms": None if span.ttft_ms is None else round(span.ttft_ms, 3),
        "queue_ms": (
            None if span.queue_wait_ms is None
            else round(span.queue_wait_ms, 3)
        ),
        "reused_prefix_tokens": span.reused_prefix_tokens,
    }


def _completion_response(
    state: "ApiState",
    content: str,
    finish_reason: str,
    n_prompt: int,
    n_completion: int,
    span=None,
) -> dict:
    """The chat.completion response body, shared by the serialized and
    lane-scheduled serving paths."""
    resp = {
        "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
        "object": "chat.completion",
        "created": int(time.time()),
        "model": state.model_name,
        "choices": [
            {
                "index": 0,
                "message": {"role": "assistant", "content": content},
                "finish_reason": finish_reason,
            }
        ],
        "usage": {
            "prompt_tokens": n_prompt,
            "completion_tokens": n_completion,
            "total_tokens": n_prompt + n_completion,
        },
    }
    meta = _span_metadata(span)
    if meta is not None:
        resp["dllama"] = meta
    return resp


def _sse_write(wfile, data: str) -> None:
    """One HTTP-chunked SSE frame (shared by both streaming paths)."""
    raw = data.encode("utf-8")
    wfile.write(f"{len(raw):x}\r\n".encode() + raw + b"\r\n")


def _chunk_payload(
    state: ApiState,
    delta: str | None,
    stop: bool,
    reason: str = "stop",
    span=None,
) -> dict:
    choice: dict = {"index": 0, "finish_reason": reason if stop else None}
    if not stop:
        choice["delta"] = {"role": "assistant", "content": delta}
    payload = {
        "id": "cmpl-1",
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": state.model_name,
        "choices": [choice],
    }
    if stop:
        meta = _span_metadata(span)
        if meta is not None:
            payload["dllama"] = meta
    return payload


_KNOWN_PATHS = frozenset(
    {
        "/v1/chat/completions",
        "/v1/models",
        "/v1/health",
        "/v1/debug/recorder",
        "/v1/debug/memory",
        "/v1/debug/compile",
        "/v1/debug/xlalint",
        "/v1/debug/kv",
        "/v1/debug/timeline",
        "/v1/debug/slo",
        "/v1/debug/admission",
        "/v1/debug/series",
        "/v1/debug/profile",
        "/v1/drain",
        "/dashboard",
        "/metrics",
        "/health",
        "/healthz",
    }
)


def make_handler(state: ApiState):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _count_request(self) -> None:
            # unknown paths fold into one label so a scanner can't blow up
            # the metric's cardinality; query strings don't split series
            path = self.path.partition("?")[0]
            if path not in _KNOWN_PATHS:
                path = "other"
            state.m_http.labels(path=path).inc()

        def log_message(self, fmt, *args):  # quiet access log
            pass

        def _json(
            self, payload: dict, status: int = 200,
            retry_after: int | None = None,
        ) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(status)
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header("Content-Type", "application/json; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            if retry_after is not None:
                # shed/drain refusals tell the client when to come back
                self.send_header("Retry-After", str(retry_after))
            self.end_headers()
            self.wfile.write(body)

        def do_OPTIONS(self):  # CORS preflight (reference: writeCors)
            self.send_response(204)
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header(
                "Access-Control-Allow-Methods", "GET, POST, PUT, DELETE"
            )
            self.send_header(
                "Access-Control-Allow-Headers", "Content-Type, Authorization"
            )
            self.end_headers()

        def do_GET(self):
            self._count_request()
            if state.replica_id is not None:
                set_thread_replica(state.replica_id)
            # /v1/debug/timeline takes ?request_id=...; parse by hand so
            # the other exact-match branches tolerate stray queries too
            path, _, query = self.path.partition("?")
            params = parse_qs(query)
            if path == "/v1/models":
                self._json(
                    {
                        "object": "list",
                        "data": [
                            {
                                "id": state.model_name,
                                "object": "model",
                                "created": 0,
                                "owned_by": "user",
                            }
                        ],
                    }
                )
            elif path == "/metrics":
                # the shared refresh path (device memory, SLO windows,
                # step cost) — the series sampler runs the SAME hooks, so
                # scrape and sampler always agree
                state.obs.run_refresh_hooks()
                body = state.obs.render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", state.obs.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/v1/health":
                # composed status (ok/degraded/draining) — the same
                # snapshot postmortem dumps embed (ApiState.health_snapshot)
                self._json(state.health_snapshot())
            elif path == "/v1/debug/recorder":
                # the engine flight recorder's ring: the last N
                # dispatches/compiles/epochs/scheduler decisions
                self._json(state.recorder.dump())
            elif path == "/v1/debug/memory":
                stats = sample_device_memory(state.obs)
                mr = state.mem_report
                self._json(
                    {
                        "devices": stats,
                        "analytic": {
                            "params_bytes": mr.params_bytes,
                            "cache_bytes": mr.cache_bytes,
                            "total_bytes": mr.total_bytes,
                            "per_device_bytes": mr.per_device_bytes,
                        },
                        "comparison": compare_with_analytic(
                            mr.per_device_bytes, stats
                        ),
                    }
                )
            elif path == "/v1/debug/kv":
                # paged-KV pool + radix tree accounting (lane path);
                # {"enabled": false} when sharing is off or single-lane
                if state.kv_manager is None:
                    self._json({"enabled": False})
                else:
                    payload = state.kv_manager.debug()
                    payload["enabled"] = True
                    self._json(payload)
            elif path == "/v1/debug/compile":
                self._json(
                    {
                        "programs": state.engine.compile_cache_report(),
                        "cost": state.engine.cost_report(),
                    }
                )
            elif path == "/v1/debug/xlalint":
                # compiled-program lint over the live compile cache:
                # donation/collective/dtype/host/cost-budget findings
                # split new-vs-baselined (docs/static_analysis.md)
                self._json(state.engine.xlalint_report())
            elif path == "/v1/debug/timeline":
                # Chrome-trace / Perfetto JSON of the span ring; with
                # ?request_id= it narrows to one request and adds its
                # millisecond-accounting summary under "dllama". The
                # fleet stitcher adds ?replica= (keep only that replica's
                # spans — the in-process fleet shares one tracker),
                # ?pid_prefix= and ?pid_base= so merged fragments don't
                # collide (obs/spans.py, ISSUE 19)
                rid = (params.get("request_id") or [None])[0]
                rep = (params.get("replica") or [None])[0]
                prefix = (params.get("pid_prefix") or [None])[0]
                try:
                    base = int((params.get("pid_base") or ["0"])[0])
                except ValueError:
                    base = 0
                self._json(state.spans.chrome_trace(
                    request_id=rid, replica=rep, pid_prefix=prefix,
                    pid_base=base,
                ))
            elif path == "/v1/debug/slo":
                self._json(state.slo.snapshot())
            elif path == "/v1/debug/admission":
                # predictive-admission introspection (ISSUE 20): the
                # predictor's calibration, live occupancy, and recent
                # prediction-error percentiles
                self._json(state.admission_snapshot())
            elif path == "/v1/debug/series":
                # in-process time-series: no ?name= lists the tracked
                # series (plus the anomaly monitor's status); with
                # ?name=&window= it returns the trailing points
                name = (params.get("name") or [None])[0]
                if name is None:
                    self._json(
                        {
                            "names": state.series.names(),
                            "interval_s": state.series.interval_s,
                            "retention_s": state.series.retention_s,
                            "anomaly": state.anomaly.status(),
                        }
                    )
                    return
                try:
                    window = float(
                        (params.get("window") or ["300"])[0]
                    )
                except ValueError:
                    self._json(
                        {"error": {"message": "bad window"}}, 400
                    )
                    return
                result = state.series.query(name, window)
                if result is None:
                    self._json(
                        {"error": {"message": f"no series {name!r}"}}, 404
                    )
                    return
                self._json(result)
            elif path == "/dashboard":
                # single-file live dashboard (obs/dashboard.py): inline
                # HTML/JS sparklines over /v1/debug/series, no external
                # assets
                body = render_dashboard()
                self.send_response(200)
                self.send_header("Content-Type", DASHBOARD_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path in ("/health", "/healthz"):
                self._json({"status": "ok"})
            else:
                self.send_error(404, "Not Found")

        def do_POST(self):
            self._count_request()
            if state.replica_id is not None:
                # replica-attributed spans (ISSUE 19): handler threads are
                # per-request, so tag each one; the in-process fleet's
                # shared tracker then knows which replica each span is
                set_thread_replica(state.replica_id)
            path = self.path.partition("?")[0]
            if path == "/v1/debug/profile":
                self._profile()
                return
            if path == "/v1/drain":
                # graceful drain: stop admission, finish in-flight
                # streams, flush sinks, flip /v1/health to "draining"
                self._json(state.begin_drain())
                return
            if path != "/v1/chat/completions":
                self.send_error(404, "Not Found")
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
                params = self._parse_params(body)
            except (ValueError, KeyError, TypeError) as e:
                self._json({"error": {"message": f"bad request: {e}"}}, 400)
                return

            if params.trace_id is not None:
                # fleet identity adopted: leave a recorder trail BEFORE
                # the shed gate so even refused relays are attributable
                state.recorder.record(
                    "trace_adopt", trace_id=params.trace_id,
                    request_id=params.request_id,
                    replica=state.replica_id,
                    resumed=params.resume_tokens is not None,
                )

            # load shedding BEFORE the request touches the queue or the
            # engine lock: a refused request costs the server nothing
            shed = state.admission_decision(params.priority, params)
            if shed is not None:
                reason, retry_after = shed
                state.m_shed.labels(reason=reason).inc()
                state.recorder.record(
                    "request_shed", reason=reason,
                    priority=params.priority, retry_after_s=retry_after,
                )
                self._json(
                    {
                        "error": {
                            "message": f"request shed: {reason}",
                            "retryable": True,
                            "retry_after_s": retry_after,
                        }
                    },
                    503 if reason == "draining" else 429,
                    retry_after=retry_after,
                )
                return

            if state.scheduler is not None:
                self._complete_lanes(params)
                return
            if params.resume_tokens is not None:
                # the serialized (batch_size == 1) path has no
                # recovery-admission machinery; a resume there would
                # silently retokenize — refuse instead
                self._json(
                    {
                        "error": {
                            "message": "resume_tokens requires the lane "
                            "scheduler (batch_size > 1)",
                        }
                    },
                    400,
                )
                return
            span = state.tracer.span(
                request_id=params.request_id, path="single",
                trace_id=params.trace_id,
            )
            with state.lock:
                # queue wait on this path is the engine-lock wait
                state.m_queue_wait.observe(span.mark_admitted())
                state.m_admissions.inc()
                state.m_lanes_active.set(1)
                try:
                    if params.stream:
                        self._stream(params, span)
                    else:
                        try:
                            response = state.complete(
                                params, emit=lambda d: None, span=span
                            )
                        except ValueError as e:  # client-caused (e.g. prompt too long)
                            self._json({"error": {"message": str(e)}}, 400)
                            return
                        except Exception as e:  # surface model errors as JSON
                            self._json({"error": {"message": str(e)}}, 500)
                            return
                        self._json(response)
                finally:
                    state.m_lanes_active.set(0)

        def _profile(self) -> None:
            """POST /v1/debug/profile — on-demand jax.profiler capture.

            Body: {"seconds": 2.0, "out_dir": "..."} (both optional).
            One capture at a time (409 while another runs); the hardened
            telemetry.profile() context logs-and-continues on backends
            where tracing is unavailable, so this is CPU-safe."""
            import os
            import tempfile

            from ..utils import telemetry

            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
                seconds = float(body.get("seconds", 2.0))
                out_dir = body.get("out_dir")
            except (ValueError, TypeError) as e:
                self._json({"error": {"message": f"bad request: {e}"}}, 400)
                return
            if not (0.0 < seconds <= 60.0):
                self._json(
                    {"error": {"message": "seconds must be in (0, 60]"}},
                    400,
                )
                return
            if not out_dir:
                out_dir = os.path.join(
                    tempfile.gettempdir(),
                    f"dllama-profile-{uuid.uuid4().hex[:8]}",
                )
            if not state.profile_lock.acquire(blocking=False):
                self._json(
                    {"error": {"message": "a capture is already running"}},
                    409,
                )
                return
            try:
                with telemetry.profile(out_dir):
                    time.sleep(seconds)
            finally:
                state.profile_lock.release()
            n_files = 0
            for _, _, files in os.walk(out_dir):
                n_files += len(files)
            state.recorder.record(
                "profile_capture", log_dir=out_dir, seconds=seconds,
                n_files=n_files,
            )
            self._json(
                {"log_dir": out_dir, "seconds": seconds, "n_files": n_files}
            )

        def _complete_lanes(self, params: InferenceParams) -> None:
            """Concurrent path: submit to the lane scheduler and relay its
            event stream; many handler threads can sit here at once."""
            # `seed` is honored per lane (r5): the scheduler threads it
            # to decode_lanes, whose per-lane (seed, position) keys make
            # the stream reproducible independent of other lanes
            job = state.scheduler.submit(params)
            if params.stream:
                self._sse_headers()
                finish_reason = "stop"
                errored = False
                try:
                    while True:
                        kind, payload = job.events.get()
                        if kind == "delta":
                            # include_tokens deltas arrive as dicts with
                            # exact token/piece attribution; plain deltas
                            # (and the public-prompt echo) stay strings
                            if isinstance(payload, dict):
                                chunk = _chunk_payload(
                                    state, payload["text"], stop=False
                                )
                                chunk["dllama_tokens"] = payload["tokens"]
                                chunk["dllama_piece"] = payload["piece"]
                            else:
                                chunk = _chunk_payload(
                                    state, payload, stop=False
                                )
                                if params.include_tokens:
                                    # prompt-echo text: no generated
                                    # tokens back it (they are already in
                                    # the prompt), but the piece field
                                    # keeps exact-text accounting whole
                                    chunk["dllama_tokens"] = []
                                    chunk["dllama_piece"] = payload
                            # one span per SSE frame: a slow client's
                            # socket backpressure shows up on the http
                            # track of the timeline, not as engine time
                            with state.spans.span(
                                "sse_flush", component="http",
                                request_id=job.span.request_id,
                                lane=job.span.lane,
                            ):
                                # chaos site: a mid-stream client death is
                                # indistinguishable from a flush failure,
                                # so inject it AS one (exercises the
                                # cancel path below)
                                # `op` scopes the injection to one
                                # replica (sse_flush:op=r1:...) so fleet
                                # chaos can kill a single replica's
                                # streams while its siblings stay clean
                                fault = get_fault_plane().draw(
                                    "sse_flush", op=state.replica_id
                                )
                                if fault is not None:
                                    raise OSError(str(fault))
                                _sse_write(
                                    self.wfile,
                                    f"data: {json.dumps(chunk)}\r\n\r\n",
                                )
                        elif kind == "error":
                            err = (
                                payload
                                if isinstance(payload, dict)
                                else {"message": str(payload)}
                            )
                            _sse_write(
                                self.wfile,
                                "data: "
                                + json.dumps({"error": err})
                                + "\r\n\r\n",
                            )
                            errored = True
                            break
                        else:  # done
                            finish_reason = payload
                            break
                    if not errored:
                        final = _chunk_payload(
                            state, None, True, finish_reason, span=job.span
                        )
                        _sse_write(
                            self.wfile,
                            "data: " + json.dumps(final) + "\r\n\r\n",
                        )
                    _sse_write(self.wfile, "data: [DONE]\r\n\r\n")
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    # client went away: tell the scheduler to stop paying
                    # for this lane (the serialized path aborts via the
                    # emit exception; this is the lane-mode equivalent).
                    # The chunked body is unterminated, so this keep-alive
                    # connection can never carry another request — close
                    # it, which is also what lets a fleet router observe
                    # the death as EOF instead of a stalled read
                    job.cancelled = True
                    self.close_connection = True
                return
            finish_reason = "stop"
            while True:
                kind, payload = job.events.get()
                if kind == "error":
                    err = (
                        payload
                        if isinstance(payload, dict)
                        else {"message": str(payload)}
                    )
                    # a retryable failure (engine fault, not the client's
                    # request) answers 503 + Retry-After; validation
                    # errors keep their 500
                    self._json(
                        {"error": err},
                        503 if err.get("retryable") else 500,
                        # derived Retry-After (ISSUE 20): quote the
                        # predicted queue-drain, not a constant
                        retry_after=(
                            state.predicted_retry_after()
                            if err.get("retryable")
                            else None
                        ),
                    )
                    return
                if kind == "done":
                    finish_reason = payload
                    break
            response = _completion_response(
                state,
                job.buffer,
                finish_reason,
                job.n_prompt_tokens,
                job.n_completion,
                span=job.span,
            )
            self._json(response)

        def _sse_headers(self) -> None:
            self.send_response(200)
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header("Content-Type", "text/event-stream; charset=utf-8")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

        def _stream(self, params: InferenceParams, span=None) -> None:
            self._sse_headers()

            def write_chunk(data: str) -> None:
                _sse_write(self.wfile, data)

            def emit(delta: str) -> None:
                payload = _chunk_payload(state, delta, stop=False)
                write_chunk(f"data: {json.dumps(payload)}\r\n\r\n")

            finish_reason = "stop"
            try:
                result = state.complete(params, emit=emit, span=span)
                finish_reason = result["choices"][0]["finish_reason"]
            except OSError:
                # the client disconnected mid-stream (emit hit its dead
                # socket); complete() already recorded the cancellation —
                # nothing left to write to
                return
            except Exception as e:
                # headers are already sent; deliver the error in-stream so
                # the client still gets a well-formed SSE termination
                write_chunk(
                    f"data: {json.dumps({'error': {'message': str(e)}})}\r\n\r\n"
                )
            write_chunk(
                "data: "
                + json.dumps(
                    _chunk_payload(state, None, True, finish_reason, span=span)
                )
                + "\r\n\r\n"
            )
            write_chunk("data: [DONE]\r\n\r\n")
            self.wfile.write(b"0\r\n\r\n")

        def _parse_params(self, body: dict) -> InferenceParams:
            """(reference: parseRequest, src/dllama-api.cpp:491-520)"""
            params = InferenceParams(
                temperature=state.default_temperature,
                top_p=state.default_top_p,
                stop=[],
            )
            if body.get("resume_tokens") is not None:
                # fleet failover resume: a raw fed-token history stands in
                # for the chat messages (lane path only; see do_POST)
                params.resume_tokens = [
                    int(t) for t in body["resume_tokens"]
                ]
                params.messages = [
                    ChatMessage(m["role"], m["content"])
                    for m in body.get("messages", [])
                ]
            else:
                params.messages = [
                    ChatMessage(m["role"], m["content"])
                    for m in body["messages"]
                ]
            if "include_tokens" in body:
                params.include_tokens = bool(body["include_tokens"])
            if "stream" in body:
                params.stream = bool(body["stream"])
            if "temperature" in body:
                params.temperature = float(body["temperature"])
            if "top_p" in body:
                params.top_p = float(body["top_p"])
            if "seed" in body:
                params.seed = int(body["seed"])
            if "max_tokens" in body:
                params.max_tokens = int(body["max_tokens"])
            if "stop" in body:
                stop = body["stop"]
                # OpenAI allows a bare string or a list of strings
                params.stop = [stop] if isinstance(stop, str) else [str(x) for x in stop]
            if "priority" in body:
                priority = str(body["priority"])
                if priority not in ("low", "normal", "high"):
                    raise ValueError(f"unknown priority {priority!r}")
                params.priority = priority
            # predictive admission (ISSUE 20): optional latency budgets.
            # Body fields win; the x-dllama-deadline-ms relay header
            # (fleet router) backstops deadline_ms so budgets survive
            # relays and failover re-issues
            if body.get("deadline_ms") is not None:
                params.deadline_ms = float(body["deadline_ms"])
                if params.deadline_ms <= 0:
                    raise ValueError("deadline_ms must be > 0")
            if body.get("ttft_budget_ms") is not None:
                params.ttft_budget_ms = float(body["ttft_budget_ms"])
                if params.ttft_budget_ms <= 0:
                    raise ValueError("ttft_budget_ms must be > 0")
            hdr_deadline = self.headers.get("x-dllama-deadline-ms")
            if hdr_deadline and params.deadline_ms is None:
                try:
                    params.deadline_ms = float(hdr_deadline)
                except ValueError:
                    pass  # a malformed relay header never fails the request
                else:
                    if params.deadline_ms <= 0:
                        params.deadline_ms = None
            # fleet trace propagation (ISSUE 19): adopt the router-minted
            # identity headers; absent outside a fleet
            trace_id = self.headers.get("x-dllama-trace")
            request_id = self.headers.get("x-dllama-request")
            if trace_id:
                params.trace_id = str(trace_id)
            if request_id:
                params.request_id = str(request_id)
            return params

    return Handler


def serve(
    engine: InferenceEngine,
    tokenizer: Tokenizer,
    host: str = "0.0.0.0",
    port: int = 9990,
    model_name: str = "dllama-tpu",
    chat_template_type: ChatTemplateType = ChatTemplateType.UNKNOWN,
    trace_out: str | None = None,
    postmortem_dir: str | None = None,
    lane_block_size: int | None = None,
    admission_chunk: int | None = None,
    kv_page_size: int | None = None,
    kv_pool_pages: int | None = None,
    kv_native: bool | None = None,
    max_streams: int | None = None,
    timeline_out: str | None = None,
    slo_ttft_ms: float | None = None,
    slo_tpot_ms: float | None = None,
    series_retention: float | None = None,
    speculation: str | None = None,
    spec_k: int | None = None,
    draft_model: str | None = None,
    retry_max: int | None = None,
    retry_backoff_ms: int | None = None,
    max_queue_depth: int | None = None,
    faults: str | None = None,
    replica_id: str | None = None,
    admission_predict: bool | None = None,
    admission_max_wait_ms: int | None = None,
    deadline_default_ms: int | None = None,
    deadline_priority_step_ms: int | None = None,
):
    block, chunk = resolve_lane_knobs(lane_block_size, admission_chunk)
    page_size, pool_pages, native = resolve_kv_knobs(
        kv_page_size, kv_pool_pages, kv_native
    )
    streams = resolve_stream_knobs(max_streams)
    spec_mode, spec_k_val = resolve_spec_knobs(speculation, spec_k)
    if spec_mode == "draft":
        draft_path = resolve_draft_model(draft_model)
        if draft_path is None:
            raise ValueError(
                "--speculation draft needs a draft checkpoint: pass "
                "--draft-model or set DLLAMA_DRAFT_MODEL"
            )
        # load BEFORE ApiState: the scheduler's admission rehearsal
        # prefetches draft_prefill/draft_step only if the model is there
        engine.init_draft_model(draft_path)
    r_max, r_backoff, q_depth = resolve_resilience_knobs(
        retry_max, retry_backoff_ms, max_queue_depth
    )
    predict_on, max_wait_ms = resolve_admission_knobs(
        admission_predict, admission_max_wait_ms
    )
    ddl_default, ddl_step = resolve_deadline_knobs(
        deadline_default_ms, deadline_priority_step_ms
    )
    if faults is not None:
        # arm the process-wide chaos plane for this server's lifetime
        # (--faults; the env spec DLLAMA_FAULTS armed it at import)
        set_fault_plane(faults)
    state = ApiState(
        engine,
        tokenizer,
        model_name,
        chat_template_type,
        tracer=Tracer(sink_path=trace_out) if trace_out else None,
        lane_block_size=block,
        admission_chunk=chunk,
        kv_page_size=page_size,
        kv_pool_pages=pool_pages,
        kv_native=native,
        max_streams=streams,
        slo_ttft_ms=slo_ttft_ms,
        slo_tpot_ms=slo_tpot_ms,
        series_retention=series_retention,
        speculation=spec_mode,
        spec_k=spec_k_val,
        retry_max=r_max,
        retry_backoff_ms=r_backoff,
        max_queue_depth=q_depth,
        replica_id=replica_id,
        admission_predict=predict_on,
        admission_max_wait_ms=max_wait_ms,
        deadline_default_ms=ddl_default,
        deadline_priority_step_ms=ddl_step,
    )
    if postmortem_dir:
        # a crashed scheduler loop / engine step dumps the event ring here
        state.recorder.postmortem_dir = postmortem_dir
    if timeline_out:
        # throttled Chrome-trace export per finished request, plus an
        # unconditional flush when the server is closed
        state.spans.set_sink(timeline_out)
    server = ThreadingHTTPServer((host, port), make_handler(state))
    server.state = state  # tests and callers reach the tracer/registry here
    inner_close = server.server_close

    def _close_and_flush():
        inner_close()
        if state.scheduler is not None:
            state.scheduler.stop()
        if state.watchdog is not None:
            state.watchdog.stop()
        # join the sampler so a closed server (and test churn) never
        # leaks a thread mutating the shared registry
        state.sampler.stop()
        if timeline_out:
            state.spans.flush()

    server.server_close = _close_and_flush
    if host in ("0.0.0.0", "127.0.0.1"):
        print(f"Server URL: http://localhost:{port}/v1/")
    return server  # caller runs serve_forever() (tests drive it in a thread)


def _install_drain_handler(server) -> None:
    """SIGTERM = graceful drain (the rolling-restart primitive a replica
    router relies on): stop admission, let in-flight streams finish (60 s
    cap), flush sinks, then shut the HTTP server down. Signal handlers
    only install from the main thread; anywhere else (tests driving
    main() in a worker) this is a no-op."""
    import signal

    def _on_term(signum, frame):
        server.state.begin_drain()

        def _wait_and_stop():
            server.state.drained.wait(timeout=60.0)
            server.shutdown()

        threading.Thread(  # dlint: disable=thread-hygiene — process is exiting; server.shutdown() is the terminal act
            target=_wait_and_stop, daemon=True, name="dllama-drain-stop"
        ).start()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass


def main(argv=None) -> None:
    import argparse
    import os

    import jax

    from ..cli import add_engine_args, load_engine

    parser = argparse.ArgumentParser(prog="dllama-tpu-api")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=9990)
    add_engine_args(parser)  # includes --trace-out (the JSONL sink)
    args = parser.parse_args(argv)

    from ..parallel.mesh import enable_compilation_cache, reassert_platform

    reassert_platform()
    enable_compilation_cache()

    # crash-and-retry outer loop (reference: dllama-api retries whole app
    # init every 3 s, dllama-api.cpp:616-628). Transient failures
    # (accelerator/tunnel/runtime errors) retry; permanent configuration
    # errors (missing files, invalid settings) exit, and the dead engine is
    # dropped before a reload so device memory isn't pinned twice.
    import gc

    while True:
        engine = None
        try:
            engine, tok = load_engine(args)
            ttype = (
                CHAT_TEMPLATE_NAMES[args.chat_template]
                if args.chat_template
                else ChatTemplateType.UNKNOWN
            )
            server = serve(
                engine,
                tok,
                host=args.host,
                port=args.port,
                model_name=os.path.basename(args.model),
                chat_template_type=ttype,
                trace_out=args.trace_out,
                postmortem_dir=args.postmortem_dir,
                lane_block_size=args.lane_block_size,
                admission_chunk=args.admission_chunk,
                kv_page_size=args.kv_page_size,
                kv_pool_pages=args.kv_pool_pages,
                kv_native=args.kv_native,
                max_streams=args.max_streams,
                timeline_out=args.timeline_out,
                slo_ttft_ms=args.slo_ttft_ms,
                slo_tpot_ms=args.slo_tpot_ms,
                series_retention=args.series_retention,
                speculation=args.speculation,
                spec_k=args.spec_k,
                draft_model=args.draft_model,
                retry_max=args.retry_max,
                retry_backoff_ms=args.retry_backoff_ms,
                max_queue_depth=args.max_queue_depth,
                faults=args.faults,
                replica_id=args.replica_id,
                admission_predict=args.admission_predict,
                admission_max_wait_ms=args.admission_max_wait_ms,
                deadline_default_ms=args.deadline_default_ms,
                deadline_priority_step_ms=args.deadline_priority_step_ms,
            )
            _install_drain_handler(server)
            server.serve_forever()
            return
        except KeyboardInterrupt:
            return
        except (SystemExit, FileNotFoundError, ValueError):
            raise
        except Exception as e:
            print(f"⚠️  {e}; retrying in 3s...")
            del engine
            gc.collect()
            time.sleep(3)


if __name__ == "__main__":
    main()
